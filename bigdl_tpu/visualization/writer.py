"""FileWriter: append Event protobufs to an events.out.tfevents file.

Reference: visualization/tensorboard/FileWriter.scala + EventWriter.scala
(queue + writer thread, :26-68) + RecordWriter.scala:25 (length/crc
framing).  The queue/thread is unnecessary here — scalar writes are
microseconds off the training step's critical path (the step itself runs
async on the TPU), so writes are synchronous and flushed per event.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.dataset.tfrecord import frame_record, iter_framed
from bigdl_tpu.visualization import proto


class FileWriter:
    """reference: visualization/tensorboard/FileWriter.scala."""

    def __init__(self, log_dir: str, filename_suffix: str = ""):
        os.makedirs(log_dir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}{filename_suffix}")
        self.path = os.path.join(log_dir, fname)
        self._fh = open(self.path, "ab")
        # every event file starts with a file_version event
        self._write_event(proto.encode_event(time.time(),
                                             file_version="brain.Event:2"))

    def _write_event(self, event: bytes) -> None:
        self._fh.write(frame_record(event))
        self._fh.flush()

    def add_scalar(self, tag: str, value: float, step: int,
                   wall_time: Optional[float] = None) -> None:
        v = proto.encode_value_scalar(tag, float(value))
        self._write_event(proto.encode_event(wall_time or time.time(),
                                             step=int(step), values=[v]))

    def add_histogram(self, tag: str, values: np.ndarray, step: int,
                      wall_time: Optional[float] = None) -> None:
        histo = histogram_of(np.asarray(values))
        v = proto.encode_value_histo(tag, histo)
        self._write_event(proto.encode_event(wall_time or time.time(),
                                             step=int(step), values=[v]))

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def histogram_of(values: np.ndarray) -> bytes:
    """Build a HistogramProto with TensorBoard's exponential bucket scheme
    (reference parity: the Scala writer uses the same TF bucketing)."""
    flat = values.reshape(-1).astype(np.float64)
    if flat.size == 0:
        return proto.encode_histogram(0, 0, 0, 0, 0, [], [])
    limits = _default_bucket_limits()
    counts, _ = np.histogram(flat, bins=[-np.inf] + list(limits))
    nz = np.nonzero(counts)[0]
    if nz.size:
        lo, hi = nz[0], nz[-1] + 1
        used_limits = limits[lo:hi]
        used_counts = counts[lo:hi]
    else:
        used_limits, used_counts = limits[:1], counts[:1]
    return proto.encode_histogram(
        float(flat.min()), float(flat.max()), float(flat.size),
        float(flat.sum()), float(np.square(flat).sum()),
        used_limits, used_counts)


_BUCKETS: Optional[np.ndarray] = None


def _default_bucket_limits() -> np.ndarray:
    global _BUCKETS
    if _BUCKETS is None:
        pos = []
        v = 1e-12
        while v < 1e20:
            pos.append(v)
            v *= 1.1
        neg = [-x for x in reversed(pos)]
        _BUCKETS = np.asarray(neg + [0.0] + pos + [np.finfo(np.float64).max])
    return _BUCKETS


# ---------------------------------------------------------------------------
# read-back (reference: TrainSummary.readScalar)
# ---------------------------------------------------------------------------


def read_events(path: str) -> Iterator[Dict]:
    with open(path, "rb") as f:
        for data in iter_framed(f, "event"):
            yield proto.decode_event(data)


def read_scalar(log_dir_or_file: str, tag: str) -> List[Tuple[int, float]]:
    """(step, value) series for `tag` across all event files in a dir."""
    if os.path.isdir(log_dir_or_file):
        paths = sorted(
            os.path.join(log_dir_or_file, f) for f in os.listdir(log_dir_or_file)
            if "tfevents" in f)
    else:
        paths = [log_dir_or_file]
    out: List[Tuple[int, float]] = []
    for p in paths:
        for ev in read_events(p):
            for v in ev["values"]:
                if v.get("tag") == tag and "simple_value" in v:
                    out.append((int(ev.get("step", 0)), float(v["simple_value"])))
    return out
