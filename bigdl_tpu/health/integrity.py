"""Checkpoint integrity primitives: CRC32C per array leaf.

Reference: the BigDL artifact path ships "generated protobuf + a CRC"
(survey §2.6 / PAPER.md) — every serialized module carries a checksum the
loader verifies.  Here the analogous artifact is the `ckpt_<step>/` dir:
each flattened pytree leaf gets a CRC32C (bigdl_tpu.native.crc32c — the
same native/pure-python pair the TFRecord framing uses) computed in the
AsyncCheckpointer writer thread, stored under `meta.json["integrity"]`,
and verified on restore.

This module holds the PURE primitives (checksum a flat dict, compare two
checksum maps) plus the process-wide counters the restore fallback chain
feeds — it deliberately imports nothing from `utils.checkpoint` so both
that module and `resilience.async_ckpt` can use it without a cycle.

Verification is on by default and gated by `BIGDL_TPU_CKPT_VERIFY`
(docs/training.md "Numeric health, integrity & hang detection").
Checkpoints written before this schema addition have no `integrity` block
and load without verification — old runs stay restorable.
"""

from __future__ import annotations

import os
from collections.abc import Mapping
from typing import Dict, Iterator, Optional

import numpy as np

from bigdl_tpu import native
from bigdl_tpu import obs as _obs

__all__ = [
    "CorruptCheckpointError",
    "INTEGRITY_COUNTERS",
    "leaf_crc",
    "reset_counters",
    "tree_crcs",
    "verify_enabled",
    "verify_flat",
]


class CorruptCheckpointError(IOError):
    """A checkpoint file failed its CRC32C (or could not be read at all).

    Raised by `utils.checkpoint.verify_checkpoint` / `load_checkpoint`;
    `latest_checkpoint(verify=True)` catches it per candidate and walks
    the fallback chain instead of crashing the restore."""


# Counters for the restore fallback chain (warn + METRIC per the health
# contract).  The state lives on the active `bigdl_tpu.obs` MetricsRegistry
# under the "integrity/" namespace — not in this module — so parallel
# tests stop sharing counters (swap the registry, get fresh counters).
# `INTEGRITY_COUNTERS` survives as a read-through Mapping alias.
_PREFIX = "integrity/"
_BASE_KEYS = (
    "verified",           # checkpoints that passed a full CRC verify
    "corrupt_skipped",    # candidates skipped for CRC/read failures
    "unhealthy_skipped",  # candidates skipped for a diverged verdict
)


class _CounterView(Mapping):
    """Live read-only view of the active registry's integrity/ counters."""

    def __getitem__(self, key: str) -> int:
        return int(_obs.registry().get(_PREFIX + key, 0))

    def _keys(self):
        names = set(_BASE_KEYS)
        names.update(k[len(_PREFIX):]
                     for k in _obs.registry().counters(_PREFIX))
        return names

    def __iter__(self) -> Iterator[str]:
        return iter(sorted(self._keys()))

    def __len__(self) -> int:
        return len(self._keys())

    def __repr__(self) -> str:
        return repr({k: self[k] for k in self})


INTEGRITY_COUNTERS = _CounterView()


def count(name: str, n: int = 1) -> None:
    _obs.registry().inc(_PREFIX + name, n)


def reset_counters() -> None:
    _obs.registry().reset(_PREFIX)


def verify_enabled(override: Optional[bool] = None) -> bool:
    """Restore-time CRC verification toggle: explicit override wins, else
    `BIGDL_TPU_CKPT_VERIFY` (default ON — integrity is opt-out)."""
    if override is not None:
        return bool(override)
    return os.environ.get("BIGDL_TPU_CKPT_VERIFY", "1").lower() in (
        "1", "true", "yes", "on")


def leaf_crc(arr: np.ndarray) -> int:
    """CRC32C over one leaf's raw bytes.  dtype + shape are folded in via
    a tiny header so a reinterpreted buffer (same bytes, different view)
    cannot masquerade as the original tensor."""
    a = np.ascontiguousarray(arr)
    head = f"{a.dtype.str}:{a.shape}".encode()
    crc = native.crc32c(head + a.tobytes())
    return int(crc) & 0xFFFFFFFF


def tree_crcs(flat: Dict[str, np.ndarray]) -> Dict[str, int]:
    """Checksum map for one flattened pytree ({leaf key: crc32c})."""
    return {key: leaf_crc(arr) for key, arr in flat.items()}


def verify_flat(flat: Dict[str, np.ndarray], expected: Dict[str, int],
                where: str) -> None:
    """Compare a loaded flat dict against its stored checksum map.

    Raises CorruptCheckpointError naming every failing leaf — a restore
    that dies on integrity must say WHICH tensor rotted, not just that
    something did.  Leaves present on disk but absent from the map (or
    vice versa) count as corruption: a dropped/duplicated entry is as
    fatal as a flipped bit."""
    bad = []
    for key, want in expected.items():
        if key not in flat:
            bad.append(f"{key} (missing from file)")
            continue
        got = leaf_crc(flat[key])
        if got != int(want) & 0xFFFFFFFF:
            bad.append(f"{key} (crc {got:#010x} != stored {int(want):#010x})")
    extra = sorted(set(flat) - set(expected))
    bad.extend(f"{key} (not in stored checksums)" for key in extra)
    if bad:
        raise CorruptCheckpointError(
            f"checkpoint integrity failure in {where}: " + "; ".join(bad))
