"""Numeric-divergence watchdog + hang watchdog.

Long TPU runs die two ways the process-failure machinery (PR 3:
resilience/) cannot see: NUMERIC failure — a NaN/Inf loss or gradient
that silently poisons every later step — and WEDGING, where a collective,
a feed worker or a checkpoint write blocks forever and the run makes no
progress without crashing.  This module holds the host side of both:

  * `DivergenceWatchdog` — the policy ladder consuming the per-step
    health flag the jitted train step computes on device (one extra f32
    in the telemetry ring, zero additional host syncs; see
    optimizer._build_step_uncached).  The DEVICE already refused the bad
    update (params/opt state are gated by `where(healthy, new, old)`), so
    the ladder only decides how loudly to react:

        skip_batch -> lr_backoff -> rollback_to_last_good -> abort

    Skips are counted; after `skip_limit` consecutive bad steps the lr is
    scaled down (`backoff_factor`, up to `max_backoffs` times); after
    that a `NumericDivergence` is raised — RETRYABLE: the optimizer's
    bounded-restart loop restores from the last checkpoint stamped
    healthy (meta.json watchdog verdict) and replays; the offending step
    range is MARKED so the replay skips it without re-escalating.  Once
    `max_rollbacks` rollbacks are spent, `DivergenceAbort` (non-retryable)
    ends the run.

  * `HangWatchdog` — a daemon monitor thread with per-phase deadlines
    (step dispatch, feed `__next__`, checkpoint `wait()`).  On a breach
    it dumps every Python thread's stack ONCE (the post-mortem a wedged
    run never leaves behind) and flags the stall; cooperative check
    points (`check()`, threaded into the feed/writer poll loops as
    `stall_check`) then raise `StalledStep` — retryable, so the restart
    loop recovers the run.  A phase wedged inside a C extension (a hung
    collective) cannot be interrupted from Python: there the dump is the
    deliverable and the stall raises at the next reachable check point.

Everything here is host-side bookkeeping on already-transferred scalars —
nothing in this module touches a device.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
import traceback
from typing import Dict, Iterable, List, Optional, Set, Tuple

from bigdl_tpu import obs as _obs

logger = logging.getLogger("bigdl_tpu.health")

__all__ = [
    "DivergenceAbort",
    "DivergenceWatchdog",
    "HangWatchdog",
    "NumericDivergence",
    "StalledStep",
    "WatchdogConfig",
]

VERDICT_HEALTHY = "healthy"
VERDICT_DIVERGED = "diverged"


class NumericDivergence(RuntimeError):
    """The policy ladder escalated past lr backoff: roll back to the last
    HEALTHY checkpoint.  Retryable — the optimizer's restart loop catches
    it and restores with `require_healthy=True`."""

    def __init__(self, msg: str, bad_steps: Tuple[int, ...] = ()):
        super().__init__(msg)
        self.bad_steps = tuple(bad_steps)


class DivergenceAbort(RuntimeError):
    """The rollback budget is spent (or the ladder is configured to stop
    sooner): end the run.  NOT retryable — replaying a persistently
    diverging trajectory again is wasted accelerator time."""


class StalledStep(RuntimeError):
    """A watched phase blew its deadline (wedged feed/collective/writer).
    Retryable: the restart loop restores the latest checkpoint and
    resumes, replacing the wedged workers with fresh ones."""

    def __init__(self, phase: str, elapsed_s: float, deadline_s: float):
        super().__init__(
            f"hang watchdog: phase {phase!r} stalled for {elapsed_s:.1f}s "
            f"(deadline {deadline_s:.1f}s); thread stacks were dumped to "
            f"the log")
        self.phase = phase
        self.elapsed_s = elapsed_s
        self.deadline_s = deadline_s


class WatchdogConfig:
    """Knobs for the divergence policy ladder + hang deadlines.

    Parameters
    ----------
    skip_limit : consecutive bad steps tolerated as pure on-device skips
        before the ladder escalates (each bad step is always skipped on
        device regardless — escalation only adds reactions).
    backoff_factor / max_backoffs : each escalation multiplies the lr by
        `backoff_factor` (applied as a device-side scale, no recompile),
        at most `max_backoffs` times; 0 backoffs goes straight from
        skipping to rollback.
    max_rollbacks : rollbacks to the last healthy checkpoint before
        `DivergenceAbort`; 0 aborts instead of ever rolling back.
    max_lag : cap on the driver's async depth while the watchdog is on —
        bounds how many steps can dispatch between a bad step executing
        and the drain observing its health flag.
    hang_deadlines : per-phase seconds for the hang watchdog
        ({"step_dispatch", "feed_next", "ckpt_wait"}); None disables hang
        monitoring.  Defaults are generous — they catch wedges, not slow
        steps.
    """

    DEFAULT_HANG_DEADLINES = {
        "step_dispatch": 600.0,
        "feed_next": 300.0,
        "ckpt_wait": 900.0,
    }

    def __init__(self, skip_limit: int = 3, backoff_factor: float = 0.5,
                 max_backoffs: int = 1, max_rollbacks: int = 2,
                 max_lag: int = 8,
                 hang_deadlines: Optional[Dict[str, float]] = "default",
                 hang_poll_s: float = 0.25):
        if not (0.0 < backoff_factor <= 1.0):
            raise ValueError(
                f"backoff_factor must be in (0, 1], got {backoff_factor}")
        self.skip_limit = max(0, int(skip_limit))
        self.backoff_factor = float(backoff_factor)
        self.max_backoffs = max(0, int(max_backoffs))
        self.max_rollbacks = max(0, int(max_rollbacks))
        self.max_lag = max(1, int(max_lag))
        if hang_deadlines == "default":
            hang_deadlines = dict(self.DEFAULT_HANG_DEADLINES)
        self.hang_deadlines = dict(hang_deadlines) if hang_deadlines else None
        self.hang_poll_s = float(hang_poll_s)


class DivergenceWatchdog:
    """Host-side policy ladder over the device-computed health flags.

    One instance lives on the Optimizer and SURVIVES in-process restarts:
    the marked bad-step set and the rollback budget must outlive the
    trajectory they rolled back."""

    def __init__(self, config: Optional[WatchdogConfig] = None):
        self.config = config or WatchdogConfig()
        self.lr_scale = 1.0          # applied on device; re-put on change
        self.bad_steps: Set[int] = set()
        self.marked: Set[int] = set()  # pre-rollback range: skip silently
        self.skipped = 0
        self.backoffs = 0
        self.rollbacks = 0
        self.events: List[Dict] = []   # (kind, step) ring for summaries
        self._consecutive = 0
        self._run: List[int] = []      # current unresolved bad-step run

    # ------------------------------------------------------------------

    def observe(self, step: int, healthy: bool) -> str:
        """Feed one drained step's health flag; returns the action taken
        ("ok" | "skip" | "lr_backoff") or raises NumericDivergence /
        DivergenceAbort when the ladder escalates past backoff."""
        if healthy:
            self._consecutive = 0
            self._run = []
            return "ok"
        cfg = self.config
        self.bad_steps.add(step)
        self.skipped += 1
        if step in self.marked:
            # replaying a step range a rollback already handled: the
            # device gate skips it again; no re-escalation
            self._event("skip", step, marked=True)
            return "skip"
        self._consecutive += 1
        self._run.append(step)
        if self._consecutive <= cfg.skip_limit:
            self._event("skip", step)
            return "skip"
        if self.backoffs < cfg.max_backoffs:
            self.backoffs += 1
            self._consecutive = 0
            self.lr_scale *= cfg.backoff_factor
            self._event("lr_backoff", step, lr_scale=self.lr_scale)
            logger.warning(
                "watchdog: %d consecutive non-finite step(s) through %d; "
                "lr scaled to %.3g (backoff %d/%d)", cfg.skip_limit + 1,
                step, self.lr_scale, self.backoffs, cfg.max_backoffs)
            return "lr_backoff"
        bad = tuple(self._run)
        if self.rollbacks < cfg.max_rollbacks:
            # mark BEFORE raising: the replay after restore must not
            # re-escalate on the same steps
            self.marked.update(bad)
            self._consecutive = 0
            self._run = []
            self._event("rollback", step, bad_steps=list(bad))
            raise NumericDivergence(
                f"numeric divergence: {len(bad)} non-finite step(s) "
                f"ending at {step}; rolling back to the last healthy "
                f"checkpoint", bad_steps=bad)
        self._event("abort", step, bad_steps=list(bad))
        raise DivergenceAbort(
            f"numeric divergence at step {step} with the rollback budget "
            f"spent ({self.rollbacks}/{cfg.max_rollbacks}); aborting")

    def note_rollback(self) -> None:
        """The optimizer restored a healthy checkpoint for us."""
        self.rollbacks += 1

    def adopt_marked(self, steps: Iterable[int]) -> None:
        """Merge bad steps recorded in a checkpoint's health stamp (a
        cross-process resume has no in-memory marks)."""
        self.marked.update(int(s) for s in steps)
        self.bad_steps.update(int(s) for s in steps)

    def verdict(self, ckpt_step: int) -> Dict:
        """The health stamp for a checkpoint at `ckpt_step` (stored in
        meta.json driver_state).  "diverged" while a bad-step run is
        unresolved or any bad step landed within the telemetry lag window
        of the snapshot — `latest_checkpoint(require_healthy=True)` walks
        past such checkpoints on rollback."""
        window_lo = ckpt_step - self.config.max_lag
        diverged = bool(self._run) or any(
            s > window_lo for s in self.bad_steps)
        recent = sorted(s for s in self.bad_steps if s > window_lo)
        return {
            "verdict": VERDICT_DIVERGED if diverged else VERDICT_HEALTHY,
            "bad_steps": recent,
            "lr_scale": self.lr_scale,
        }

    def _event(self, kind: str, step: int, **payload) -> None:
        self.events.append({"kind": kind, "step": int(step), **payload})
        if len(self.events) > 1024:  # bounded: long runs must not grow
            del self.events[:512]
        # policy transitions on the shared timeline: an lr backoff or a
        # rollback shows up between the step spans that caused it
        _obs.registry().inc(f"health/{kind}")
        _obs.instant(f"watchdog.{kind}", cat="health", step=int(step),
                     **{k: v for k, v in payload.items()
                        if isinstance(v, (int, float, str, bool))})
        if kind in ("rollback", "abort"):
            # the run is about to unwind — snapshot the black box NOW,
            # while the offending steps are still in the ring
            _obs.flight_notify(f"watchdog.{kind}", step=int(step))


class _Phase:
    __slots__ = ("name", "t0")

    def __init__(self, name: str, t0: float):
        self.name = name
        self.t0 = t0


class HangWatchdog:
    """Per-phase deadline monitor ("HealthWatchdog" daemon thread).

    The driver brackets each watched section with `phase(name)`; the
    monitor wakes every `poll_s`, and a section older than its deadline
    gets every Python thread's stack dumped to the log (once per breach)
    and the stall flagged.  `check()` — called from the driver loop and
    threaded into the DeviceFeed / AsyncCheckpointer poll loops as
    `stall_check` — raises the pending `StalledStep`."""

    def __init__(self, deadlines: Dict[str, float], poll_s: float = 0.25,
                 name: str = "HealthWatchdog"):
        self.deadlines = {k: float(v) for k, v in deadlines.items()}
        self.poll_s = float(poll_s)
        self._name = name
        self._lock = threading.Lock()
        self._phase: Optional[_Phase] = None
        self._stall: Optional[StalledStep] = None
        self._dumped_for: Optional[Tuple[str, float]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stalls: List[Tuple[str, float]] = []

    # ------------------------------------------------------------------

    def start(self) -> "HangWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._run,
                                            name=self._name, daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            if t.is_alive():  # pragma: no cover - defensive
                raise RuntimeError(f"{self._name} monitor did not stop")
            self._thread = None

    def __enter__(self) -> "HangWatchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------------

    def phase(self, name: str):
        """Context manager bracketing one watched section."""
        return _PhaseCtx(self, name)

    def check(self) -> None:
        """Raise the pending StalledStep, once.  Cheap enough for poll
        loops: one lock-free read on the happy path."""
        # double-checked: the lock-free fast-path read may be stale for
        # one poll tick; the locked re-read below decides for real
        stall = self._stall  # tpu-lint: disable=unguarded-state
        if stall is not None:
            with self._lock:
                stall, self._stall = self._stall, None
            if stall is not None:
                raise stall

    def clear(self) -> None:
        """Drop any pending stall (called when the restart loop resumes —
        the wedged workers are gone; a stale flag must not re-kill the
        fresh attempt)."""
        with self._lock:
            self._stall = None
            self._phase = None
            self._dumped_for = None

    # ------------------------------------------------------------------

    def _enter_phase(self, name: str) -> None:
        with self._lock:
            self._phase = _Phase(name, time.monotonic())

    def _exit_phase(self) -> None:
        with self._lock:
            self._phase = None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            with self._lock:
                ph = self._phase
            if ph is None:
                continue
            deadline = self.deadlines.get(ph.name)
            if deadline is None:
                continue
            elapsed = time.monotonic() - ph.t0
            if elapsed <= deadline:
                continue
            key = (ph.name, ph.t0)
            with self._lock:
                first = self._dumped_for != key
                if first:
                    self._dumped_for = key
                    self._stall = StalledStep(ph.name, elapsed, deadline)
                    self.stalls.append((ph.name, elapsed))
            if first:
                _obs.registry().inc("health/stalls")
                _obs.instant("watchdog.stall", cat="health", phase=ph.name,
                             elapsed_s=round(elapsed, 3),
                             deadline_s=deadline)
                _obs.flight_notify("watchdog.stall", phase=ph.name,
                                   elapsed_s=round(elapsed, 3))
                logger.error(
                    "hang watchdog: phase %r exceeded its %.1fs deadline "
                    "(%.1fs elapsed); dumping all thread stacks\n%s",
                    ph.name, deadline, elapsed, dump_thread_stacks())


def dump_thread_stacks() -> str:
    """Every Python thread's current stack, formatted — the post-mortem a
    wedged run never writes on its own."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sys._current_frames().items():
        parts.append(f"--- thread {names.get(ident, '?')} ({ident}) ---\n"
                     + "".join(traceback.format_stack(frame)))
    return "\n".join(parts)


class _PhaseCtx:
    __slots__ = ("_hw", "_name")

    def __init__(self, hw: HangWatchdog, name: str):
        self._hw = hw
        self._name = name

    def __enter__(self):
        self._hw._enter_phase(self._name)
        return self

    def __exit__(self, *exc) -> None:
        self._hw._exit_phase()
