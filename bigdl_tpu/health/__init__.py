"""bigdl_tpu.health — numeric-divergence watchdog, checkpoint integrity
CRCs, and hang detection.

Three failure families the process-level resilience layer (PR 3) cannot
see, and what this package does about each:

  numeric divergence  DivergenceWatchdog + a device-side finite check on
                      loss and grad global-norm folded into the jitted
                      train step (one extra scalar in the telemetry
                      ring, zero added host syncs); policy ladder
                      skip_batch -> lr_backoff -> rollback -> abort.
  bit rot             CRC32C per array leaf, computed in the async
                      checkpoint writer, stamped into meta.json and
                      verified on restore; `latest_checkpoint` grows a
                      fallback chain that skips corrupt or
                      diverged-stamped checkpoints.
  wedged runs         HangWatchdog monitor thread with per-phase
                      deadlines; dumps all thread stacks and raises the
                      retryable `StalledStep`.

See docs/training.md "Numeric health, integrity & hang detection".
"""

from bigdl_tpu.health.integrity import (
    CorruptCheckpointError,
    INTEGRITY_COUNTERS,
    leaf_crc,
    reset_counters,
    tree_crcs,
    verify_enabled,
    verify_flat,
)
from bigdl_tpu.health.watchdog import (
    DivergenceAbort,
    DivergenceWatchdog,
    HangWatchdog,
    NumericDivergence,
    StalledStep,
    WatchdogConfig,
    dump_thread_stacks,
)

__all__ = [
    "CorruptCheckpointError",
    "DivergenceAbort",
    "DivergenceWatchdog",
    "HangWatchdog",
    "INTEGRITY_COUNTERS",
    "NumericDivergence",
    "StalledStep",
    "WatchdogConfig",
    "dump_thread_stacks",
    "leaf_crc",
    "reset_counters",
    "tree_crcs",
    "verify_enabled",
    "verify_flat",
]
