"""GenerationEngine: prefill/decode serving with continuous batching.

The serving stack (bigdl_tpu.serving) turned fixed-shape forwards into a
production path: bucketed executables, versioned hot-swap, AOT warmup,
admission control.  This module does the same for AUTOREGRESSIVE
generation, where the reference has nothing at all (its
PredictionService.scala runs one stateless forward per request — "decode"
would be a full prompt re-forward per token).

Shape discipline (the TPU cost model, same as MicroBatcher's buckets):

  * Each configured length bucket C owns one DECODE LANE: a ring-buffer
    `KVCache` of (slots, C) plus a PINNED executable set —
    `generation/prefill/bucket=C` (prompt padded to C, writes one slot,
    samples the first token) and `generation/decode/bucket=C` (length-1
    query for ALL slots at once, samples the next token per slot).
    Chunked prefill (BIGDL_TPU_PREFILL_CHUNK) REPLACES prefill with
    `prefill_chunk` (fixed chunk width, traced progress — still 2 per
    bucket); speculative decoding (BIGDL_TPU_SPEC_DECODE + a draft
    model) adds `draft_prefill`-or-`draft_chunk`, `draft_step` and
    `verify` (5 per bucket).  The set is documented in
    `compile_count()`, pinned at warmup, and never grows after — a
    64-request burst compiles nothing past warmup
    (tests/test_generation.py asserts it, with CompileMonitor's
    steady-state recompile alarm as the witness).
  * Continuous batching: the engine thread interleaves admission with
    in-flight decode — a new request claims a free slot, prefills, and
    joins the NEXT decode step of requests already mid-generation; EOS /
    max-token / non-finite retirement frees the slot for the queue.  Slot
    claim/free are traced indices inside the compiled step, never new
    shapes.
  * Sampling (greedy / temperature / top-k, generation/sampling.py) runs
    on device inside the decode executable; the per-step host traffic is
    one (slots,) token read-back.

Serving integration: the engine reuses `ModelRegistry` (atomic hot-swap;
its warmup chain AOT-warms prefill+decode per bucket BEFORE a version
activates — through `compilecache.load_or_compile` when the persistent
store is on), the serving admission-control idiom (bounded queue,
`Rejected`/`ServingClosed`), and the runtime's `reject_nonfinite` health
policy.  `ServingRuntime.enable_generation()` attaches an engine to a
live runtime so one registry swap warms BOTH the batch forwards and the
generation executables.  A swap mid-generation applies to subsequent
tokens of in-flight requests (their cached K/V is kept); call `drain()`
first when strict single-version generations are required.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import zlib
from collections import deque
from contextlib import nullcontext
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import obs as _obs
from bigdl_tpu.analysis.runtime import strict_transfers, strict_transfers_enabled
from bigdl_tpu.generation.kvcache import KVCache, insert
from bigdl_tpu.generation.kvcache import slot_view as _ring_slot_view
from bigdl_tpu.generation.pagedkv import (DEFAULT_BLOCK_SIZE, BlockPool,
                                          PagedKVCache, blocks_for)
from bigdl_tpu.generation.pagedkv import slot_view as _paged_slot_view
from bigdl_tpu.generation.prefixcache import PrefixStore, world_key
from bigdl_tpu.generation.sampling import (request_key, request_keys,
                                           sample_tokens,
                                           sample_tokens_per_slot,
                                           spec_accept)
from bigdl_tpu.serving.batcher import Rejected, ServingClosed, _Future
from bigdl_tpu.serving.metrics import GenerationMetrics
from bigdl_tpu.serving.registry import ModelRegistry, ModelVersion

_NULL = nullcontext()
_log = logging.getLogger("bigdl_tpu.generation")

_KV_DTYPES = {"int8": jnp.int8, "bf16": jnp.bfloat16,
              "bfloat16": jnp.bfloat16, "fp32": jnp.float32,
              "float32": jnp.float32}

# What ships ON by default per backend, decided by the interleaved A/B in
# benchmarks/bench_generation.py --decode-quick (numbers committed to
# benchmarks/results/spec_quick.json) — same discipline as
# ops/decode_attention._MEASURED_DEFAULTS.  Chunked prefill wins its
# TTFT-under-long-prompt target on cpu but stays OPT-IN (it reshapes the
# admission latency profile, a policy change deployments should choose);
# spec decode LOSES ms/token on the cpu quick tier (the draft's k extra
# dispatches outweigh accepted tokens against a tiny target) so it ships
# off everywhere until a tpu measurement says otherwise.  Flip only with
# fresh numbers in spec_quick.json.
_MEASURED_CHUNK_DEFAULTS = {"cpu": 0, "tpu": 0}
_MEASURED_SPEC_DEFAULTS = {"cpu": False, "tpu": False}
# Prefix caching (benchmarks/bench_generation.py --prefix-quick, numbers
# in benchmarks/results/prefix_quick.json): shared-on wins its bars on
# cpu — fewer cold prefill tokens and chunks, lower p50 TTFT, bitwise
# parity — but it REQUIRES chunked prefill, which ships opt-in as an
# admission-policy change, so the default follows its prerequisite: off
# until a deployment opts into chunking and flips
# BIGDL_TPU_PREFIX_CACHE alongside it.
_MEASURED_PREFIX_DEFAULTS = {"cpu": False, "tpu": False}

_SIZE_SUFFIX = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30}


def _parse_bytes(text: str) -> int:
    t = text.strip().lower()
    mult = _SIZE_SUFFIX.get(t[-1:], 1)
    return int(float(t[:-1] if mult != 1 else t) * mult)


class GenerationConfig:
    """Knobs for the generation engine (docs/serving.md).

    `paged=None` / `cache_dtype=None` defer to the `BIGDL_TPU_PAGED_KV` /
    `BIGDL_TPU_KV_DTYPE` environment variables (docs/serving.md "Paged KV
    & quantized cache"), so deployments flip the allocator and KV dtype
    without touching call sites; the in-code default stays the ring
    fp32 baseline.

    `prefill_chunk=None` / `spec_decode=None` likewise defer to
    `BIGDL_TPU_PREFILL_CHUNK` (tokens per prefill chunk; 0 disables) and
    `BIGDL_TPU_SPEC_DECODE` (on/off, or an integer which both enables
    speculative decoding and sets `spec_k`), falling back to the
    per-backend measured defaults above (docs/serving.md "Chunked
    prefill & speculative decoding").

    `prefix_cache=None` defers to `BIGDL_TPU_PREFIX_CACHE` (on/off, or
    a byte budget like `64M` which also caps the store) with
    `BIGDL_TPU_PREFIX_CACHE_MAX_BLOCKS` as a block-count cap; requires
    paged KV + chunked prefill (docs/serving.md "Prefix caching")."""

    def __init__(self, buckets: Sequence[int] = (64, 256), slots: int = 4,
                 capacity: int = 128, max_new_tokens: int = 64,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_id: Optional[int] = None, cache_dtype=None,
                 seed: int = 0, reject_nonfinite: bool = False,
                 strict_transfers: Optional[bool] = None,
                 paged: Optional[bool] = None,
                 kv_block_size: int = DEFAULT_BLOCK_SIZE,
                 kv_pool_blocks: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 spec_decode: Optional[bool] = None, spec_k: int = 4,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_bytes: Optional[int] = None,
                 prefix_cache_max_blocks: Optional[int] = None,
                 progress_meta: Optional[bool] = None):
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 2:
            raise ValueError(f"length buckets must be >= 2, got {buckets}")
        self.slots = int(slots)          # concurrent requests per bucket lane
        self.capacity = int(capacity)    # admission queue bound
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)          # static: part of the executables
        self.eos_id = eos_id
        if cache_dtype is None:
            env = os.environ.get("BIGDL_TPU_KV_DTYPE", "").strip().lower()
            if env and env not in _KV_DTYPES:
                raise ValueError(
                    f"BIGDL_TPU_KV_DTYPE={env!r}: expected one of "
                    f"{sorted(_KV_DTYPES)}")
            cache_dtype = _KV_DTYPES.get(env)
        self.cache_dtype = cache_dtype or jnp.float32
        self.seed = int(seed)
        self.reject_nonfinite = bool(reject_nonfinite)
        self.strict_transfers = strict_transfers
        if paged is None:
            paged = os.environ.get("BIGDL_TPU_PAGED_KV", "").strip().lower() \
                in ("1", "true", "on", "yes")
        self.paged = bool(paged)
        self.kv_block_size = int(kv_block_size)
        self.kv_pool_blocks = kv_pool_blocks
        if self.paged:
            bad = [b for b in self.buckets if b % self.kv_block_size]
            if bad:
                raise ValueError(
                    f"paged KV needs every bucket divisible by "
                    f"kv_block_size={self.kv_block_size}, got {bad}")
        if prefill_chunk is None:
            env = os.environ.get("BIGDL_TPU_PREFILL_CHUNK", "").strip()
            if env:
                try:
                    prefill_chunk = int(env)
                except ValueError:
                    raise ValueError(
                        f"BIGDL_TPU_PREFILL_CHUNK={env!r}: expected an "
                        "integer chunk size in tokens (0 disables)")
            else:
                prefill_chunk = _MEASURED_CHUNK_DEFAULTS.get(
                    jax.default_backend(), 0)
        self.prefill_chunk = max(0, int(prefill_chunk))
        self.spec_k = int(spec_k)
        if spec_decode is None:
            env = os.environ.get("BIGDL_TPU_SPEC_DECODE", "").strip().lower()
            if env in ("1", "on", "true", "yes"):
                spec_decode = True
            elif env in ("0", "off", "false", "no"):
                spec_decode = False
            elif env:
                try:
                    self.spec_k = int(env)
                except ValueError:
                    raise ValueError(
                        f"BIGDL_TPU_SPEC_DECODE={env!r}: expected on/off "
                        "or an integer draft length k")
                spec_decode = True
            else:
                spec_decode = _MEASURED_SPEC_DEFAULTS.get(
                    jax.default_backend(), False)
        self.prefix_cache_bytes = prefix_cache_bytes
        if prefix_cache is None:
            env = os.environ.get("BIGDL_TPU_PREFIX_CACHE", "").strip().lower()
            if env in ("1", "on", "true", "yes"):
                prefix_cache = True
            elif env in ("0", "off", "false", "no"):
                prefix_cache = False
            elif env:
                try:
                    self.prefix_cache_bytes = _parse_bytes(env)
                except ValueError:
                    raise ValueError(
                        f"BIGDL_TPU_PREFIX_CACHE={env!r}: expected on/off "
                        "or a byte budget like 64M / 2G")
                prefix_cache = True
            else:
                prefix_cache = _MEASURED_PREFIX_DEFAULTS.get(
                    jax.default_backend(), False)
        self.prefix_cache = bool(prefix_cache)
        if prefix_cache_max_blocks is None:
            env = os.environ.get(
                "BIGDL_TPU_PREFIX_CACHE_MAX_BLOCKS", "").strip()
            if env:
                try:
                    prefix_cache_max_blocks = int(env)
                except ValueError:
                    raise ValueError(
                        f"BIGDL_TPU_PREFIX_CACHE_MAX_BLOCKS={env!r}: "
                        "expected an integer block count")
        self.prefix_cache_max_blocks = prefix_cache_max_blocks
        if self.prefix_cache:
            # the store shares immutable POOL blocks and skips CHUNKS —
            # both prerequisites are hard, so misconfiguration fails
            # loudly instead of silently serving cold
            if not self.paged:
                raise ValueError(
                    "prefix_cache requires the paged KV allocator "
                    "(paged=True / BIGDL_TPU_PAGED_KV=1): only pool "
                    "blocks can be shared across slots")
            if self.prefill_chunk <= 0:
                raise ValueError(
                    "prefix_cache requires chunked prefill "
                    "(prefill_chunk / BIGDL_TPU_PREFILL_CHUNK > 0): hits "
                    "are realized by skipping whole prefill chunks")
            if self.prefill_chunk % self.kv_block_size:
                raise ValueError(
                    f"prefix_cache needs prefill_chunk "
                    f"({self.prefill_chunk}) divisible by kv_block_size "
                    f"({self.kv_block_size}) so chunk boundaries land on "
                    "block boundaries")
        if progress_meta is None:
            # emitted-token progress snapshots in future.meta (the fleet
            # failover resume source) ship ON: host-side dict writes per
            # settle-safe boundary, measured <=1% on the bench_fleet
            # --failover-quick interleaved A/B.  BIGDL_TPU_GEN_PROGRESS=0
            # turns them off (and fleet recovery degrades to a cold
            # full-recompute redispatch).
            progress_meta = os.environ.get(
                "BIGDL_TPU_GEN_PROGRESS", "1").strip().lower() \
                not in ("0", "off", "false", "no")
        self.progress_meta = bool(progress_meta)
        self.spec_decode = bool(spec_decode)
        if self.spec_decode:
            if self.spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {self.spec_k}")
            if self.spec_k + 1 >= self.buckets[-1]:
                raise ValueError(
                    f"spec_k={self.spec_k} needs k+1 verify positions but "
                    f"the largest bucket is {self.buckets[-1]}; no lane "
                    "could ever run a speculative round")

    def chunk_for(self, bucket: int) -> int:
        """Prefill-chunk executable width for one bucket (a configured
        chunk wider than the bucket clamps to it)."""
        return min(self.prefill_chunk, int(bucket)) if self.prefill_chunk \
            else 0


class GenerationResult(NamedTuple):
    """Generated token ids (prompt excluded) + per-request meta
    (cid, version, bucket, finish_reason, ttft_ms, ms_per_token, ...)."""

    tokens: np.ndarray
    meta: Dict[str, Any]


class _SlotState:
    __slots__ = ("req", "tokens", "generated", "t_first", "step_ms_sum")

    def __init__(self, req):
        self.req = req
        # generated ids, streamed back per step.  A resumed request's
        # slot starts with the victim's emitted tokens already in the
        # list (they sit at the tail of the effective prompt), so the
        # settled result always carries the FULL emission — exactly-once
        # delivery is structural: one single-assignment future, one
        # complete list, set once.
        self.tokens: List[int] = [
            int(t) for t in req.prompt[req.prompt.size - req.resume_n:]
        ] if req.resume_n else []
        self.generated = req.resume_n
        self.t_first: Optional[float] = None
        self.step_ms_sum = 0.0


class _PrefillState:
    """Host bookkeeping for one slot mid chunked-prefill: which chunk of
    the schedule folds next, accumulated fold time, and whether another
    long prefill was already in flight at admission (feeds the
    TTFT-under-long-prompt histogram)."""

    __slots__ = ("req", "sched", "next_i", "prefill_ms", "contended",
                 "long", "map_shared")

    def __init__(self, req, sched, contended):
        self.req = req
        self.sched = sched  # [(progress, n_valid), ...]
        self.next_i = 0
        self.prefill_ms = 0.0
        self.contended = contended
        # spans >1 scheduler pass (counted in _long_inflight); a prefix
        # hit can resume the schedule at its last chunk, making a long
        # prompt short — admission overrides after seeding next_i
        self.long = len(sched) > 1
        # shared blocks to map into the device table at the FIRST fold
        # (not at admission): the batched decode step writes K/V for
        # every slot at its DEVICE length, and a just-admitted slot's
        # device length is stale until its first fold sets it — mapping
        # early would let that garbage write land inside a shared block
        self.map_shared = 0


def _chunk_schedule(n: int, ch: int) -> "List[Tuple[int, int]]":
    """Chunk offsets for an n-token prompt at executable width `ch`: full
    chunks, then a RIGHT-ALIGNED remainder (the final chunk re-folds the
    last `ch` tokens, ending exactly at n).  The overlap rewrite is
    bitwise idempotent — K/V at a position are a deterministic function
    of token, position and prior context — so right alignment avoids a
    padded tail chunk clobbering live ring columns past n."""
    if n <= ch:
        return [(0, n)]
    sched = [(i * ch, ch) for i in range(n // ch)]
    if n % ch:
        sched.append((n - ch, ch))
    return sched


class _GenRequest:
    __slots__ = ("prompt", "max_new", "temperature", "eos_id", "future",
                 "t_submit", "cid", "uid", "rng_uid", "resume_n",
                 "hit_tokens")

    def __init__(self, prompt, max_new, temperature, eos_id, uid,
                 cid=None, rng_uid=None, resume_n=0):
        # `prompt` is the EFFECTIVE prompt: original prompt + any tokens
        # resumed from a dead replica's progress snapshot (resume_n of
        # them, at the tail).  All admission machinery — bucket pick,
        # chunk schedule, prefix lookup/publish — operates on it
        # unchanged; only sampling indices and result meta distinguish
        # resumed tokens from prompt tokens.
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.eos_id = eos_id
        self.future = _Future()
        self.t_submit = time.perf_counter()
        # fleet-routed prompts carry the router's cid so one id spans
        # replicas; direct submits mint a fresh one
        self.cid = cid if cid is not None else _obs.next_cid()
        self.uid = uid  # per-engine request index (admission ordering)
        # the sampling stream id: derived from the cid by default so a
        # request redispatched across replicas (same cid) keeps its
        # stream — sampled output is bitwise resumable given the same
        # engine seed.  Distinct requests get distinct cids, hence
        # distinct streams.
        self.rng_uid = int(rng_uid) if rng_uid is not None \
            else zlib.crc32(self.cid.encode()) & 0x7FFFFFFF
        self.resume_n = int(resume_n)
        self.hit_tokens = 0  # prefix-store tokens mapped at admission


class _Lane:
    """One length bucket: its KV residency + host-side bookkeeping.

    Ring mode owns a private `(slots, C)` `KVCache`; paged mode owns no
    K/V at all — just this lane's (slots, max_blocks) block table and
    lengths over the engine-wide `BlockPool`, composed into a
    `PagedKVCache` view per step.  Table edits happen on the host mirror
    (`table_np`) and upload lazily (`_table_dirty`) so steady-state
    decode with no claims moves zero table bytes."""

    def __init__(self, model, bucket: int, slots: int, dtype,
                 pool: Optional[BlockPool] = None, draft_model=None):
        self.bucket = bucket
        self.pool = pool
        if pool is None:
            # committed placement: pjit caches key on sharding commitment,
            # so every input (cache, tokens, scalars) must be device_put
            # like the warmup args or the first real step silently
            # re-traces
            self.cache: KVCache = jax.device_put(
                model.init_cache(slots, bucket, dtype))
        else:
            nbb = bucket // pool.block_size
            self.table_np = np.zeros((slots, nbb), np.int32)
            self._table_dev = jax.device_put(jnp.zeros((slots, nbb),
                                                       jnp.int32))
            self._table_dirty = False
            self.lengths_dev = jax.device_put(jnp.zeros((slots,), jnp.int32))
            self.claimed: List[List[int]] = [[] for _ in range(slots)]
            self.reserved: List[int] = [0] * slots
        # host position mirror (ring AND paged): total tokens written per
        # slot — the spec-round base, chunk progress, and claim cursor
        self.lengths_np = np.zeros((slots,), np.int64)
        # the draft lane is always a private ring (the draft is small);
        # its lengths are overridden per draft step from lengths_np
        self.dcache: Optional[KVCache] = None
        if draft_model is not None:
            self.dcache = jax.device_put(
                draft_model.init_cache(slots, bucket, dtype))
        # slots mid chunked-prefill, FIFO by admission order
        self.prefilling: Dict[int, _PrefillState] = {}
        # latched True when a plain decode step advances a slot the draft
        # cache didn't see; such a slot stays non-speculative until retire
        self.spec_stale = np.zeros((slots,), bool)
        self.slots: List[Optional[_SlotState]] = [None] * slots
        self.free: List[int] = list(range(slots))
        # host mirrors, device_put explicitly each step (tiny, guard-safe)
        self.last_np = np.zeros((slots, 1), np.int32)
        self.temps_np = np.zeros((slots,), np.float32)
        self.active_np = np.zeros((slots,), bool)
        # per-slot sampling stream: rng_uid + next generated index (the
        # decode executable folds both per row, so sampled sequences are
        # slot- and interleaving-independent — resumable across replicas)
        self.uids_np = np.zeros((slots,), np.int32)
        self.gens_np = np.zeros((slots,), np.int32)

    @property
    def n_active(self) -> int:
        return int(self.active_np.sum())

    def table_dev(self) -> jax.Array:
        if self._table_dirty:
            self._table_dev = jax.device_put(jnp.asarray(self.table_np))
            self._table_dirty = False
        return self._table_dev


def _tree_sig(tree: Any) -> tuple:
    return tuple((tuple(np.shape(l)), str(getattr(l, "dtype", type(l))))
                 for l in jax.tree_util.tree_leaves(tree))


def _vocab_size(model) -> Optional[int]:
    """vocab_size through delegating wrappers (WeightOnlyInt8 exposes the
    cache protocol by delegation but not the attribute — walk `.inner`)."""
    seen = 0
    while model is not None and seen < 8:
        v = getattr(model, "vocab_size", None)
        if v is not None:
            return int(v)
        model = getattr(model, "inner", None)
        seen += 1
    return None


class GenerationEngine:
    """Continuous-batching prefill/decode engine over a versioned registry.

    `model` must expose the cache-aware protocol (`init_cache`,
    `apply_cached`) — TransformerLM natively, and quantized wrappers like
    `WeightOnlyInt8` by delegation, so int8 weight-only decode via
    `quantize(mode='auto')` drops in unchanged.
    """

    def __init__(self, model, params: Any = None, state: Any = None, *,
                 config: Optional[GenerationConfig] = None,
                 registry: Optional[ModelRegistry] = None,
                 version: str = "v0", summary=None,
                 draft_model=None, draft_params: Any = None,
                 draft_version: str = "draft", **config_kw):
        if not (hasattr(model, "apply_cached") and hasattr(model, "init_cache")):
            raise TypeError(
                f"{type(model).__name__} has no KV-cache forward "
                "(init_cache/apply_cached); generation needs a cache-aware "
                "model (models/transformer.TransformerLM or a wrapper)")
        self.model = model
        self.config = config or GenerationConfig(**config_kw)
        self.metrics = GenerationMetrics()
        self.summary = summary
        self._export_step = 0
        self._uid_counter = 0
        self._steps = 0
        self._chunk_folds = 0  # cumulative prefill-chunk executions
        self._step_hook = None  # chaos: fn(kind, count), see set_step_hook
        self._strict = strict_transfers_enabled(self.config.strict_transfers)
        self._chunk_on = self.config.prefill_chunk > 0
        if self.config.spec_decode and draft_model is None:
            _log.warning(
                "spec_decode is enabled but no draft model was supplied; "
                "speculative decoding stays off (pass draft_model= / "
                "draft_params= or enable_generation(draft_model=...))")
        self._spec_on = bool(self.config.spec_decode
                             and draft_model is not None)
        self._draft_model = draft_model if self._spec_on else None
        self._vocab: Optional[int] = None
        if self._spec_on:
            if not (hasattr(draft_model, "apply_cached")
                    and hasattr(draft_model, "init_cache")):
                raise TypeError(
                    f"draft {type(draft_model).__name__} has no KV-cache "
                    "forward (init_cache/apply_cached)")
            tv, dv = _vocab_size(model), _vocab_size(draft_model)
            if tv is not None and dv is not None and tv != dv:
                raise ValueError(
                    f"draft vocab_size {dv} != target vocab_size {tv}: the "
                    "verify pass compares their distributions row-for-row")
            self._vocab = tv if tv is not None else dv
            if self._vocab is None:
                raise ValueError(
                    "cannot determine vocab_size from target or draft "
                    "model; speculative decoding needs it for the draft "
                    "log-prob buffer")
        self._long_inflight = 0  # chunked prefills spanning >1 chunk
        self._pool: Optional[BlockPool] = None
        if self.config.paged:
            blk = self.config.kv_block_size
            # probe each bucket through init_cache so paged lanes get the
            # same rope/max_len validation as ring lanes, and read the
            # model's cache dims off the last probe (works through
            # delegating wrappers like WeightOnlyInt8)
            for b in self.config.buckets:
                probe = model.init_cache(1, b, self.config.cache_dtype)
            n_layer, _, _, n_head, head_dim = probe.k.shape
            n_blocks = self.config.kv_pool_blocks
            if n_blocks is None:
                # worst case every slot of every lane fully resident,
                # +1 for the trash block — sized for zero admission
                # backpressure; shrink kv_pool_blocks to oversubscribe
                n_blocks = 1 + sum(
                    blocks_for(b, blk) * self.config.slots
                    for b in self.config.buckets)
            self._pool = BlockPool(n_layer, int(n_blocks), blk, n_head,
                                   head_dim, self.config.cache_dtype)
        self._prefix: Optional[PrefixStore] = None
        self._prefix_version: Optional[str] = None
        if self.config.prefix_cache:
            # config validation guarantees paged + chunked here; the
            # reclaim hook lets a claim shortfall evict idle store
            # entries instead of failing
            self._prefix = PrefixStore(
                self._pool, max_bytes=self.config.prefix_cache_bytes,
                max_blocks=self.config.prefix_cache_max_blocks)
            self._pool.set_reclaim(self._prefix.reclaim)
        self._lanes: Dict[int, _Lane] = {
            b: _Lane(model, b, self.config.slots, self.config.cache_dtype,
                     pool=self._pool, draft_model=self._draft_model)
            for b in self.config.buckets}
        self._warned_wrap = False
        self._update_kv_gauges()
        (self._prefill, self._chunk, self._decode, self._dprefill,
         self._dchunk, self._dstep, self._verify) = self._build_fns()
        if self._spec_on:
            # constant round inputs, allocated once: the zero draft
            # buffers every round starts from, and the k+1 step indices
            # (device-resident so the draft loop transfers nothing)
            k = self.config.spec_k
            self._toks0 = jax.device_put(
                jnp.zeros((self.config.slots, k), jnp.int32))
            self._q0 = jax.device_put(
                jnp.zeros((self.config.slots, k, self._vocab), jnp.float32))
            self._i_dev = jax.device_put(
                tuple(np.int32(i) for i in range(k + 1)))
        # warmed executables: (phase, bucket) -> callable (AOT-loaded when
        # the compile cache is on, the pjit fn otherwise); psig pins the
        # param tree they were warmed for, exactly like ServingRuntime.
        # Draft-phase entries trace against DRAFT params and are pinned by
        # dsig instead, surviving target swaps untouched.
        self._warmed: Dict[Tuple[str, int], Any] = {}
        self._warmed_psig: Optional[tuple] = None
        self._warmed_dsig: Optional[tuple] = None

        self._pending: "deque[_GenRequest]" = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._abort = False
        self._drained = threading.Event()

        if registry is None:
            self.registry = ModelRegistry(warmup=self._warmup)
            if self._spec_on:
                # install the draft BEFORE the first register: the warmup
                # chain then warms draft+verify executables together with
                # prefill/decode, and every future target hot-swap re-warms
                # the verify lane (it traces against target params) before
                # activation — never a cold compile mid-traffic
                self.registry.set_draft(draft_version, draft_params)
            self.registry.register(version, params,
                                   state if state is not None else {})
        else:
            # layered behind a live ServingRuntime: warm the ACTIVE version
            # now, then join the registry's warmup chain so every future
            # hot-swap warms generation executables before activation too
            self.registry = registry
            if self._spec_on:
                registry.set_draft(draft_version, draft_params)
            snap = registry.active()
            self._warmup(snap.params, snap.state)
            registry.add_warmup(self._warmup)
        mon = _obs.compile_monitor()
        if mon is not None:
            # warmup compiled every (bucket x phase) above: any compile
            # under generation/ from here on is a steady-state alarm
            mon.mark_steady("generation/")
        self._thread = threading.Thread(target=self._loop,
                                        name="generation-engine", daemon=True)
        self._thread.start()

    # -- compiled step functions ------------------------------------------

    def _build_fns(self):
        m = self.model
        dm = self._draft_model
        top_k = self.config.top_k
        paged = self.config.paged

        def ring_prefill_for(model):
            def prefill_ring(params, cache, tokens, n, slot, temp, seed,
                             uid, gen0):
                # fresh single-slot cache at the lane's capacity; fold the
                # prompt in, sample the first GENERATED token (index gen0
                # of the request's rng stream: 0 normally, the resumed
                # count after a failover re-admission) from the last REAL
                # row, then write the slot — all one executable per
                # bucket, so slot claim costs no extra compile
                L, _, C, H, D = cache.k.shape
                quant = cache.k_scale is not None
                fresh = KVCache(
                    k=jnp.zeros((L, 1, C, H, D), cache.k.dtype),
                    v=jnp.zeros((L, 1, C, H, D), cache.v.dtype),
                    lengths=jnp.zeros((1,), jnp.int32),
                    k_scale=jnp.zeros((L, 1, C, H), jnp.float32)
                    if quant else None,
                    v_scale=jnp.zeros((L, 1, C, H), jnp.float32)
                    if quant else None)
                logp, fresh = model.apply_cached(params, tokens, fresh)
                last = jax.lax.dynamic_slice_in_dim(logp, n - 1, 1,
                                                    axis=1)[:, 0]
                key = request_key(seed, uid, gen0)
                tok = sample_tokens(last, key, temp, top_k=top_k)
                ok = jnp.isfinite(last).all()
                return tok, insert(cache, slot, fresh, n), ok
            return prefill_ring

        def prefill_paged(params, cache, tokens, n, slot, temp, seed, uid,
                          gen0):
            # no fresh buffer + insert here: the slot's table row is
            # sliced out and the prompt's K/V stream STRAIGHT into the
            # claimed pool blocks (pad positions past the claimed prefix
            # hit the trash block).  Same signature, so the warmup /
            # compile-count machinery is allocator-agnostic.
            row = jax.lax.dynamic_slice_in_dim(cache.block_tables, slot, 1, 0)
            sub = PagedKVCache(k=cache.k, v=cache.v, block_tables=row,
                               lengths=jnp.zeros((1,), jnp.int32),
                               k_scale=cache.k_scale, v_scale=cache.v_scale)
            logp, sub = m.apply_cached(params, tokens, sub)
            last = jax.lax.dynamic_slice_in_dim(logp, n - 1, 1, axis=1)[:, 0]
            key = request_key(seed, uid, gen0)
            tok = sample_tokens(last, key, temp, top_k=top_k)
            ok = jnp.isfinite(last).all()
            new = cache._replace(
                k=sub.k, v=sub.v, k_scale=sub.k_scale, v_scale=sub.v_scale,
                lengths=cache.lengths.at[slot].set(jnp.asarray(n, jnp.int32)))
            return tok, new, ok

        prefill = jax.jit(prefill_paged if paged else ring_prefill_for(m))

        def ring_chunk_for(model):
            def chunk_ring(params, cache, tokens, n_valid, progress, slot,
                           temp, seed, uid, gen0):
                # fold ONE chunk against the slot's accumulated prefix:
                # slice the slot out at its current progress, append with
                # the wrap-safe mask (a prompt longer than the ring slides
                # its window chunk by chunk), write back.  Same-signature
                # per bucket regardless of n_valid/progress, so chunking
                # adds ZERO executables beyond swapping prefill for
                # prefill_chunk.  The final chunk's last row is bitwise
                # the unchunked prefill's last row (chunk-parity tests),
                # and the SAME request_key(seed, uid, gen0) samples from
                # it, so token #1 is bitwise chunking-invariant.
                sub = _ring_slot_view(cache, slot, progress)
                logp, sub = model.apply_cached(params, tokens, sub,
                                               wrapped_append=True)
                last = jax.lax.dynamic_slice_in_dim(logp, n_valid - 1, 1,
                                                    axis=1)[:, 0]
                key = request_key(seed, uid, gen0)
                tok = sample_tokens(last, key, temp, top_k=top_k)
                ok = jnp.isfinite(last).all()
                return tok, insert(cache, slot, sub, progress + n_valid), ok
            return chunk_ring

        def chunk_paged(params, cache, tokens, n_valid, progress, slot,
                        temp, seed, uid, gen0):
            sub = _paged_slot_view(cache, slot, progress)
            logp, sub = m.apply_cached(params, tokens, sub,
                                       wrapped_append=True)
            last = jax.lax.dynamic_slice_in_dim(logp, n_valid - 1, 1,
                                                axis=1)[:, 0]
            key = request_key(seed, uid, gen0)
            tok = sample_tokens(last, key, temp, top_k=top_k)
            ok = jnp.isfinite(last).all()
            new = cache._replace(
                k=sub.k, v=sub.v, k_scale=sub.k_scale, v_scale=sub.v_scale,
                lengths=cache.lengths.at[slot].set(
                    jnp.asarray(progress + n_valid, jnp.int32)))
            return tok, new, ok

        chunk = jax.jit(chunk_paged if paged else ring_chunk_for(m)) \
            if self._chunk_on else None

        def decode(params, cache, last_tokens, temps, active, uids, gens,
                   seed):
            # per-row keys over (rng_uid, generated index) — NOT the
            # engine's global step: a request's sampled sequence is then
            # a pure function of (seed, rng_uid, index), invariant to
            # slot placement and batch interleaving, which is what makes
            # mid-stream failover token-for-token resumable on another
            # engine with the same seed
            logp, new = m.apply_cached(params, last_tokens, cache)
            logits = logp[:, 0]
            toks = sample_tokens_per_slot(logits,
                                          request_keys(seed, uids, gens),
                                          temps, top_k=top_k)
            # free/parked slots still flow through the fixed-shape step;
            # only ACTIVE slots advance their ring position
            lengths = jnp.where(active, new.lengths, cache.lengths)
            ok = jnp.isfinite(logits).all(axis=-1)
            return toks[:, None], new._replace(lengths=lengths), ok

        if dm is None:
            return (prefill, chunk, jax.jit(decode), None, None, None, None)

        dprefill = jax.jit(ring_prefill_for(dm)) if not self._chunk_on \
            else None
        dchunk = jax.jit(ring_chunk_for(dm)) if self._chunk_on else None

        def draft_step(dparams, dcache, cur, base_len, toks_buf, q_buf, i,
                       temps, step, seed):
            # draft step i of a spec round: feed the previous token at
            # absolute position base+i, record the proposal and its
            # PROPOSAL distribution (what spec_accept tests against) at
            # buffer row i.  The extra call at i=k exists only to write
            # d_k's K/V into the draft cache so the NEXT round's step 0
            # starts from a complete prefix; its outputs are discarded
            # (the clamped buffer index keeps it from clobbering row k-1).
            dc = dcache._replace(lengths=base_len + i)
            logp, dc = dm.apply_cached(dparams, cur, dc)
            row = logp[:, 0]
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(seed), step), i)
            tok = sample_tokens(row, key, temps, top_k=top_k)
            j = jnp.minimum(i, toks_buf.shape[1] - 1)
            toks2 = jax.lax.dynamic_update_slice(toks_buf, tok[:, None],
                                                 (0, j))
            q2 = jax.lax.dynamic_update_slice(q_buf, row[:, None], (0, j, 0))
            return tok[:, None], toks2, q2, dc

        def verify(params, cache, base_len, last, toks_buf, q_buf, temps,
                   active, step, seed):
            # ONE batched target forward scores the whole (k+1)-token
            # window: [last, d_1..d_k] appends at base..base+k, row i of
            # the log-probs is the target distribution after accepting i
            # draft tokens.  Rejected suffixes roll back by SHRINKING
            # lengths — no K/V copy; the stale columns are overwritten
            # before they can become attendable (monotone-write
            # invariant), and inactive/prefilling slots keep base.
            c = cache._replace(lengths=base_len)
            x = jnp.concatenate([last, toks_buf], axis=1)
            logp, new = m.apply_cached(params, x, c, wrapped_append=True)
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(seed), step), 0x5BEC)
            n_acc, emitted = spec_accept(logp, q_buf, toks_buf, temps, key,
                                         top_k=top_k)
            ok = jnp.isfinite(logp).all(axis=(1, 2))
            lengths = jnp.where(active, base_len + n_acc + 1, base_len)
            return (toks_buf, emitted[:, None], n_acc,
                    new._replace(lengths=lengths), ok)

        return (prefill, chunk, jax.jit(decode), dprefill, dchunk,
                jax.jit(draft_step), jax.jit(verify))

    def _warmup_args(self, params, lane: _Lane) -> "Dict[str, tuple]":
        """Per-phase warmup argument tuples for one lane — exactly the
        phases the hot path will run given the chunk/spec configuration
        (chunking REPLACES prefill with prefill_chunk; spec adds the
        draft lane + verify).  Every non-param arg is device_put so
        warmup avals (committed arrays) match the hot path exactly — an
        uncommitted numpy arg here would warm an executable the real
        steps never hit."""
        s, c = self.config.slots, lane.bucket
        seed = np.int32(self.config.seed)
        if self._pool is not None:
            # warm against the REAL pool arrays (functional: outputs are
            # discarded), with an all-trash table — same avals as the hot
            # path without double-allocating pool-sized HBM
            nbb = c // self._pool.block_size
            throwaway = self._pool.lane_view(
                jax.device_put(jnp.zeros((s, nbb), jnp.int32)),
                jax.device_put(jnp.zeros((s,), jnp.int32)))
        else:
            throwaway = jax.device_put(
                self.model.init_cache(s, c, self.config.cache_dtype))
        args: Dict[str, tuple] = {}
        if self._chunk_on:
            ch = self.config.chunk_for(c)
            args["prefill_chunk"] = (params, throwaway) + jax.device_put(
                (np.zeros((1, ch), np.int32), np.int32(1), np.int32(0),
                 np.int32(0), np.zeros((1,), np.float32), seed, np.int32(0),
                 np.int32(0)))
        else:
            args["prefill"] = (params, throwaway) + jax.device_put(
                (np.zeros((1, c), np.int32), np.int32(1), np.int32(0),
                 np.zeros((1,), np.float32), seed, np.int32(0),
                 np.int32(0)))
        args["decode"] = (params, throwaway) + jax.device_put(
            (np.zeros((s, 1), np.int32), np.zeros((s,), np.float32),
             np.zeros((s,), bool), np.zeros((s,), np.int32),
             np.zeros((s,), np.int32), seed))
        if self._spec_on:
            args["verify"] = (params, throwaway) + jax.device_put(
                (np.zeros((s,), np.int32), np.zeros((s, 1), np.int32))) + (
                self._toks0, self._q0) + jax.device_put(
                (np.zeros((s,), np.float32), np.zeros((s,), bool),
                 np.int32(0), seed))
            dp = self.registry.draft().params
            dthrow = jax.device_put(self._draft_model.init_cache(
                s, c, self.config.cache_dtype))
            if self._chunk_on:
                ch = self.config.chunk_for(c)
                args["draft_chunk"] = (dp, dthrow) + jax.device_put(
                    (np.zeros((1, ch), np.int32), np.int32(1), np.int32(0),
                     np.int32(0), np.zeros((1,), np.float32), seed,
                     np.int32(0), np.int32(0)))
            else:
                args["draft_prefill"] = (dp, dthrow) + jax.device_put(
                    (np.zeros((1, c), np.int32), np.int32(1), np.int32(0),
                     np.zeros((1,), np.float32), seed, np.int32(0),
                     np.int32(0)))
            args["draft_step"] = (dp, dthrow) + jax.device_put(
                (np.zeros((s, 1), np.int32), np.zeros((s,), np.int32))) + (
                self._toks0, self._q0, self._i_dev[0]) + jax.device_put(
                (np.zeros((s,), np.float32), np.int32(0), seed))
        return args

    def _base_fn(self, phase: str):
        return {"prefill": self._prefill, "prefill_chunk": self._chunk,
                "decode": self._decode, "draft_prefill": self._dprefill,
                "draft_chunk": self._dchunk, "draft_step": self._dstep,
                "verify": self._verify}[phase]

    def _warmup(self, params: Any, state: Any = None) -> None:
        """Warm every hot-path executable for every bucket BEFORE a
        version activates (ModelRegistry calls this off the request
        path).  Same three tiers as ServingRuntime._warmup: params-only
        swap reuses live executables; compile cache on -> AOT load from
        disk; off -> one real call per (bucket, phase).  Draft-phase
        entries trace against draft params, so a TARGET hot-swap keeps
        them and re-warms only prefill/decode/verify — and a draft swap
        (`registry.set_draft`) does the converse."""
        from bigdl_tpu import compilecache as _cc

        psig = _tree_sig(params)
        if psig != self._warmed_psig:
            self._warmed = {kk: vv for kk, vv in self._warmed.items()
                            if kk[0].startswith("draft_")}
        draft = self.registry.draft() if self._spec_on else None
        if draft is not None:
            dsig = _tree_sig(draft.params)
            if dsig != self._warmed_dsig:
                self._warmed = {kk: vv for kk, vv in self._warmed.items()
                                if not kk[0].startswith("draft_")}
                self._warmed_dsig = dsig
        use_cache = _cc.enabled()
        reg = _obs.registry()
        for lane in self._lanes.values():
            for phase, args in self._warmup_args(params, lane).items():
                fn = self._base_fn(phase)
                keyk = (phase, lane.bucket)
                if keyk in self._warmed:
                    reg.inc("generation/warmup_reused")
                    continue
                sig = f"generation/{phase}/bucket={lane.bucket}"
                with _obs.attribute(sig), \
                        _obs.span("gen.warmup", cat="generation",
                                  phase=phase, bucket=lane.bucket):
                    if use_cache:
                        warmed, status = _cc.load_or_compile(
                            fn, args, signature=sig,
                            extra_key={"kind": "generation", "phase": phase,
                                       "bucket": lane.bucket,
                                       "slots": self.config.slots,
                                       "top_k": self.config.top_k,
                                       # allocator/dtype enter the traced
                                       # avals (table shapes, int8 pools)
                                       # and so the StableHLO digest too;
                                       # keyed explicitly as belt and
                                       # suspenders
                                       "paged": self.config.paged,
                                       "kv_dtype": str(jnp.dtype(
                                           self.config.cache_dtype)),
                                       "block": self.config.kv_block_size
                                       if self.config.paged else 0,
                                       "chunk": self.config.chunk_for(
                                           lane.bucket),
                                       "spec_k": self.config.spec_k
                                       if self._spec_on else 0},
                            process_scope="generation")
                        self._warmed[keyk] = warmed if status != "error" else fn
                    else:
                        out = fn(*args)
                        jax.tree_util.tree_map(
                            lambda l: getattr(l, "block_until_ready",
                                              lambda: l)(), out)
                        self._warmed[keyk] = fn
        self._warmed_psig = psig

    def _fn(self, phase: str, bucket: int, snap: ModelVersion):
        # draft phases are pinned by the DRAFT param signature (snap is
        # then the draft ModelVersion), target phases by the active one
        sig = self._warmed_dsig if phase.startswith("draft_") \
            else self._warmed_psig
        if self._warmed and sig == _tree_sig(snap.params):
            fn = self._warmed.get((phase, bucket))
            if fn is not None:
                return fn
        return self._base_fn(phase)

    def compile_count(self) -> int:
        """Distinct compiled generation executables — the bucket-discipline
        probe.  The pinned budget per bucket: both features off =
        {prefill, decode} (2, pre-existing); chunked prefill on =
        {prefill_chunk, decode} (still 2 — chunking REPLACES prefill);
        spec decode on adds {draft_prefill | draft_chunk, draft_step,
        verify} (5 total).  pjit cache sizes are the ground truth, plus
        AOT-loaded executables which live outside them."""
        fns = [f for f in (self._prefill, self._chunk, self._decode,
                           self._dprefill, self._dchunk, self._dstep,
                           self._verify) if f is not None]
        base = {id(f) for f in fns}
        aot = sum(1 for fn in self._warmed.values() if id(fn) not in base)
        try:
            return int(sum(f._cache_size() for f in fns)) + aot
        except Exception:
            return len(self._warmed)

    # -- KV residency ------------------------------------------------------

    def _lane_cache(self, lane: _Lane):
        """The device cache pytree for one step: the lane's private ring,
        or a PagedKVCache view composing the shared pool with this lane's
        (lazily uploaded) table + lengths."""
        if self._pool is None:
            return lane.cache
        return self._pool.lane_view(lane.table_dev(), lane.lengths_dev)

    def _store_cache(self, lane: _Lane, new) -> None:
        if self._pool is None:
            lane.cache = new
            return
        self._pool.update_from(new)
        lane.lengths_dev = new.lengths

    def kv_nbytes(self) -> int:
        """Device bytes resident for KV (pool, or the sum of ring lanes)."""
        if self._pool is not None:
            return self._pool.nbytes()
        return sum(lane.cache.nbytes() for lane in self._lanes.values())

    def _prefix_store(self, snap: ModelVersion) -> Optional[PrefixStore]:
        """The prefix store pinned to `snap`'s KV world — refreshes the
        world fingerprint on the first touch after a hot-swap, which
        sweeps idle entries written under the old weights (in-flight
        mappings linger until their slots retire, then evict)."""
        if self._prefix is None:
            return None
        if snap.version != self._prefix_version:
            self._prefix.set_world(world_key(
                snap.version, _tree_sig(snap.params),
                str(jnp.dtype(self.config.cache_dtype)),
                self.config.kv_block_size))
            self._prefix_version = snap.version
        return self._prefix

    @property
    def prefix_store(self) -> Optional[PrefixStore]:
        return self._prefix

    def kv_sharing(self) -> Dict[str, int]:
        """Host-side sharing snapshot: logical resident blocks (each
        slot's claims counted independently), unique resident blocks
        (slot claims + store-held), and the bytes each implies — the
        resident-tokens-per-HBM-byte numerator/denominator for the
        prefix A/B (no device sync)."""
        if self._pool is None:
            return {}
        per_block = self._pool.bytes_per_token() * self._pool.block_size
        logical = 0
        uniq: set = set()
        tokens = 0
        for lane in self._lanes.values():
            for s in range(self.config.slots):
                logical += len(lane.claimed[s])
                uniq.update(lane.claimed[s])
                tokens += int(min(lane.lengths_np[s], lane.bucket))
        if self._prefix is not None:
            uniq.update(self._prefix.block_ids())
        return {"logical_blocks": logical, "unique_blocks": len(uniq),
                "logical_bytes": logical * per_block,
                "unique_bytes": len(uniq) * per_block,
                "resident_tokens": tokens,
                "shared_blocks": self._pool.blocks_shared}

    def _update_kv_gauges(self) -> None:
        # HBM budgeting gauges (Prometheus: bigdl_tpu_generation_...
        # {lane="..."}); host-side arithmetic only, no device sync
        reg = _obs.registry()
        if self._pool is not None:
            reg.set_gauge("generation/kv_hbm_bytes|lane=pool",
                          float(self._pool.nbytes()))
            reg.set_gauge("generation/kv_blocks_free",
                          float(self._pool.blocks_free))
            reg.set_gauge("generation/kv_blocks_reserved",
                          float(self._pool.blocks_reserved))
            reg.set_gauge("generation/kv_blocks_shared",
                          float(self._pool.blocks_shared))
            if self._prefix is not None:
                reg.set_gauge("generation/prefix_cache_blocks",
                              float(len(self._prefix)))
        else:
            for b, lane in self._lanes.items():
                reg.set_gauge(f"generation/kv_hbm_bytes|lane={b}",
                              float(lane.cache.nbytes()))

    # -- admission ---------------------------------------------------------

    def submit(self, prompt, *, max_new_tokens: Optional[int] = None,
               temperature: Optional[float] = None,
               eos_id: Optional[int] = None,
               cid: Optional[str] = None,
               resume_tokens=None,
               rng_uid: Optional[int] = None) -> _Future:
        """Async admission: returns a future resolving to a
        `GenerationResult` (`.result(timeout=...)`).

        `resume_tokens` re-admits a request that already emitted tokens
        on a replica that died (the fleet failover path): they fold as
        part of the EFFECTIVE prompt — a chunk-skipping warm prefill
        when the prefix store holds the prompt head — and generation
        continues at sampling index `len(resume_tokens)` of the
        request's rng stream (`rng_uid`, defaulting to a digest of the
        cid so victim and survivor derive the same stream).  The result
        contains the FULL token list, resumed + new, so settle-side
        dedup is structural: one future, one list, set once."""
        toks = np.asarray(prompt, np.int32).reshape(-1)
        if toks.size < 1:
            raise ValueError("empty prompt")
        resume = np.asarray(
            resume_tokens if resume_tokens is not None else [],
            np.int32).reshape(-1)
        max_new = max(1, int(self.config.max_new_tokens
                             if max_new_tokens is None else max_new_tokens))
        temp = float(self.config.temperature
                     if temperature is None else temperature)
        eos = self.config.eos_id if eos_id is None else eos_id
        if resume.size:
            done = None
            if eos is not None and int(eos) in resume:
                # the victim emitted EOS but died before (or while)
                # settling: the request is already complete — settle
                # from the snapshot, refolding nothing
                resume = resume[:int(np.argmax(resume == int(eos))) + 1]
                done = "eos"
            elif resume.size >= max_new:
                done = "length"
            if done is not None:
                return self._settle_resumed(toks, resume, done, cid, temp)
        eff = np.concatenate([toks, resume]) if resume.size else toks
        if eff.size > self.config.buckets[-1] and not self._chunk_on:
            # with chunked prefill on, a longer prompt folds through the
            # largest bucket chunk by chunk (sliding window past C)
            raise ValueError(
                f"prompt of {eff.size} tokens exceeds the largest length "
                f"bucket {self.config.buckets[-1]}; truncate or configure "
                "a larger bucket")
        with self._cond:
            if self._closed:
                self.metrics.on_reject("shutdown")
                raise ServingClosed("generation engine is closed")
            if len(self._pending) >= self.config.capacity:
                self.metrics.on_reject("queue_full")
                _obs.instant("gen.reject", cat="generation",
                             reason="queue_full")
                raise Rejected(
                    f"generation queue full ({self.config.capacity} "
                    "requests); backpressure — retry with backoff or raise "
                    "capacity")
            self._uid_counter += 1
            req = _GenRequest(eff, max_new, temp, eos, self._uid_counter,
                              cid=cid, rng_uid=rng_uid,
                              resume_n=int(resume.size))
            self._pending.append(req)
            depth = len(self._pending)
            self._cond.notify()
        self.metrics.on_admit(depth)
        _obs.instant("gen.admit", cat="generation", cid=req.cid,
                     prompt_tokens=int(toks.size), depth=depth,
                     resumed=int(resume.size))
        return req.future

    def _settle_resumed(self, prompt, resume, reason: str,
                        cid: Optional[str], temp: float) -> _Future:
        """A resumed request whose snapshot already finished (EOS emitted
        or max_new reached before the kill): settle immediately with the
        snapshot tokens — refolding would regenerate past the end."""
        fut = _Future()
        cid = cid if cid is not None else _obs.next_cid()
        self.metrics.on_admit(0)
        meta = {
            "cid": cid, "version": self.registry.active_version,
            "bucket": None, "finish_reason": reason,
            "prompt_tokens": int(prompt.size), "tokens": int(resume.size),
            "ttft_ms": 0.0, "ms_per_token": None,
            "resumed_tokens": int(resume.size), "recovered": True,
        }
        self.metrics.on_complete(0.0, int(resume.size))
        _obs.instant("gen.complete", cat="generation", cid=cid,
                     tokens=int(resume.size), reason=reason, recovered=True)
        fut.meta = meta
        fut.set_result(GenerationResult(np.asarray(resume, np.int32), meta))
        return fut

    def generate(self, prompt, timeout: Optional[float] = 120.0,
                 **kw) -> GenerationResult:
        """Blocking single-request generation."""
        return self.submit(prompt, **kw).result(timeout)

    # -- scheduler loop ----------------------------------------------------

    def _pick_lane(self, req: _GenRequest) -> Optional[_Lane]:
        """Smallest bucket holding prompt+completion without ring wrap;
        otherwise the LARGEST bucket that fits the prompt (wrap = sliding
        window over the last C tokens).  Returns None when no eligible
        lane has a free slot (the request stays queued, FIFO)."""
        n = int(req.prompt.size)
        # max_new counts TOTAL emission (resumed + new), and resumed
        # tokens already sit inside the effective prompt — subtract them
        # or a resumed request would double-count its own progress and
        # get bumped into a needlessly large bucket
        fits = [b for b in self.config.buckets
                if b >= n + req.max_new - req.resume_n]
        wraps = [b for b in reversed(self.config.buckets) if b >= n]
        if not wraps and self._chunk_on:
            # longer than every bucket: chunked prefill folds the FULL
            # prompt through the largest ring (sliding window), instead
            # of the pre-chunking submit-time rejection
            wraps = [self.config.buckets[-1]]
        for b in fits + wraps:
            if self._lanes[b].free:
                return self._lanes[b]
        return None

    def _n_active(self) -> int:
        return sum(lane.n_active for lane in self._lanes.values())

    def _admit(self, snap: ModelVersion, tr) -> None:
        mon = _obs.compile_monitor()
        while True:
            with self._cond:
                if not self._pending:
                    return
                lane = self._pick_lane(self._pending[0])
                if lane is None:
                    return  # every eligible slot busy; retry after decode
                req = self._pending.popleft()
            n = int(req.prompt.size)
            rem = req.max_new - req.resume_n  # new tokens still to emit
            if lane.bucket < n + rem:
                if self._chunk_on and n > lane.bucket:
                    # a prompt longer than every bucket routes through
                    # chunking: the FULL prompt folds (sliding window past
                    # C), nothing is truncated at admission — counted
                    # separately from wrap-truncated generations
                    _obs.registry().inc("generation/chunked_long_prompts")
                else:
                    # the prompt only fit a wrap lane: generation will
                    # slide the window over the last `bucket` tokens —
                    # correct but lossy, so make the degradation
                    # observable
                    _obs.registry().inc("generation/wrapped_prefills")
                    if not self._warned_wrap:
                        self._warned_wrap = True
                        _log.warning(
                            "prefill of %d tokens + %d max_new exceeds "
                            "bucket %d: the KV ring will wrap and attention "
                            "degrades to a sliding window over the last %d "
                            "tokens (further wraps counted in "
                            "generation/wrapped_prefills, warned once)",
                            n, req.max_new, lane.bucket, lane.bucket)
            sched = _chunk_schedule(n, self.config.chunk_for(lane.bucket)) \
                if self._chunk_on else None
            shared_ids: List[int] = []
            skip = 0       # prompt tokens covered by mapped shared blocks
            resume_i = 0   # first chunk of the schedule that still folds
            if self._pool is not None:
                # worst-case logical reservation up front so the lazy
                # per-step claims below can never fail mid-decode; spec
                # rounds write up to k positions past the emitted length,
                # so the reservation covers them too
                spec_extra = self.config.spec_k if self._spec_on else 0
                need = blocks_for(
                    min(lane.bucket, n + rem + spec_extra),
                    self._pool.block_size)
                if need > self._pool.n_allocatable:
                    req.future.set_error(Rejected(
                        f"request needs {need} KV blocks but the pool only "
                        f"has {self._pool.n_allocatable}; raise "
                        "kv_pool_blocks or shrink max_new_tokens"))
                    continue
                store = self._prefix_store(snap)
                if store is not None and sched is not None \
                        and len(sched) > 1 \
                        and n + rem + spec_extra <= lane.bucket:
                    # map the warm prefix read-only: resume the chunk
                    # schedule at the largest block-aligned offset the
                    # store's cached prefix covers.  The final chunk
                    # always folds (it samples token #1), so even a
                    # full-prompt hit runs one chunk — which also
                    # guarantees every subsequent write (cold suffix,
                    # decode, spec overhang) lands past `skip`, i.e. in
                    # private blocks: copy-on-write by never mapping the
                    # first divergent block.  Wrap lanes are excluded —
                    # a wrapping ring rewrites low block indices, which
                    # must stay private.
                    blk = self._pool.block_size
                    hit_ids = store.lookup(req.prompt)
                    hit = len(hit_ids) * blk
                    for i in range(1, len(sched)):
                        off = sched[i][0]
                        if off > hit:
                            break
                        if off % blk == 0:
                            resume_i = i
                    if resume_i > 0:
                        skip = sched[resume_i][0]
                        shared_ids = hit_ids[:skip // blk]
                        # pin BEFORE reserving: the reserve gate
                        # discounts shared (refcount >= 2) blocks
                        self._pool.addref(shared_ids)
                # a warm prefix is already resident: reserve only the
                # COLD blocks, or a warm pool rejects requests it can
                # serve (tests/test_pagedkv.py oversubscription test)
                need -= len(shared_ids)
                if not self._pool.reserve(need):
                    # pool budget exhausted: requeue at head, retry after
                    # an in-flight request retires and releases blocks
                    if shared_ids:
                        self._pool.release(shared_ids)
                    with self._cond:
                        self._pending.appendleft(req)
                    return
            s = lane.free.pop()
            lane.spec_stale[s] = False
            if self._spec_on and req.resume_n:
                # speculative rounds key their draws on the engine's
                # GLOBAL step counter, which the survivor does not share
                # with the victim: a resumed sampled request would
                # diverge from its stream.  Latch it onto the plain
                # decode path, whose per-(rng_uid, index) keys make the
                # continuation bitwise identical.
                lane.spec_stale[s] = True
            if self._chunk_on:
                # multi-chunk admission runs NO executable here: the slot
                # parks in lane.prefilling and _advance_prefill folds one
                # chunk per scheduler iteration, interleaved with decode
                # steps — in-flight lanes never stall longer than one
                # chunk on a long prompt.  A prompt that fits ONE chunk
                # folds synchronously below (same chunk executable, so
                # the pinned set is unchanged): short requests pay no
                # scheduler-pass deferral for having chunking enabled
                if self._pool is not None:
                    # a mapped hit prefix seeds claimed[s] with SHARED
                    # ids (a dense prefix, so the lazy claim cursor and
                    # the uniform release-on-retire path need no special
                    # casing) — but the DEVICE table row stays all-trash
                    # until the first fold maps them (ps.map_shared):
                    # until that fold sets the slot's device length,
                    # batched-decode writes for this not-yet-active slot
                    # land at a STALE device length, and the trash row is
                    # what keeps them out of the shared blocks
                    lane.claimed[s] = list(shared_ids)
                    lane.reserved[s] = need
                    lane.table_np[s, :] = 0
                    lane._table_dirty = True
                    self._update_kv_gauges()
                lane.lengths_np[s] = skip
                lane.slots[s] = _SlotState(req)
                lane.active_np[s] = False
                ps = _PrefillState(req, sched, self._long_inflight > 0)
                ps.next_i = resume_i
                ps.long = len(sched) - resume_i > 1
                ps.map_shared = len(shared_ids)
                lane.prefilling[s] = ps
                if skip:
                    if self._spec_on:
                        # the draft cache never sees the skipped chunks,
                        # so its prefix K/V would be garbage: latch the
                        # slot out of speculative rounds (verify would
                        # stay correct, but every proposal would be
                        # noise) — spec and shared prefixes meet only
                        # through private tail blocks
                        lane.spec_stale[s] = True
                    req.hit_tokens = skip
                    self.metrics.on_prefix_hit(skip)
                    _obs.instant("gen.prefix_hit", cat="generation",
                                 cid=req.cid, tokens=skip,
                                 blocks=len(shared_ids))
                if ps.long:
                    self._long_inflight += 1
                else:
                    self._advance_prefill(lane, snap, tr, slot=s)
                continue
            if self._pool is not None:
                npre = blocks_for(n, self._pool.block_size)
                ids = self._pool.claim(npre)
                lane.claimed[s] = ids
                lane.reserved[s] = need
                lane.table_np[s, :] = 0
                lane.table_np[s, :npre] = ids
                lane._table_dirty = True
                self._update_kv_gauges()
            lane.lengths_np[s] = n
            padded = np.zeros((1, lane.bucket), np.int32)
            padded[0, :n] = req.prompt
            fn = self._fn("prefill", lane.bucket, snap)
            t0 = time.perf_counter()
            with (tr.span("gen.prefill", cat="generation", cid=req.cid,
                          bucket=lane.bucket, prompt_tokens=n)
                  if tr is not None else _NULL), \
                    (mon.attribute(f"generation/prefill/bucket={lane.bucket}")
                     if mon is not None else _NULL), \
                    strict_transfers(self._strict):
                args = jax.device_put(
                    (padded, np.int32(n), np.int32(s),
                     np.asarray([req.temperature], np.float32),
                     np.int32(self.config.seed), np.int32(req.rng_uid),
                     np.int32(req.resume_n)))
                tok, new_cache, ok = fn(
                    snap.params, self._lane_cache(lane), *args)
                self._store_cache(lane, new_cache)
                if self._spec_on:
                    # mirror the prompt into the draft cache so round 0's
                    # draft steps continue from a complete prefix (sampled
                    # token and finite-check are the target's business)
                    dsnap = self.registry.draft()
                    dfn = self._fn("draft_prefill", lane.bucket, dsnap)
                    with (mon.attribute(
                            f"generation/draft_prefill/bucket={lane.bucket}")
                            if mon is not None else _NULL):
                        _dt, dc, _dok = dfn(dsnap.params, lane.dcache, *args)
                        lane.dcache = dc
                tok = int(jax.device_get(tok)[0])
                ok = bool(jax.device_get(ok))
            t1 = time.perf_counter()
            st = _SlotState(req)
            st.t_first = t1
            st.tokens.append(tok)
            lane.slots[s] = st
            lane.temps_np[s] = req.temperature
            lane.active_np[s] = True
            lane.last_np[s, 0] = tok
            self.metrics.on_prefill((t1 - t0) * 1e3,
                                    (t1 - req.t_submit) * 1e3)
            self.metrics.set_active(self._n_active())
            if self.config.reject_nonfinite and not ok:
                self._retire(lane, s, "error", tr)
                continue
            st.generated = req.resume_n + 1
            if req.resume_n:
                self.metrics.on_recovery((t1 - req.t_submit) * 1e3,
                                         req.resume_n, req.hit_tokens)
                _obs.instant("gen.recovered", cat="generation", cid=req.cid,
                             resumed=req.resume_n,
                             prefix_tokens=req.hit_tokens)
            self._snap_progress(st)
            if (req.eos_id is not None and tok == req.eos_id) \
                    or st.generated >= req.max_new:
                self._retire(lane, s,
                             "eos" if req.eos_id is not None
                             and tok == req.eos_id else "length", tr)

    def _advance_prefill(self, lane: _Lane, snap: ModelVersion, tr,
                         slot: Optional[int] = None) -> None:
        """Fold ONE chunk of the lane's oldest mid-prefill request (or of
        `slot`, for the synchronous single-chunk admission) — the
        admission policy: decode lanes wait at most one chunk of any long
        prompt per scheduler iteration.  Non-final chunks dispatch async
        (no host sync; a NaN poisons the cache and surfaces at the final
        chunk's finite-check); the final chunk activates the slot exactly
        like an unchunked prefill, sampling token #1 from a bitwise-
        identical last row with the same fold_in(seed, uid) key."""
        mon = _obs.compile_monitor()
        s = next(iter(lane.prefilling)) if slot is None else slot
        ps = lane.prefilling[s]
        req = ps.req
        prog, nv = ps.sched[ps.next_i]
        final = ps.next_i == len(ps.sched) - 1
        ch = self.config.chunk_for(lane.bucket)
        if self._pool is not None:
            blk = self._pool.block_size
            if ps.map_shared:
                # deferred hit mapping: the shared ids enter the device
                # table in the SAME launch that folds the first cold
                # chunk and sets the slot's device length past them —
                # between admission and here the row was all-trash, so
                # batched-decode writes for this not-yet-active slot
                # (landing at its stale device length) hit the trash
                # block, never a shared one
                lane.table_np[s, :ps.map_shared] = \
                    lane.claimed[s][:ps.map_shared]
                lane._table_dirty = True
                ps.map_shared = 0
            # claims stay a dense prefix of block indices; a chunk that
            # wrapped past the ring cycles into already-claimed low
            # indices and claims nothing new
            hi = max((p % lane.bucket) // blk for p in range(prog, prog + nv))
            claimed_any = False
            while len(lane.claimed[s]) <= hi:
                bi = len(lane.claimed[s])
                bid = self._pool.claim(1)[0]
                lane.claimed[s].append(bid)
                lane.table_np[s, bi] = bid
                lane._table_dirty = True
                claimed_any = True
            if claimed_any:
                self._update_kv_gauges()
        toks = np.zeros((1, ch), np.int32)
        toks[0, :nv] = req.prompt[prog:prog + nv]
        fn = self._fn("prefill_chunk", lane.bucket, snap)
        t0 = time.perf_counter()
        with (tr.span("gen.prefill_chunk", cat="generation", cid=req.cid,
                      bucket=lane.bucket, progress=prog, n_valid=nv)
              if tr is not None else _NULL), \
                (mon.attribute(
                    f"generation/prefill_chunk/bucket={lane.bucket}")
                 if mon is not None else _NULL), \
                strict_transfers(self._strict):
            args = jax.device_put(
                (toks, np.int32(nv), np.int32(prog), np.int32(s),
                 np.asarray([req.temperature], np.float32),
                 np.int32(self.config.seed), np.int32(req.rng_uid),
                 np.int32(req.resume_n)))
            tok, new_cache, ok = fn(
                snap.params, self._lane_cache(lane), *args)
            self._store_cache(lane, new_cache)
            if self._spec_on:
                dsnap = self.registry.draft()
                dfn = self._fn("draft_chunk", lane.bucket, dsnap)
                with (mon.attribute(
                        f"generation/draft_chunk/bucket={lane.bucket}")
                        if mon is not None else _NULL):
                    _dt, dc, _dok = dfn(dsnap.params, lane.dcache, *args)
                    lane.dcache = dc
            if final:
                tok = int(jax.device_get(tok)[0])
                ok = bool(jax.device_get(ok))
        t1 = time.perf_counter()
        ps.prefill_ms += (t1 - t0) * 1e3
        lane.lengths_np[s] = prog + nv
        ps.next_i += 1
        self.metrics.on_prefill_chunk()
        self._chunk_folds += 1
        self._fire_step_hook("prefill_chunk")
        if not final:
            return
        del lane.prefilling[s]
        if ps.long:
            self._long_inflight -= 1
        st = lane.slots[s]
        st.t_first = t1
        st.tokens.append(tok)
        lane.temps_np[s] = req.temperature
        lane.active_np[s] = True
        lane.last_np[s, 0] = tok
        self.metrics.on_prefill(ps.prefill_ms, (t1 - req.t_submit) * 1e3,
                                contended=ps.contended)
        self.metrics.set_active(self._n_active())
        if self.config.reject_nonfinite and not ok:
            self._retire(lane, s, "error", tr)
            return
        store = self._prefix_store(snap) if self._pool is not None else None
        if store is not None:
            spec_extra = self.config.spec_k if self._spec_on else 0
            npr = int(req.prompt.size)
            if npr + req.max_new - req.resume_n + spec_extra <= lane.bucket:
                # offer the folded prompt's full blocks to the store
                # (blocks whose address is already cached keep the
                # existing entry; fresh ones get the store's own pin).
                # Wrap lanes never publish: their low blocks get
                # rewritten by the sliding window.
                if store.publish(req.prompt, npr, lane.claimed[s]):
                    self._update_kv_gauges()
        st.generated = req.resume_n + 1
        if req.resume_n:
            self.metrics.on_recovery((t1 - req.t_submit) * 1e3,
                                     req.resume_n, req.hit_tokens)
            _obs.instant("gen.recovered", cat="generation", cid=req.cid,
                         resumed=req.resume_n, prefix_tokens=req.hit_tokens)
        self._snap_progress(st)
        if (req.eos_id is not None and tok == req.eos_id) \
                or st.generated >= req.max_new:
            self._retire(lane, s,
                         "eos" if req.eos_id is not None
                         and tok == req.eos_id else "length", tr)

    def _spec_ok(self, lane: _Lane) -> bool:
        """A speculative round needs every ACTIVE slot able to take k+1
        more positions without wrapping (once a slot nears its bucket it
        plain-decodes; lengths only grow, so it never flips back) and a
        draft cache that mirrors the target (a slot that ever rode a
        plain decode step is latched stale until it retires)."""
        k = self.config.spec_k
        any_active = False
        for s in range(self.config.slots):
            if not lane.active_np[s]:
                continue
            if lane.spec_stale[s] \
                    or int(lane.lengths_np[s]) + k + 1 > lane.bucket:
                return False
            any_active = True
        return any_active

    def _spec_round(self, lane: _Lane, snap: ModelVersion, tr) -> None:
        """One draft-verify decode round: k chained draft steps propose
        tokens + proposal log-probs on device, ONE batched verify forward
        scores the (k+1)-token window against the target cache, and
        accept/resample emits n_acc+1 tokens per active slot.  Rejected
        suffixes roll back by SHRINKING lengths — no K/V copy (stale
        columns are rewritten before they can become attendable).  Host
        traffic is one device_get per ROUND, same budget as one plain
        decode step."""
        mon = _obs.compile_monitor()
        k = self.config.spec_k
        n_act = lane.n_active
        dsnap = self.registry.draft()
        if self._pool is not None:
            # claims must cover the k garbage positions past each active
            # slot's length (no wrap, by the _spec_ok gate; covered by
            # the spec-aware admission reservation, so cannot fail)
            blk = self._pool.block_size
            claimed_any = False
            for s in range(self.config.slots):
                if not lane.active_np[s]:
                    continue
                hi = (int(lane.lengths_np[s]) + k) // blk
                while len(lane.claimed[s]) <= hi:
                    bi = len(lane.claimed[s])
                    bid = self._pool.claim(1)[0]
                    lane.claimed[s].append(bid)
                    lane.table_np[s, bi] = bid
                    lane._table_dirty = True
                    claimed_any = True
            if claimed_any:
                self._update_kv_gauges()
        cids = [lane.slots[s].req.cid for s in range(self.config.slots)
                if lane.slots[s] is not None and lane.active_np[s]]
        t0 = time.perf_counter()
        with (tr.span("gen.spec_round", cat="generation", bucket=lane.bucket,
                      active=n_act, k=k, cids=cids)
              if tr is not None else _NULL), \
                strict_transfers(self._strict):
            base, cur, temps, active, step, seed = jax.device_put(
                (lane.lengths_np.astype(np.int32), lane.last_np,
                 lane.temps_np, lane.active_np, np.int32(self._steps),
                 np.int32(self.config.seed)))
            last_dev = cur
            toks_buf, q_buf = self._toks0, self._q0
            dfn = self._fn("draft_step", lane.bucket, dsnap)
            dc = lane.dcache
            with (mon.attribute(f"generation/draft_step/bucket={lane.bucket}")
                  if mon is not None else _NULL):
                for i in range(k + 1):
                    # call k only writes d_k's K/V into the draft cache;
                    # its proposal is discarded (buffer index clamped)
                    tok_d, t2, q2, dc = dfn(dsnap.params, dc, cur, base,
                                            toks_buf, q_buf, self._i_dev[i],
                                            temps, step, seed)
                    if i < k:
                        cur, toks_buf, q_buf = tok_d, t2, q2
            lane.dcache = dc
            vfn = self._fn("verify", lane.bucket, snap)
            with (mon.attribute(f"generation/verify/bucket={lane.bucket}")
                  if mon is not None else _NULL):
                d_toks, emitted, n_acc, new_cache, ok = vfn(
                    snap.params, self._lane_cache(lane), base, last_dev,
                    toks_buf, q_buf, temps, active, step, seed)
                self._store_cache(lane, new_cache)
            d_np, em_np, na_np, ok_np = jax.device_get(
                (d_toks, emitted, n_acc, ok))  # the ONE per-round sync
        step_ms = (time.perf_counter() - t0) * 1e3
        self._steps += 1
        accepted = 0
        emitted_total = 0
        for s in range(self.config.slots):
            st = lane.slots[s]
            if st is None or not lane.active_np[s]:
                continue
            if self.config.reject_nonfinite and not bool(ok_np[s]):
                self._retire(lane, s, "error", tr)
                continue
            na = int(na_np[s])
            accepted += na
            lane.lengths_np[s] += na + 1
            st.step_ms_sum += step_ms
            done = None
            for t in [int(x) for x in d_np[s, :na]] + [int(em_np[s, 0])]:
                st.tokens.append(t)
                st.generated += 1
                emitted_total += 1
                if st.req.eos_id is not None and t == st.req.eos_id:
                    done = "eos"
                    break
                if st.generated >= st.req.max_new:
                    done = "length"
                    break
            lane.last_np[s, 0] = st.tokens[-1]
            self._snap_progress(st)
            if done is not None:
                self._retire(lane, s, done, tr)
        self.metrics.on_tokens(emitted_total, step_ms)
        self.metrics.on_spec_round(n_act * k, accepted, k + 1)
        self._fire_step_hook("decode")

    def _decode_lane(self, lane: _Lane, snap: ModelVersion, tr) -> None:
        if self._spec_on and self._spec_ok(lane):
            self._spec_round(lane, snap, tr)
            return
        mon = _obs.compile_monitor()
        k = lane.n_active
        fn = self._fn("decode", lane.bucket, snap)
        cids = [lane.slots[s].req.cid for s in range(self.config.slots)
                if lane.slots[s] is not None and lane.active_np[s]]
        if self._pool is not None:
            # lazy physical claims: a slot whose NEXT write position
            # crosses into an unclaimed block claims it now (covered by
            # the admission reservation, so this cannot fail); ring wrap
            # cycles back into already-claimed blocks and claims nothing
            claimed_any = False
            for s in range(self.config.slots):
                if not lane.active_np[s]:
                    continue
                bi = (int(lane.lengths_np[s]) % lane.bucket) \
                    // self._pool.block_size
                if bi == len(lane.claimed[s]):
                    bid = self._pool.claim(1)[0]
                    lane.claimed[s].append(bid)
                    lane.table_np[s, bi] = bid
                    lane._table_dirty = True
                    claimed_any = True
            if claimed_any:
                self._update_kv_gauges()
        t0 = time.perf_counter()
        with (tr.span("gen.decode_step", cat="generation",
                      bucket=lane.bucket, active=k, cids=cids)
              if tr is not None else _NULL), \
                (mon.attribute(f"generation/decode/bucket={lane.bucket}")
                 if mon is not None else _NULL), \
                strict_transfers(self._strict):
            for s in range(self.config.slots):
                st = lane.slots[s]
                if st is not None and lane.active_np[s]:
                    # per-slot sampling keys: each active request draws
                    # token index `generated` of its own stream this step
                    lane.uids_np[s] = st.req.rng_uid
                    lane.gens_np[s] = st.generated
            toks, new_cache, ok = fn(
                snap.params, self._lane_cache(lane), *jax.device_put(
                    (lane.last_np, lane.temps_np, lane.active_np,
                     lane.uids_np, lane.gens_np,
                     np.int32(self.config.seed))))
            self._store_cache(lane, new_cache)
            toks_np = jax.device_get(toks)  # the ONE per-step host sync
            ok_np = jax.device_get(ok)
        step_ms = (time.perf_counter() - t0) * 1e3
        self._steps += 1
        for s in range(self.config.slots):
            if lane.active_np[s]:
                lane.lengths_np[s] += 1
        if self._spec_on:
            # this step advanced target state the draft cache didn't see:
            # latch the slots out of speculative rounds until they retire
            lane.spec_stale |= lane.active_np
        self.metrics.on_tokens(k, step_ms)
        for s in range(self.config.slots):
            st = lane.slots[s]
            if st is None or not lane.active_np[s]:
                continue
            if self.config.reject_nonfinite and not bool(ok_np[s]):
                self._retire(lane, s, "error", tr)
                continue
            tok = int(toks_np[s, 0])
            lane.last_np[s, 0] = tok
            st.tokens.append(tok)
            st.generated += 1
            st.step_ms_sum += step_ms
            self._snap_progress(st)
            if st.req.eos_id is not None and tok == st.req.eos_id:
                self._retire(lane, s, "eos", tr)
            elif st.generated >= st.req.max_new:
                self._retire(lane, s, "length", tr)
        self._fire_step_hook("decode")

    def _release_blocks(self, lane: _Lane, s: int) -> None:
        """Return a retired slot's pool blocks + reservation and point its
        table row back at the trash block (so its fixed-shape decode
        writes stop touching real blocks)."""
        if self._pool is None:
            lane.lengths_np[s] = 0
            return
        self._pool.release(lane.claimed[s])
        self._pool.unreserve(lane.reserved[s])
        lane.claimed[s] = []
        lane.reserved[s] = 0
        lane.table_np[s, :] = 0
        lane._table_dirty = True
        lane.lengths_np[s] = 0
        self._update_kv_gauges()

    def _snap_progress(self, st: _SlotState) -> None:
        """Publish emitted-token progress into the future's meta at a
        settle-safe boundary (after a step's tokens are appended, before
        the next executable launches).  A fleet thread that catches
        `ReplicaDead` reads `future.meta["gen_progress"]` to re-admit the
        request on a survivor with zero token loss.  The snapshot is a
        fresh dict + fresh list assigned in ONE dict-item store
        (GIL-atomic), so a concurrent reader sees either this boundary or
        an earlier complete one — never a torn list.  `rng_uid` rides
        along so the survivor continues the exact sampling stream; the
        token COUNT is the RNG state (keys fold (rng_uid, index))."""
        if not self.config.progress_meta:
            return
        st.req.future.meta["gen_progress"] = {
            "tokens": list(st.tokens), "rng_uid": st.req.rng_uid}

    def set_step_hook(self, fn) -> None:
        """Chaos instrumentation: arm `fn(kind, count)` to fire from the
        engine thread after every decode step (`kind="decode"`, count =
        cumulative steps) and every prefill-chunk fold
        (`kind="prefill_chunk"`, count = cumulative folds) — each a
        settle-safe boundary, so a hook that kills this replica models
        the worst honest mid-stream death.  Pass None to disarm.  A
        raising hook is disarmed, never fails the request."""
        self._step_hook = fn

    def _fire_step_hook(self, kind: str) -> None:
        fn = self._step_hook
        if fn is None:
            return
        try:
            fn(kind, self._steps if kind == "decode" else self._chunk_folds)
        except Exception:
            _log.exception("generation step hook raised; disarmed")
            self._step_hook = None

    def _retire(self, lane: _Lane, s: int, reason: str, tr) -> None:
        st = lane.slots[s]
        req = st.req
        lane.slots[s] = None
        lane.active_np[s] = False
        lane.spec_stale[s] = False
        lane.free.append(s)
        self._release_blocks(lane, s)
        now = time.perf_counter()
        snap_version = self.registry.active_version
        if reason == "error":
            self.metrics.on_nonfinite()
            if tr is not None:
                tr.instant("gen.nonfinite", cat="generation", cid=req.cid)
            from bigdl_tpu.serving.runtime import NonFiniteOutput

            req.future.set_error(NonFiniteOutput(
                f"non-finite logits while generating (model version "
                f"{snap_version!r}, bucket {lane.bucket})"))
            self.metrics.set_active(self._n_active())
            return
        n_gen = st.generated
        n_new = n_gen - req.resume_n  # emitted on THIS engine
        tokens = st.tokens
        ttft_ms = (st.t_first - req.t_submit) * 1e3
        meta = {
            "cid": req.cid, "version": snap_version, "bucket": lane.bucket,
            "finish_reason": reason,
            "prompt_tokens": int(req.prompt.size) - req.resume_n,
            "tokens": n_gen, "ttft_ms": round(ttft_ms, 3),
            "ms_per_token": round(st.step_ms_sum / max(1, n_new - 1), 3)
            if n_new > 1 else None,
        }
        if req.resume_n:
            meta["resumed_tokens"] = req.resume_n
            meta["recovered"] = True
            meta["recovery_prefix_tokens"] = req.hit_tokens
        self.metrics.on_complete((now - req.t_submit) * 1e3, n_gen)
        self.metrics.set_active(self._n_active())
        if tr is not None:
            tr.instant("gen.complete", cat="generation", cid=req.cid,
                       tokens=n_gen, reason=reason)
        req.future.meta = meta
        req.future.set_result(GenerationResult(np.asarray(tokens, np.int32),
                                               meta))

    # -- main loop ---------------------------------------------------------

    def _n_prefilling(self) -> int:
        return sum(len(lane.prefilling) for lane in self._lanes.values())

    def _loop(self) -> None:
        while True:
            with self._cond:
                while (not self._closed and not self._pending
                       and self._n_active() == 0
                       and self._n_prefilling() == 0):
                    self._cond.wait(0.05)
                if self._closed and self._abort:
                    break
                if (self._closed and not self._pending
                        and self._n_active() == 0
                        and self._n_prefilling() == 0):
                    break
            tr = _obs.tracer()
            try:
                snap = self.registry.active()
                self._admit(snap, tr)
                for lane in self._lanes.values():
                    # one chunk of the oldest mid-prefill prompt, THEN the
                    # lane's decode step: short-request TTFT under a long
                    # admission is bounded by one chunk, not one prompt
                    if lane.prefilling:
                        self._advance_prefill(lane, snap, tr)
                    if lane.n_active:
                        self._decode_lane(lane, snap, tr)
            except BaseException as e:  # noqa: BLE001 — fail loudly, keep serving
                self._fail_inflight(e)
        # abort path: fail everything still queued or in-flight
        self._fail_inflight(ServingClosed("generation engine shut down"))
        self._drained.set()

    def _fail_inflight(self, err: BaseException) -> None:
        with self._cond:
            pending, self._pending = list(self._pending), deque()
        for req in pending:
            self.metrics.on_reject("shutdown")
            if not req.future.done():
                req.future.set_error(err)
        for lane in self._lanes.values():
            lane.prefilling.clear()
            lane.spec_stale[:] = False
            for s in range(self.config.slots):
                st = lane.slots[s]
                if st is not None:
                    lane.slots[s] = None
                    lane.active_np[s] = False
                    lane.free.append(s)
                    self._release_blocks(lane, s)
                    if not st.req.future.done():
                        st.req.future.set_error(err)
        self._long_inflight = 0
        self.metrics.set_active(0)

    # -- versioning / lifecycle -------------------------------------------

    def swap(self, version: str, params: Any, state: Any = None) -> None:
        """Hot-swap: AOT-warm prefill+decode for the new version (off the
        decode path), then activate atomically.  In-flight requests keep
        their KV cache and continue on the new weights from their next
        token; `drain()` first for strict per-request version pinning."""
        self.registry.register(version, params,
                               state if state is not None else {})
        self.metrics.on_swap()

    def drain(self, timeout: Optional[float] = 60.0) -> None:
        """Block until every admitted request has retired."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        # poll loop: a stale lock-free read of the pending deque only
        # delays exit by one 2ms tick; taking _cond here would contend
        # with the scheduler thread for nothing
        while self._pending or self._n_active() or self._n_prefilling():  # tpu-lint: disable=unguarded-state
            if deadline is not None and time.perf_counter() > deadline:
                raise TimeoutError("generation engine did not drain in time")
            time.sleep(0.002)

    @property
    def active_version(self) -> Optional[str]:
        return self.registry.active_version

    def export_metrics(self, step: Optional[int] = None) -> dict:
        snap = self.metrics.snapshot()
        if self.summary is not None:
            if step is None:
                step = self._export_step
            self._export_step = step + 1
            self.metrics.export(self.summary, step)
        return snap

    def close(self, drain: bool = True, timeout: Optional[float] = 60.0) -> None:
        with self._cond:
            self._closed = True
            if not drain:
                self._abort = True
            self._cond.notify_all()
        if not self._drained.wait(timeout):
            raise TimeoutError("generation engine did not drain in time")
        self._thread.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
