"""On-device token sampling: greedy, temperature, top-k.

Sampling runs INSIDE the decode executable (bigdl_tpu/generation/engine.py
jits it together with the forward), so the per-step host traffic is one
(slots,) int32 read-back — never the (slots, vocab) logits.  Greedy vs
temperature is a traced `jnp.where`, not a Python branch: per-slot
temperatures ride in as an array, so requests with different sampling
settings share one executable and continuous batching never recompiles.
`top_k` is the one STATIC knob (lax.top_k needs a static k); it is fixed
per engine config, keeping the executable set at buckets x {prefill,
decode}.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.attention import NEG_INF


def apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the k highest logits per row (k static; k<=0 = off)."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    thresh = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits >= thresh, logits, NEG_INF)


def sample_tokens(logits: jax.Array, key: jax.Array,
                  temperatures: jax.Array, *, top_k: int = 0) -> jax.Array:
    """One token per row of (B, V) logits -> (B,) int32.

    Per-row `temperatures` (B,): 0 = greedy (argmax), >0 = softmax sample
    at that temperature over the (optionally top-k-masked) logits.  Both
    paths are always computed and selected with `where`, so a batch mixing
    greedy and sampled requests stays a single executable.
    """
    greedy = jnp.argmax(logits, axis=-1)
    temps = jnp.asarray(temperatures)
    safe = jnp.where(temps > 0, temps, 1.0)[:, None]
    sampled = jax.random.categorical(key, apply_top_k(logits, top_k) / safe)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def request_key(seed, uid, gen) -> jax.Array:
    """The per-token sampling key: fold the request's rng stream id
    (`uid`) and the token's GENERATED INDEX (`gen`, 0 = the prefill
    token) into the engine seed.  Keying on (uid, index) instead of the
    engine's global step makes sampled decoding RESUMABLE: a request
    re-admitted on another engine with the same seed/uid/index draws
    bitwise the same tokens regardless of slot placement, batch
    interleaving, or how many steps the new engine has run (the fleet
    failover parity bar).  `jax.random.categorical` derives its gumbel
    noise from the same counter stream for (1, V) and per-row (V,)
    shapes, so the prefill draw at index g and a decode draw at index g
    are bitwise interchangeable."""
    return jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(seed), uid), gen)


def request_keys(seed, uids: jax.Array, gens: jax.Array) -> jax.Array:
    """Vectorized `request_key` over per-slot (B,) uid/index arrays."""
    base = jax.random.PRNGKey(seed)
    return jax.vmap(
        lambda u, g: jax.random.fold_in(jax.random.fold_in(base, u), g)
    )(uids, gens)


def sample_tokens_per_slot(logits: jax.Array, keys: jax.Array,
                           temperatures: jax.Array, *,
                           top_k: int = 0) -> jax.Array:
    """`sample_tokens` with an independent key PER ROW (B, 2) — the
    decode executable's form: each slot draws from its own request
    stream, so retirement/admission churn in the other slots never
    perturbs a request's sampled sequence."""
    greedy = jnp.argmax(logits, axis=-1)
    temps = jnp.asarray(temperatures)
    safe = jnp.where(temps > 0, temps, 1.0)[:, None]
    masked = apply_top_k(logits, top_k) / safe
    sampled = jax.vmap(jax.random.categorical)(keys, masked)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)


def adjusted_log_probs(logits: jax.Array, temperatures: jax.Array, *,
                       top_k: int = 0) -> jax.Array:
    """Log-probs of the distribution `sample_tokens` actually draws from:
    top-k mask, then temperature, then log-softmax.  `logits` is (..., V)
    with `temperatures` broadcast over the leading axes; rows at
    temperature 0 divide by 1 (their argmax — the greedy pick — is
    unaffected).  Speculative decoding needs this explicitly: the
    accept test compares the DRAFT proposal distribution against the
    TARGET sampling distribution, and both must be exactly what the
    non-speculative path would have sampled from for the output
    distribution to be provably unchanged."""
    temps = jnp.asarray(temperatures)
    safe = jnp.where(temps > 0, temps, 1.0)
    safe = safe.reshape(safe.shape + (1,) * (logits.ndim - safe.ndim))
    return jax.nn.log_softmax(apply_top_k(logits, top_k) / safe, axis=-1)


def spec_accept(p_logp: jax.Array, q_logp: jax.Array, draft: jax.Array,
                temperatures: jax.Array, key: jax.Array, *,
                top_k: int = 0) -> "tuple[jax.Array, jax.Array]":
    """Vectorized speculative accept/resample (runs inside the verify
    executable, one call per decode round).

    `p_logp` (B, k+1, V): the TARGET's log-probs from the batched verify
    forward — row i is the distribution of the token after accepting the
    first i draft tokens.  `q_logp` (B, k, V): the DRAFT's raw log-probs
    that proposed `draft` (B, k) int32.  Returns `(n_acc, emitted)`:
    per row, the count of accepted draft tokens (longest accepted
    prefix) and the ONE extra token the target always contributes —
    so every round emits `n_acc + 1` tokens.

    Greedy rows (temperature 0) accept while the draft matches the
    target argmax and emit the argmax at the first mismatch (or the
    bonus row after a full accept): BITWISE the sequence the
    non-speculative greedy loop produces.  Sampled rows run the
    standard rejection scheme on the tempered/top-k'd distributions
    p', q': accept d_i iff u < p'(d_i)/q'(d_i); on rejection resample
    from the residual max(p' - q', 0) renormalized, on full accept
    sample row k of p' — the textbook construction whose marginal
    equals sampling from p' directly."""
    b, k1, _ = p_logp.shape
    k = k1 - 1
    temps = jnp.asarray(temperatures)
    greedy = jnp.argmax(p_logp, axis=-1).astype(jnp.int32)      # (B, k+1)
    p_adj = adjusted_log_probs(p_logp, temps, top_k=top_k)      # (B, k+1, V)
    q_adj = adjusted_log_probs(q_logp, temps, top_k=top_k)      # (B, k, V)
    pd = jnp.take_along_axis(p_adj[:, :k], draft[..., None], axis=-1)[..., 0]
    qd = jnp.take_along_axis(q_adj, draft[..., None], axis=-1)[..., 0]
    key_u, key_r = jax.random.split(key)
    u = jax.random.uniform(key_u, (b, k))
    acc = jnp.where(temps[:, None] > 0,
                    jnp.log(u) < pd - qd,                       # u < p'/q'
                    draft == greedy[:, :k])
    prefix = jnp.cumprod(acc.astype(jnp.int32), axis=1)
    n_acc = prefix.sum(axis=1).astype(jnp.int32)                # (B,)
    # the +1 token: residual resample at the rejection row, or the bonus
    # row-k distribution when every draft token survived
    row = jnp.minimum(n_acc, k)
    p_row = jnp.take_along_axis(p_adj, row[:, None, None], axis=1)[:, 0]
    q_row = jnp.take_along_axis(
        q_adj, jnp.minimum(row, k - 1)[:, None, None], axis=1)[:, 0]
    resid = jnp.maximum(jnp.exp(p_row) - jnp.exp(q_row), 0.0)
    mass = resid.sum(axis=-1, keepdims=True)
    bonus = (n_acc == k)[:, None] | (mass <= 0.0)  # mass==0 only numerically
    dist = jnp.where(bonus, jnp.exp(p_row), resid)
    sampled = jax.random.categorical(key_r,
                                     jnp.log(jnp.maximum(dist, 1e-38)))
    g_row = jnp.take_along_axis(greedy, row[:, None], axis=1)[:, 0]
    emitted = jnp.where(temps > 0, sampled, g_row).astype(jnp.int32)
    return n_acc, emitted
