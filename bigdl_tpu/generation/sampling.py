"""On-device token sampling: greedy, temperature, top-k.

Sampling runs INSIDE the decode executable (bigdl_tpu/generation/engine.py
jits it together with the forward), so the per-step host traffic is one
(slots,) int32 read-back — never the (slots, vocab) logits.  Greedy vs
temperature is a traced `jnp.where`, not a Python branch: per-slot
temperatures ride in as an array, so requests with different sampling
settings share one executable and continuous batching never recompiles.
`top_k` is the one STATIC knob (lax.top_k needs a static k); it is fixed
per engine config, keeping the executable set at buckets x {prefill,
decode}.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.attention import NEG_INF


def apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Mask all but the k highest logits per row (k static; k<=0 = off)."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    thresh = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits >= thresh, logits, NEG_INF)


def sample_tokens(logits: jax.Array, key: jax.Array,
                  temperatures: jax.Array, *, top_k: int = 0) -> jax.Array:
    """One token per row of (B, V) logits -> (B,) int32.

    Per-row `temperatures` (B,): 0 = greedy (argmax), >0 = softmax sample
    at that temperature over the (optionally top-k-masked) logits.  Both
    paths are always computed and selected with `where`, so a batch mixing
    greedy and sampled requests stays a single executable.
    """
    greedy = jnp.argmax(logits, axis=-1)
    temps = jnp.asarray(temperatures)
    safe = jnp.where(temps > 0, temps, 1.0)[:, None]
    sampled = jax.random.categorical(key, apply_top_k(logits, top_k) / safe)
    return jnp.where(temps > 0, sampled, greedy).astype(jnp.int32)
