"""bigdl_tpu.generation — TPU-native autoregressive inference.

The LLM-serving subsystem: ring-buffer KV caches at bucketed max lengths
(kvcache.py), on-device greedy/temperature/top-k sampling (sampling.py),
and a continuous-batching prefill/decode engine (engine.py) layered on the
serving stack's registry/hot-swap/AOT-warmup machinery.  See the module
docstrings and docs/serving.md "Autoregressive generation".

```python
from bigdl_tpu.generation import GenerationEngine

eng = GenerationEngine(model, params, buckets=(64, 256), slots=8,
                       temperature=0.0, eos_id=2)
out = eng.generate([5, 17, 99], max_new_tokens=32)   # GenerationResult
fut = eng.submit([5, 17], temperature=0.8)           # continuous batching
print(eng.export_metrics())                          # ttft / ms-per-token
eng.close()
```

Or attached to a live runtime so hot-swaps warm BOTH paths:
`rt.enable_generation(buckets=(64,), slots=8)`.
"""

from bigdl_tpu.generation.engine import (
    GenerationConfig,
    GenerationEngine,
    GenerationResult,
)
from bigdl_tpu.generation.kvcache import KVCache, alloc, insert
from bigdl_tpu.generation.sampling import apply_top_k, sample_tokens

__all__ = [
    "GenerationConfig",
    "GenerationEngine",
    "GenerationResult",
    "KVCache",
    "alloc",
    "apply_top_k",
    "insert",
    "sample_tokens",
]
