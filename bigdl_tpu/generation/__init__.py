"""bigdl_tpu.generation — TPU-native autoregressive inference.

The LLM-serving subsystem: ring-buffer KV caches at bucketed max lengths
(kvcache.py) or a shared paged block pool (pagedkv.py, env
`BIGDL_TPU_PAGED_KV`), optional int8 KV quantization
(`BIGDL_TPU_KV_DTYPE=int8`), on-device greedy/temperature/top-k sampling
(sampling.py), and a continuous-batching prefill/decode engine
(engine.py) layered on the serving stack's registry/hot-swap/AOT-warmup
machinery.  Chunked prefill (`BIGDL_TPU_PREFILL_CHUNK`) interleaves long
prompt ingestion with in-flight decode; speculative decoding
(`BIGDL_TPU_SPEC_DECODE` + a draft model) runs a draft-verify lane with
a provably unchanged output distribution (sampling.spec_accept); the
content-addressed prefix store (prefixcache.py, `BIGDL_TPU_PREFIX_CACHE`)
shares refcounted immutable pool blocks across requests with a common
prompt head, so chunked prefill skips the warm chunks entirely.  See
the module docstrings and docs/serving.md "Autoregressive generation" /
"Paged KV & quantized cache" / "Chunked prefill & speculative
decoding" / "Prefix caching".

```python
from bigdl_tpu.generation import GenerationEngine

eng = GenerationEngine(model, params, buckets=(64, 256), slots=8,
                       temperature=0.0, eos_id=2)
out = eng.generate([5, 17, 99], max_new_tokens=32)   # GenerationResult
fut = eng.submit([5, 17], temperature=0.8)           # continuous batching
print(eng.export_metrics())                          # ttft / ms-per-token
eng.close()
```

Or attached to a live runtime so hot-swaps warm BOTH paths:
`rt.enable_generation(buckets=(64,), slots=8)`.
"""

from bigdl_tpu.generation.engine import (
    GenerationConfig,
    GenerationEngine,
    GenerationResult,
)
from bigdl_tpu.generation.kvcache import KVCache, alloc, insert, slot_view
from bigdl_tpu.generation.pagedkv import (
    DEFAULT_BLOCK_SIZE,
    BlockPool,
    PagedKVCache,
    blocks_for,
)
from bigdl_tpu.generation.prefixcache import (
    PrefixStore,
    block_addr,
    world_key,
)
from bigdl_tpu.generation.sampling import (
    adjusted_log_probs,
    apply_top_k,
    sample_tokens,
    spec_accept,
)

__all__ = [
    "DEFAULT_BLOCK_SIZE",
    "BlockPool",
    "GenerationConfig",
    "GenerationEngine",
    "GenerationResult",
    "KVCache",
    "PagedKVCache",
    "PrefixStore",
    "adjusted_log_probs",
    "alloc",
    "apply_top_k",
    "block_addr",
    "blocks_for",
    "insert",
    "sample_tokens",
    "slot_view",
    "spec_accept",
    "world_key",
]
