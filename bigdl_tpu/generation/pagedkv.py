"""Paged KV allocator: one shared HBM block pool for every decode lane.

The ring-buffer cache (kvcache.py) reserves worst-case `(slots, capacity)`
HBM per bucket lane — a 4-slot 256-bucket lane holds 1024 tokens of K/V
whether its slots serve 12-token chat turns or full-context documents.
This module is the vLLM/PagedAttention answer on TPU terms: K/V live in
fixed-size BLOCKS (`block_size` tokens x n_head x head_dim) inside ONE
pool shared by all lanes, and each slot owns an int32 BLOCK TABLE padded
to its bucket's max block count.  Shape discipline is unchanged — the
table shape per bucket is static, so the executable set stays
`len(buckets) x 2` — but HBM is claimed per ~block_size tokens actually
resident instead of per worst-case bucket.

Two halves:

  * `PagedKVCache` — the device pytree (pool arrays + block tables +
    lengths) that flows through jit exactly like `KVCache`.  Block 0 is
    the TRASH BLOCK: unclaimed table entries point at it, so the
    fixed-shape decode step can scatter pad/inactive writes somewhere
    harmless and gather finite (masked-out) values for unclaimed tail
    columns.  Nothing ever reads block 0 unmasked, which is what keeps
    paged-on vs paged-off bitwise-equal at fp32.
  * `BlockPool` — the HOST-side allocator: a free list over block ids
    with `claim`/`release` on slot admit/EOS and a logical `reserve`
    taken at admission for a request's worst-case block count, so a
    mid-decode claim can never fail (claims are lazy, reservations are
    conservative; the gap between the two is what the gauges show).

Int8 KV rides along: pass `dtype=jnp.int8` and the pool carries
per-token per-head fp32 scale planes (`k_scale`/`v_scale`), quantized at
write and dequantized fused into the decode attention read
(nn/attention.py).
"""

from __future__ import annotations

import threading
from typing import (Callable, Dict, List, NamedTuple, Optional, Sequence,
                    Tuple)

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BLOCK_SIZE = 16


def _leaf_nbytes(*leaves) -> int:
    return sum(int(np.prod(l.shape)) * l.dtype.itemsize
               for l in leaves if l is not None)


class PagedKVCache(NamedTuple):
    """Per-lane view of the shared block pool (a jax pytree).

    `k`/`v` are the POOL: (n_layer, n_blocks, block_size, n_head,
    head_dim), shared by every lane.  `block_tables` is this lane's
    (slots, max_blocks) int32 map from ring-block index to pool block id
    (0 = trash block for unclaimed entries); `lengths` counts total
    tokens written per slot, exactly like `KVCache.lengths`.  The
    logical per-slot capacity is `max_blocks * block_size`, and ring
    index `p % capacity` lives at block `idx // block_size`, offset
    `idx % block_size`.
    """

    k: jax.Array
    v: jax.Array
    block_tables: jax.Array  # (slots, max_blocks) int32 pool block ids
    lengths: jax.Array       # (slots,) int32 — total tokens written
    k_scale: Optional[jax.Array] = None  # (n_layer, n_blocks, block, n_head)
    v_scale: Optional[jax.Array] = None

    @property
    def n_layer(self) -> int:
        return self.k.shape[0]

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]

    @property
    def block_size(self) -> int:
        return self.k.shape[2]

    @property
    def slots(self) -> int:
        return self.block_tables.shape[0]

    @property
    def max_blocks(self) -> int:
        return self.block_tables.shape[1]

    @property
    def capacity(self) -> int:
        return self.max_blocks * self.block_size

    def window(self) -> jax.Array:
        return jnp.minimum(self.lengths, self.capacity)

    def nbytes(self) -> int:
        """Device bytes of the POOL (shared across lanes) plus this
        lane's table/lengths bookkeeping."""
        return _leaf_nbytes(self.k, self.v, self.k_scale, self.v_scale,
                            self.block_tables, self.lengths)

    def resident_nbytes(self) -> "Tuple[int, int]":
        """(logical, unique) resident K/V bytes across this lane's slots.

        Logical counts every slot's resident blocks independently; unique
        counts distinct pool block ids, so `logical / unique` is the
        prefix-sharing ratio (1.0 with no shared blocks).  Trash-block
        entries (id 0) are excluded from both.  Pulls the table/lengths
        mirrors to host — a reporting method, not a hot-path one."""
        tables = np.asarray(self.block_tables)
        lengths = np.asarray(self.lengths)
        n_layer, _, blk, n_head, head_dim = self.k.shape
        per_block = 2 * n_layer * blk * n_head * head_dim \
            * self.k.dtype.itemsize
        if self.k_scale is not None:
            per_block += 2 * n_layer * blk * n_head \
                * self.k_scale.dtype.itemsize
        logical = 0
        uniq: set = set()
        for s in range(tables.shape[0]):
            nb = min(blocks_for(min(int(lengths[s]), self.capacity), blk),
                     self.max_blocks)
            ids = [int(b) for b in tables[s, :nb] if int(b) != 0]
            logical += len(ids)
            uniq.update(ids)
        return logical * per_block, len(uniq) * per_block


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold `tokens` resident tokens."""
    return -(-int(tokens) // int(block_size))


def slot_view(cache: PagedKVCache, slot, length) -> PagedKVCache:
    """Single-slot view for a k-token append resuming at `length` tokens
    written: the slot's block-table row is sliced out (the pool arrays
    are shared, so no K/V moves) and `lengths` pinned — the paged twin
    of `kvcache.slot_view`, used by the chunked-prefill executable.
    Writes through the view scatter into the slot's claimed pool blocks
    (unclaimed entries hit the trash block); merge back by adopting the
    returned pool arrays and setting the lane's `lengths[slot]`.

    Rollback after a rejected speculative suffix is, as with the ring,
    just a shorter `lengths` — claimed blocks stay claimed (still
    covered by the admission reservation) and are rewritten in place by
    subsequent sequential appends, so the BlockPool leak accounting is
    untouched by any accept/reject pattern."""
    row = jax.lax.dynamic_slice_in_dim(cache.block_tables, slot, 1, axis=0)
    return cache._replace(block_tables=row,
                          lengths=jnp.asarray(length, jnp.int32)[None])


class BlockPool:
    """Host-side allocator over the shared device block pool.

    Block 0 is reserved as the trash block and never handed out, so
    `n_allocatable = n_blocks - 1`.  `reserve(n)` is the ADMISSION-time
    logical budget (a request's worst-case resident blocks,
    `ceil(min(bucket, prompt + max_new) / block_size)`); `claim(n)` is
    the lazy physical allocation as the ring head actually crosses a
    block boundary.  Because every claim is covered by a prior
    reservation, `claim` cannot fail mid-decode — admission is the only
    place that can run out, and it backpressures there.  Thread-safe:
    the engine loop and `export_metrics` callers may race.

    Blocks are REFCOUNTED so the prefix store (prefixcache.py) can map
    one immutable block into several slots: `claim` hands out blocks at
    refcount 1, `addref` pins an extra owner, and `release` only returns
    a block to the free list when the last owner lets go — slot retire
    paths call the same `release` whether a block was private or shared.
    The reserve gate discounts shared blocks (refcount >= 2): a shared
    block is pinned by the store for as long as any slot maps it, so no
    reservation will ever need to claim it again, and counting it
    against the budget would make a warm pool reject requests it can
    serve.  Invariant: claims stay fail-safe because
    `sum(reservations) <= n_allocatable - blocks_shared` at every grant,
    and store-held idle blocks (refcount 1, no slot) are reclaimed on
    demand via the `set_reclaim` hook before a claim is allowed to fail.
    """

    def __init__(self, n_layer: int, n_blocks: int, block_size: int,
                 n_head: int, head_dim: int, dtype=jnp.float32):
        if n_blocks < 2:
            raise ValueError(f"pool needs >= 2 blocks (1 is the trash "
                             f"block), got {n_blocks}")
        self.block_size = int(block_size)
        shape = (n_layer, n_blocks, block_size, n_head, head_dim)
        self.k = jax.device_put(jnp.zeros(shape, dtype))
        self.v = jax.device_put(jnp.zeros(shape, dtype))
        self.k_scale = self.v_scale = None
        if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
            sshape = (n_layer, n_blocks, block_size, n_head)
            self.k_scale = jax.device_put(jnp.zeros(sshape, jnp.float32))
            self.v_scale = jax.device_put(jnp.zeros(sshape, jnp.float32))
        # plain (non-reentrant) lock, and a LEAF in the global lock
        # order: no pool method calls out while holding it — a claim
        # shortfall invokes the reclaim hook with the lock RELEASED, so
        # the hook's store-lock -> release() path nests store -> pool,
        # never pool -> store (lockdep enforces the DAG at runtime)
        self._lock = threading.Lock()
        # LIFO free list: recently-released blocks are re-claimed first,
        # keeping the hot working set compact in the pool
        self._free: List[int] = list(range(n_blocks - 1, 0, -1))
        self._reserved = 0
        self._refs: Dict[int, int] = {}  # block id -> owner count
        self._reclaim: Optional[Callable[[int], int]] = None

    # -- sizing ------------------------------------------------------------

    @property
    def n_blocks(self) -> int:
        return int(self.k.shape[1])

    @property
    def n_allocatable(self) -> int:
        return self.n_blocks - 1  # block 0 is the trash block

    @property
    def blocks_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def blocks_reserved(self) -> int:
        with self._lock:
            return self._reserved

    @property
    def blocks_shared(self) -> int:
        """Blocks with more than one owner (store + >=1 slot, or two
        slots riding one prefix) — the `kv_blocks_shared` gauge."""
        with self._lock:
            return sum(1 for c in self._refs.values() if c >= 2)

    def nbytes(self) -> int:
        return _leaf_nbytes(self.k, self.v, self.k_scale, self.v_scale)

    def bytes_per_token(self) -> int:
        """HBM bytes per resident token across all layers (the
        resident-tokens-per-byte denominator for the int8 A/B)."""
        n_layer, _, blk, n_head, head_dim = self.k.shape
        per = 2 * n_layer * n_head * head_dim * self.k.dtype.itemsize
        if self.k_scale is not None:
            per += 2 * n_layer * n_head * self.k_scale.dtype.itemsize
        return per

    # -- allocation --------------------------------------------------------

    def set_reclaim(self, cb: Optional[Callable[[int], int]]) -> None:
        """Install the claim-shortfall hook: `cb(n)` must try to free at
        least `n` blocks (the prefix store evicts idle refcount-1
        entries) and return how many it released.  Called WITHOUT the
        pool lock held, on the claiming thread: the hook may take its
        own lock and call `release` freely, and the acquired-before
        order stays store -> pool everywhere."""
        with self._lock:
            self._reclaim = cb

    def reserve(self, n: int) -> bool:
        """Logically reserve `n` blocks at admission; False = pool budget
        exhausted (caller keeps the request queued).  Shared blocks
        (refcount >= 2) are discounted from the budget: they are pinned
        resident already, so a request riding them reserves only its
        COLD blocks — the caller subtracts the hit prefix before calling.
        The published-but-still-private overlap (a slot's own blocks the
        store just pinned) double-counts against the budget until that
        slot retires; conservative, never unsafe."""
        with self._lock:
            shared = sum(1 for c in self._refs.values() if c >= 2)
            if self._reserved + n > self.n_allocatable - shared:
                return False
            self._reserved += n
            return True

    def unreserve(self, n: int) -> None:
        with self._lock:
            self._reserved -= n
            assert self._reserved >= 0, "unreserve underflow"

    def claim(self, n: int = 1) -> List[int]:
        """Physically allocate `n` block ids at refcount 1.  A shortfall
        first asks the reclaim hook to evict idle store-held blocks;
        raising after that is impossible while every claim is
        reservation-covered (reservations are granted against
        `n_allocatable - blocks_shared`, and non-shared resident blocks
        are either reservation-covered or reclaimable).

        The hook runs with the pool lock RELEASED (it takes the store
        lock and calls back into `release`); claims are engine-thread-
        only and reservation-covered, so the drop-and-retake window
        cannot be raced into a false exhaustion."""
        with self._lock:
            shortfall = n - len(self._free)
            reclaim = self._reclaim
        if shortfall > 0 and reclaim is not None:
            reclaim(shortfall)
        with self._lock:
            if len(self._free) < n:
                raise RuntimeError(
                    f"block pool exhausted: want {n}, free {len(self._free)}"
                    " (claim without a covering reservation?)")
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
            return out

    def addref(self, ids: Sequence[int]) -> None:
        """Pin an extra owner on already-claimed blocks (the prefix
        store on publish; the engine when mapping a hit into a slot)."""
        with self._lock:
            for b in ids:
                assert b in self._refs, f"addref of unclaimed block {b}"
                self._refs[b] += 1

    def refcount(self, b: int) -> int:
        with self._lock:
            return self._refs.get(int(b), 0)

    def release(self, ids: Sequence[int]) -> None:
        """Drop one owner per id; a block returns to the free list only
        when its last owner releases it (shared prefixes just decrement)."""
        with self._lock:
            for b in ids:
                assert 0 < b < self.n_blocks, f"bad block id {b}"
                assert self._refs.get(b, 0) > 0, \
                    f"double release of block {b}"
                self._refs[b] -= 1
                if self._refs[b] == 0:
                    del self._refs[b]
                    self._free.append(b)

    # -- device-side sync --------------------------------------------------

    def update_from(self, cache: PagedKVCache) -> None:
        """Adopt the pool arrays a compiled step returned (the engine
        threads ONE pool through every lane's executables)."""
        self.k, self.v = cache.k, cache.v
        if cache.k_scale is not None:
            self.k_scale, self.v_scale = cache.k_scale, cache.v_scale

    def lane_view(self, block_tables: jax.Array,
                  lengths: jax.Array) -> PagedKVCache:
        return PagedKVCache(k=self.k, v=self.v, block_tables=block_tables,
                            lengths=lengths, k_scale=self.k_scale,
                            v_scale=self.v_scale)
