"""Process-global prefix cache: content-addressed, copy-on-write paged KV.

At serving scale most prompts share a head — system prompts, few-shot
headers, RAG boilerplate — and the engine re-prefills every byte of it
per request.  The paged allocator already stores K/V in fixed immutable
blocks behind per-slot block tables, and chunked prefill already folds
prompts in fixed-width chunks; this module is the missing join (vLLM's
shared-prefix block pool, SGLang's RadixAttention turned into cache
hits): a host-side store mapping BLOCK-ALIGNED token prefixes to pool
block ids, so a new admission maps the warm prefix into its table and
folds only the cold suffix.

Content addressing (the compilecache discipline, keys.py): each full
block of a prompt hashes to a CHAINED digest over

    world fingerprint  (model version + param tree signature + kv dtype
                        + block size — everything that decides whether
                        cached K/V bytes are valid)
  + parent address     (the digest of the preceding block, so an address
                        pins the entire prefix, not just its own tokens)
  + the block's tokens

A hit is valid only in the exact KV world it was written under — a
hot-swap changes the fingerprint and every old entry goes cold by
construction (wrong-world entries are unreachable BY KEY and evicted
preferentially).  Addresses deliberately exclude the bucket: K/V at a
position depend only on the identical token prefix and absolute RoPE
positions, so one cached block serves every lane.

Copy-on-write is REUSE-UNTIL-WRITE, implemented without any new
executable: shared blocks are mapped read-only into the table prefix,
admission seeds chunk progress past them, and every subsequent write —
the cold prefill suffix, decode appends, speculative overhang — lands
at positions past the mapped prefix, i.e. in PRIVATE blocks claimed the
normal lazy way.  The first divergent block is simply never mapped: its
tokens fold with the cold suffix into a fresh block (recompute-on-write
at block granularity), so the compiled step functions never see a "fork
this block" path and the pinned executable set is unchanged.

Eviction is refcount-0 LRU under a byte budget (`BIGDL_TPU_PREFIX_CACHE`
accepts on/off or a byte budget like `256M`;
`BIGDL_TPU_PREFIX_CACHE_MAX_BLOCKS` caps block count): only idle leaves
— refcount 1 (store-only) and no cached children — are evictable, so a
block a slot still maps can never be yanked, and a claim shortfall in
`BlockPool.claim` reclaims idle entries on demand before it may fail.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu import obs as _obs
from bigdl_tpu.generation.pagedkv import BlockPool

_ROOT = "root"  # parent address of a prompt's first block


def world_key(version: str, params_sig: Any, kv_dtype: str,
              block_size: int) -> str:
    """Fingerprint of the KV world cached blocks were written under.

    Mirrors compilecache key discipline: everything that decides whether
    the cached BYTES are still the bytes a fresh prefill would write
    goes into the digest — model version and param tree signature (a
    swap invalidates), kv dtype (int8 vs fp32 pools hold different
    bytes), block size (addresses chunk tokens per block).  Buckets are
    deliberately absent: absolute positions make blocks bucket-portable.
    """
    payload = json.dumps(
        {"v": 1, "version": str(version), "params": repr(params_sig),
         "kv_dtype": str(kv_dtype), "block": int(block_size)},
        sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()


def block_addr(world: str, parent: Optional[str],
               tokens: np.ndarray) -> str:
    """Chained content address of one full block: world fingerprint +
    parent address + this block's tokens.  The parent link makes the
    address a commitment to the ENTIRE prefix — two prompts sharing
    tokens [B..2B) but differing in [0..B) hash to different addresses
    for their second block."""
    h = hashlib.sha256()
    h.update(world.encode())
    h.update(b"\x00")
    h.update((parent or _ROOT).encode())
    h.update(b"\x00")
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.hexdigest()


class _Entry:
    __slots__ = ("addr", "block_id", "parent", "world", "children", "seq")

    def __init__(self, addr: str, block_id: int, parent: Optional[str],
                 world: str, seq: int):
        self.addr = addr
        self.block_id = block_id
        self.parent = parent
        self.world = world
        self.children = 0  # cached entries whose parent is this addr
        self.seq = seq     # LRU clock at last touch


class PrefixStore:
    """Host-side content-addressed map from block-aligned token prefixes
    to resident pool blocks.

    The store owns ONE refcount on every cached block (taken at publish
    via `pool.addref`, dropped at eviction via `pool.release`); slots
    mapping a hit take their own ref per block, so `pool.blocks_shared`
    (refcount >= 2) counts exactly the store blocks some slot currently
    rides.  All mutation happens on the engine scheduler thread; the
    internal lock only guards metric/snapshot readers.

    Lock order: store lock -> pool lock, everywhere.  Publish/evict
    nest `pool.addref`/`pool.release` under the store lock, and the
    claim-shortfall reclaim hook runs with the pool lock RELEASED
    (`BlockPool.claim` drops it before invoking the hook), so the
    acquired-before graph is a DAG — both locks are plain non-reentrant
    Locks and the runtime lockdep sanitizer verifies the order.
    """

    def __init__(self, pool: BlockPool, max_bytes: Optional[int] = None,
                 max_blocks: Optional[int] = None):
        self.pool = pool
        self.block_size = pool.block_size
        per_block = pool.bytes_per_token() * pool.block_size
        cap = pool.n_allocatable
        if max_blocks is not None:
            cap = min(cap, int(max_blocks))
        if max_bytes is not None:
            cap = min(cap, int(max_bytes) // per_block)
        self.cap_blocks = max(0, cap)
        self._block_bytes = per_block
        # plain lock; always taken BEFORE the pool lock (never re-entered:
        # _evict_idle is caller-holds-lock by convention)
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._world: Optional[str] = None
        self._seq = 0
        self.evictions = 0
        self.publishes = 0

    # -- world -------------------------------------------------------------

    def set_world(self, world: str) -> None:
        """Pin the current KV world (call on every version activation).
        Idle entries from other worlds are swept eagerly; entries still
        mapped by in-flight slots linger unreachable-by-key until their
        slots retire, then fall to the preferential dead-world eviction.
        """
        with self._lock:
            if world == self._world:
                return
            self._world = world
            self._evict_idle(lambda e: e.world != world, limit=None)

    @property
    def world(self) -> Optional[str]:
        with self._lock:
            return self._world

    # -- lookup / publish --------------------------------------------------

    def lookup(self, tokens: np.ndarray) -> List[int]:
        """Longest cached block-prefix of `tokens`: walks the address
        chain over full blocks and returns the matched pool block ids
        (possibly empty).  Touches matched entries' LRU clocks.  The ids
        stay valid until the next claim/publish on the engine thread —
        the caller (admission) pins them with `pool.addref` immediately,
        with no allocation in between."""
        B = self.block_size
        out: List[int] = []
        with self._lock:
            if self._world is None:
                return out
            self._seq += 1
            parent: Optional[str] = None
            for i in range(int(tokens.size) // B):
                addr = block_addr(self._world, parent,
                                  tokens[i * B:(i + 1) * B])
                ent = self._entries.get(addr)
                if ent is None:
                    break
                ent.seq = self._seq
                out.append(ent.block_id)
                parent = addr
        return out

    def publish(self, tokens: np.ndarray, n_tokens: int,
                block_ids: Sequence[int]) -> int:
        """Offer the first `n_tokens` (floor to full blocks) of a folded
        prompt to the store; `block_ids` are the owning slot's claimed
        blocks in table order.  New entries addref their block (the
        store's own pin); blocks whose address is already cached keep
        the existing entry — the slot's duplicate stays private and
        frees at retire.  Stops early (returns entries added so far)
        when the budget has no evictable room."""
        B = self.block_size
        added = 0
        with self._lock:
            if self._world is None:
                return 0
            self._seq += 1
            parent: Optional[str] = None
            for i in range(int(n_tokens) // B):
                addr = block_addr(self._world, parent,
                                  tokens[i * B:(i + 1) * B])
                ent = self._entries.get(addr)
                if ent is not None:
                    ent.seq = self._seq
                    parent = addr
                    continue
                if len(self._entries) >= self.cap_blocks:
                    self._evict_idle(
                        lambda e: True,
                        limit=len(self._entries) - self.cap_blocks + 1)
                    if len(self._entries) >= self.cap_blocks:
                        break  # everything resident is pinned; no room
                self.pool.addref([block_ids[i]])
                self._entries[addr] = _Entry(addr, int(block_ids[i]),
                                             parent, self._world, self._seq)
                if parent is not None:
                    self._entries[parent].children += 1
                parent = addr
                added += 1
            if added:
                self.publishes += added
        return added

    # -- eviction ----------------------------------------------------------

    def _evictable(self, e: _Entry) -> bool:
        # idle leaf: no cached children and no slot maps it (the store's
        # own pin is the single remaining ref)
        return e.children == 0 and self.pool.refcount(e.block_id) == 1

    def _evict_idle(self, pred, limit: Optional[int]) -> int:
        """Evict up to `limit` idle-leaf entries matching `pred`,
        dead-world first, then least recently used.  Caller holds the
        store lock.  Returns blocks released to the pool."""
        freed = 0
        while limit is None or freed < limit:
            cand = [e for e in self._entries.values()
                    if pred(e) and self._evictable(e)]
            if not cand:
                break
            cand.sort(key=lambda e: (e.world == self._world, e.seq))
            take = cand if limit is None \
                else cand[:limit - freed]
            for e in take:
                del self._entries[e.addr]
                if e.parent is not None and e.parent in self._entries:
                    self._entries[e.parent].children -= 1
                self.pool.release([e.block_id])
                self.evictions += 1
                freed += 1
            _obs.registry().inc("generation/prefix_evictions", len(take))
            _obs.instant("gen.prefix_evict", cat="generation",
                         blocks=len(take),
                         resident=len(self._entries))
            # parents of evicted leaves may now be idle leaves: loop
        return freed

    def reclaim(self, n: int) -> int:
        """`BlockPool.set_reclaim` hook: free >= `n` blocks if possible
        by evicting idle entries (LRU).  Runs on the claiming thread
        with the pool lock NOT held (store -> pool order preserved)."""
        with self._lock:
            return self._evict_idle(lambda e: True, limit=max(1, int(n)))

    def clear(self) -> int:
        """Evict every idle entry (tests / explicit flush); entries
        still mapped by slots survive.  Returns blocks released."""
        with self._lock:
            return self._evict_idle(lambda e: True, limit=None)

    # -- reporting ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def nbytes(self) -> int:
        with self._lock:
            return len(self._entries) * self._block_bytes

    def block_ids(self) -> List[int]:
        with self._lock:
            return [e.block_id for e in self._entries.values()]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "cap_blocks": self.cap_blocks,
                "nbytes": len(self._entries) * self._block_bytes,
                "publishes": self.publishes,
                "evictions": self.evictions,
            }
