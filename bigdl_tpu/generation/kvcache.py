"""Ring-buffer KV cache for autoregressive decode.

No reference counterpart (the reference's only sequence model is LSTM/GRU
recurrence, nn/Recurrent.scala — its "state" is the recurrent hidden, not
an attention cache).  The TPU-native design constraint is SHAPE STABILITY:
XLA compiles one executable per shape, so the cache is a fixed-capacity
ring buffer allocated at a bucketed max length and every decode step runs
the exact same program regardless of how many tokens each request holds.

Layout: K/V are (n_layer, slots, capacity, n_head, head_dim) — layer-major
so `lax.scan` over the model's stacked blocks consumes the cache as a
scanned input, mirroring models/transformer.py's weight-stationary layout.
`lengths` (slots,) counts TOTAL tokens ever written per slot; the ring
index of position p is simply `p % capacity`, and a slot that outgrows its
bucket degrades to sliding-window attention over the last `capacity`
tokens instead of recompiling at a bigger shape.

The pytree is a NamedTuple, so it flows through jit/scan unchanged and a
whole cache update is one functional `.at[].set` per layer inside the
compiled step — never a host round-trip.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np


class KVCache(NamedTuple):
    """Per-model KV ring buffer (a jax pytree; see module docstring).

    With an int8 cache dtype the ring additionally carries per-token
    per-head fp32 scale planes (`k_scale`/`v_scale`): K/V rows are
    quantized symmetrically at write time and dequantized fused into the
    attention read (nn/attention.py), halving-plus HBM per resident
    token.  fp32/bf16 caches leave the scale fields None.
    """

    k: jax.Array        # (n_layer, slots, capacity, n_head, head_dim)
    v: jax.Array        # same shape as k
    lengths: jax.Array  # (slots,) int32 — total tokens written per slot
    k_scale: Optional[jax.Array] = None  # (n_layer, slots, capacity, n_head)
    v_scale: Optional[jax.Array] = None

    @property
    def n_layer(self) -> int:
        return self.k.shape[0]

    @property
    def slots(self) -> int:
        return self.k.shape[1]

    @property
    def capacity(self) -> int:
        return self.k.shape[2]

    def window(self) -> jax.Array:
        """Tokens currently resident per slot (= lengths until the ring
        wraps, then the sliding-window size `capacity`)."""
        return jnp.minimum(self.lengths, self.capacity)

    def nbytes(self) -> int:
        """Device bytes this cache pins in HBM (K + V + scales +
        bookkeeping) — the per-lane reservation the paged allocator
        (pagedkv.py) exists to shrink."""
        return sum(int(np.prod(l.shape)) * l.dtype.itemsize
                   for l in (self.k, self.v, self.lengths,
                             self.k_scale, self.v_scale)
                   if l is not None)


def alloc(n_layer: int, slots: int, capacity: int, n_head: int,
          head_dim: int, dtype=jnp.float32) -> KVCache:
    """Zeroed cache for `slots` concurrent requests of up to `capacity`
    resident tokens each.  `dtype=jnp.int8` allocates the quantized ring
    (int8 K/V + fp32 per-token per-head scales)."""
    shape = (n_layer, slots, capacity, n_head, head_dim)
    k_scale = v_scale = None
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        sshape = (n_layer, slots, capacity, n_head)
        k_scale = jnp.zeros(sshape, jnp.float32)
        v_scale = jnp.zeros(sshape, jnp.float32)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   lengths=jnp.zeros((slots,), jnp.int32),
                   k_scale=k_scale, v_scale=v_scale)


def slot_view(cache: KVCache, slot, length) -> KVCache:
    """Slice `slot` out of a lane cache as a single-slot `KVCache` whose
    `lengths` is pinned to `length` (total tokens already written) — the
    working view for a k-token append that RESUMES mid-ring: chunked
    prefill folds chunk i against `slot_view(cache, s, i*chunk)` and
    writes back with `insert`, so prompt ingestion never needs a
    capacity-sized fresh buffer per chunk.  Traced-index safe (`slot`
    and `length` may be jit scalars).

    Rollback is the degenerate append: because `lengths` alone decides
    where the next write lands and what the mask attends, rejecting a
    speculated suffix is `cache._replace(lengths=shorter)` — no K/V
    copy; the stale rows beyond `lengths` are masked until sequential
    writes overwrite them (engine.py's spec-decode verify relies on
    this)."""
    def take(a):
        if a is None:
            return None
        return jax.lax.dynamic_slice_in_dim(a, slot, 1, axis=1)

    return KVCache(k=take(cache.k), v=take(cache.v),
                   lengths=jnp.asarray(length, jnp.int32)[None],
                   k_scale=take(cache.k_scale), v_scale=take(cache.v_scale))


def insert(cache: KVCache, slot, src: KVCache, length) -> KVCache:
    """Write single-slot cache `src` (same capacity) into `slot` of
    `cache` and pin that slot's length to `length` (the REAL token count —
    a bucketed prefill runs padded to capacity, so `src.lengths` counts
    pad rows too).  Traced-index safe: runs inside jit with `slot` and
    `length` as scalars, so slot claim/free never triggers a recompile."""
    if src.k.shape[2] != cache.k.shape[2]:
        raise ValueError(
            f"capacity mismatch: inserting {src.k.shape[2]} into "
            f"{cache.k.shape[2]} (prefill and decode lanes must share a "
            "length bucket)")
    def upd(dst, src_arr):
        if dst is None:
            return None
        return jax.lax.dynamic_update_index_in_dim(dst, src_arr[:, 0], slot, 1)

    return KVCache(
        k=upd(cache.k, src.k),
        v=upd(cache.v, src.v),
        lengths=cache.lengths.at[slot].set(
            jnp.asarray(length, jnp.int32)),
        k_scale=upd(cache.k_scale, src.k_scale),
        v_scale=upd(cache.v_scale, src.v_scale))
