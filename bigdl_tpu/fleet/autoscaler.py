"""FleetAutoscaler: grow/retire replicas from signals the fleet already emits.

No new telemetry: the autoscaler reads the queues the router owns, the
per-tenant p99 the `ServingMetrics` histograms already track, and the
CompileMonitor's `compile/steady_recompiles` alarm counter — the
observability plane IS the control plane.

Decisions are hysteretic, the classic way:

  * GROW  after `grow_after` CONSECUTIVE high ticks (total queue depth ≥
    `high_queue_depth`, or worst-tenant p99 ≥ `high_p99_ms`) while below
    `max_replicas`.
  * RETIRE after `shrink_after` consecutive low ticks (depth ≤
    `low_queue_depth` and p99 healthy) while above `min_replicas` —
    VETOED whenever the steady-recompile alarm fired since the last
    tick: a fleet that is recompiling in steady state must not also
    churn replicas (retire→regrow would repeat the compiles the alarm
    is complaining about).
  * Every action starts a `cooldown_ticks` refractory window, and any
    neutral tick resets both streaks — oscillating load holds.

Config defaults come from `BIGDL_TPU_FLEET_*` env vars (docs/fleet.md
lists them) so a deployment tunes thresholds without code.  `tick()` is
a pure, synchronous decision step driven by an injectable `signals_fn`
— tests feed deterministic signal sequences and assert the decision
trace; `start()` merely runs `tick()` on a `fleet-autoscaler` wall-clock
thread.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from bigdl_tpu import obs as _obs

logger = logging.getLogger("bigdl_tpu.fleet")


def _env(name: str, default: float) -> float:
    val = os.environ.get(name, "").strip()
    if not val:
        return default
    try:
        return float(val)
    except ValueError:
        logger.warning("fleet: ignoring non-numeric %s=%r", name, val)
        return default


@dataclass
class AutoscalerConfig:
    """Hysteresis thresholds; every default reads its BIGDL_TPU_FLEET_*
    env var so deployments tune without code."""

    min_replicas: int = field(
        default_factory=lambda: int(_env("BIGDL_TPU_FLEET_MIN_REPLICAS", 1)))
    max_replicas: int = field(
        default_factory=lambda: int(_env("BIGDL_TPU_FLEET_MAX_REPLICAS", 4)))
    high_queue_depth: float = field(
        default_factory=lambda: _env("BIGDL_TPU_FLEET_HIGH_QUEUE", 16))
    high_p99_ms: float = field(
        default_factory=lambda: _env("BIGDL_TPU_FLEET_HIGH_P99_MS", 200.0))
    high_burn_rate: float = field(
        default_factory=lambda: _env("BIGDL_TPU_FLEET_HIGH_BURN", 6.0))
    low_queue_depth: float = field(
        default_factory=lambda: _env("BIGDL_TPU_FLEET_LOW_QUEUE", 1))
    grow_after: int = field(
        default_factory=lambda: int(_env("BIGDL_TPU_FLEET_GROW_AFTER", 3)))
    shrink_after: int = field(
        default_factory=lambda: int(_env("BIGDL_TPU_FLEET_SHRINK_AFTER", 6)))
    cooldown_ticks: int = field(
        default_factory=lambda: int(_env("BIGDL_TPU_FLEET_COOLDOWN", 5)))
    interval_s: float = field(
        default_factory=lambda: _env("BIGDL_TPU_FLEET_AUTOSCALE_INTERVAL", 1.0))

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")


class FleetAutoscaler:
    """Hysteretic replica-count controller over a FleetRouter."""

    def __init__(self, router, config: Optional[AutoscalerConfig] = None,
                 signals_fn: Optional[Callable[[], Dict[str, float]]] = None):
        self.router = router
        self.config = config or AutoscalerConfig()
        self._signals_fn = signals_fn or self._default_signals
        self._high = 0
        self._low = 0
        self._cooldown = 0
        self._last_alarms = _obs.registry().get("compile/steady_recompiles")
        self.decisions: list = []  # (tick_index, decision) trace
        self._ticks = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals ------------------------------------------------------------

    def _default_signals(self) -> Dict[str, float]:
        """Live signals off the router + registry (the injectable seam
        tests replace with scripted sequences)."""
        with self.router._lock:
            queues = list(self.router._tenants.values())
        p99 = max((q.metrics.total_ms.percentile(99) for q in queues),
                  default=0.0)
        # SLO burn rate: the worst per-tenant fast-window burn the
        # SloMonitor exported on its last tick (0.0 when no monitor runs)
        burn = max((v for k, v in _obs.registry().gauges().items()
                    if k.startswith("slo/burn_rate")), default=0.0)
        return {
            "queue_depth": float(self.router.queue_depth_total()),
            "p99_ms": float(p99),
            "recompile_alarms":
                _obs.registry().get("compile/steady_recompiles"),
            "slo_burn_rate": float(burn),
        }

    # -- the decision step --------------------------------------------------

    def tick(self) -> str:
        """One synchronous decision: returns "grow", "shrink", or "hold"
        (and performs the action on the router)."""
        cfg = self.config
        sig = self._signals_fn()
        depth = sig.get("queue_depth", 0.0)
        p99 = sig.get("p99_ms", 0.0)
        alarms = sig.get("recompile_alarms", 0.0)
        burn = sig.get("slo_burn_rate", 0.0)
        alarm_delta = alarms - self._last_alarms
        self._last_alarms = alarms

        high = (depth >= cfg.high_queue_depth or p99 >= cfg.high_p99_ms
                or burn >= cfg.high_burn_rate)
        low = (depth <= cfg.low_queue_depth and p99 < cfg.high_p99_ms
               and burn < cfg.high_burn_rate)
        if high:
            self._high += 1
            self._low = 0
        elif low:
            self._low += 1
            self._high = 0
        else:  # neutral tick resets both streaks — oscillation holds
            self._high = 0
            self._low = 0
        if self._cooldown > 0:
            self._cooldown -= 1

        n = self.router.n_replicas()
        decision = "hold"
        if (self._cooldown == 0 and self._high >= cfg.grow_after
                and n < cfg.max_replicas):
            self.router.add_replica()
            decision = "grow"
        elif (self._cooldown == 0 and self._low >= cfg.shrink_after
                and n > cfg.min_replicas):
            if alarm_delta > 0:
                logger.warning(
                    "fleet autoscaler: retire vetoed — %d steady-state "
                    "recompile alarm(s) since last tick", int(alarm_delta))
                decision = "veto"
            elif self.router.retire_replica() is not None:
                decision = "shrink"
        if decision in ("grow", "shrink"):
            self._high = 0
            self._low = 0
            self._cooldown = cfg.cooldown_ticks
        reg = _obs.registry()
        reg.set_gauge("fleet/autoscaler_high_streak", self._high)
        reg.set_gauge("fleet/autoscaler_low_streak", self._low)
        if decision != "hold":
            reg.inc(f"fleet/autoscaler_{decision}")
            logger.info("fleet autoscaler: %s (depth=%.0f p99=%.1fms "
                        "replicas=%d)", decision, depth, p99,
                        self.router.n_replicas())
        self.decisions.append((self._ticks, decision))
        self._ticks += 1
        return decision

    # -- wall-clock driver --------------------------------------------------

    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-autoscaler", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a bad tick must not kill scaling
                logger.exception("fleet autoscaler tick failed")

    def close(self, timeout: Optional[float] = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
