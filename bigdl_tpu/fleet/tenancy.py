"""Tenancy: per-tenant admission classes + deficit-weighted fair share.

A fleet front door multiplexes tenants with different SLOs over shared
replicas.  Two mechanisms keep them honest:

  * **Admission classes** — every tenant belongs to a latency TIER
    (`interactive` > `batch` > `best_effort`), which fixes its default
    deadline and its strict scheduling priority.  Each tenant gets its
    own BOUNDED queue (the serving/batcher admission idiom: overload
    rejects loudly at admission, never queues into timeout oblivion) and
    its own `ServingMetrics(tenant=...)` mirror, so per-tenant p50/p99
    export through the registry's label dimension.
  * **Deficit-weighted round robin** (`FairShareScheduler.pick_next`) —
    within a tier, tenants accumulate row credit (`quantum × weight`)
    per scheduler visit and spend it per dispatched row, so a tenant
    bursting 10× the traffic cannot starve a peer: the peer's head
    request is dispatched after a bounded number of the burster's rows
    (the starvation bound asserted in tests/test_fleet.py).  Tiers are
    STRICT priority: a waiting interactive request always dispatches
    before any batch request — that is what the deadline classes mean.

Deficits reset when a queue runs empty (no banking unlimited credit
while idle), and the per-tier pointer stays on the current holder while
its deficit affords the head request, which is what turns weights into
real dispatch ratios instead of plain round robin.
"""

from __future__ import annotations

import collections
import math
import time
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Sequence

from bigdl_tpu import obs as _obs
from bigdl_tpu.serving.batcher import DeadlineExceeded, Rejected, _Future
from bigdl_tpu.serving.metrics import ServingMetrics

# Strict-priority order, highest first.
TIERS = ("interactive", "batch", "best_effort")

# Default deadline per tier (ms); None = no deadline (best effort waits).
TIER_DEADLINES_MS: Dict[str, Optional[float]] = {
    "interactive": 250.0,
    "batch": 5000.0,
    "best_effort": None,
}


@dataclass(frozen=True)
class TenantConfig:
    """One tenant's admission contract.

    weight scales the DRR quantum within the tier (2.0 = twice the rows
    per scheduling round of a weight-1.0 peer); capacity bounds the
    tenant's own queue (its burst cannot consume a peer's headroom);
    deadline_ms None inherits the tier default.
    """

    name: str
    tier: str = "batch"
    weight: float = 1.0
    capacity: int = 128
    deadline_ms: Optional[float] = None

    def __post_init__(self):
        if self.tier not in TIERS:
            raise ValueError(f"tier must be one of {TIERS}, got {self.tier!r}")
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {self.capacity}")

    @property
    def effective_deadline_ms(self) -> Optional[float]:
        if self.deadline_ms is not None:
            return self.deadline_ms
        return TIER_DEADLINES_MS[self.tier]


class FleetRequest:
    """One accepted request riding through the router.

    Carries its OWN future (settled exactly once toward the caller) and
    redispatch state: `attempts` counts dispatches, and the absolute
    deadline survives redispatch so a request bounced off a dead replica
    keeps its original SLO, not a fresh one.
    """

    __slots__ = ("tenant", "x", "rows", "future", "deadline", "t_enqueue",
                 "t_dispatch", "cid", "attempts", "resume")

    def __init__(self, tenant: str, x, rows: int,
                 deadline: Optional[float]):
        self.tenant = tenant
        self.x = x
        self.rows = rows
        self.future = _Future()
        self.deadline = deadline  # absolute perf_counter time, or None
        self.t_enqueue = time.perf_counter()
        self.t_dispatch = self.t_enqueue  # updated per dispatch attempt
        self.cid = _obs.next_cid()
        self.attempts = 0
        # failover progress: the dead replica's last settle-safe snapshot
        # ({"tokens": [...], "rng_uid": int}, from the inner future's
        # gen_progress meta), re-offered to the next replica so a
        # generation resumes mid-stream instead of recomputing
        self.resume: Optional[dict] = None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def remaining_ms(self, now: float) -> Optional[float]:
        if self.deadline is None:
            return None
        return max(0.0, (self.deadline - now) * 1e3)


class TenantQueue:
    """Bounded FIFO + DRR state + per-tenant metrics for one tenant.

    NOT self-locking: the router's dispatcher condition (`FleetRouter.
    _lock`) owns every mutation — admission, pop, requeue, and expiry all
    happen under it, so queue state and scheduler state move together.
    """

    def __init__(self, config: TenantConfig):
        self.config = config
        self.name = config.name
        self.metrics = ServingMetrics(tenant=config.name)
        self.deficit = 0.0
        self._q: Deque[FleetRequest] = collections.deque()
        # registry keys are per-request hot-path costs: build them once
        self.k_admitted = f"fleet/admitted|tenant={config.name}"
        self.k_completed = f"fleet/completed|tenant={config.name}"
        # earliest queued deadline: expire() is called every dispatcher
        # wake, so the common no-expiry case must be O(1), not O(queue)
        self._min_deadline = math.inf

    def __len__(self) -> int:
        return len(self._q)

    def admit(self, req: FleetRequest) -> None:
        if len(self._q) >= self.config.capacity:
            self.metrics.on_reject("queue_full")
            _obs.instant("fleet.reject", cat="fleet", cid=req.cid,
                         tenant=self.name, reason="queue_full")
            raise Rejected(
                f"tenant {self.name!r} queue full ({self.config.capacity} "
                "requests); backpressure — retry with backoff")
        self._q.append(req)
        if req.deadline is not None and req.deadline < self._min_deadline:
            self._min_deadline = req.deadline
        self.metrics.on_admit(len(self._q))

    def head_rows(self) -> int:
        return self._q[0].rows if self._q else 0

    def pop(self) -> FleetRequest:
        req = self._q.popleft()
        if not self._q:
            self.deficit = 0.0  # no banking credit while idle
        return req

    def push_front(self, req: FleetRequest) -> None:
        """Redispatch path: a request bounced off a dying replica goes
        back to the HEAD of its tenant queue (it already waited)."""
        self._q.appendleft(req)
        if req.deadline is not None and req.deadline < self._min_deadline:
            self._min_deadline = req.deadline

    def expire(self, now: float) -> List[FleetRequest]:
        """Fail every deadline-passed request loudly (an *accepted*
        request is never silently dropped — it completes or it fails
        with DeadlineExceeded).  Returns the expired ones."""
        if now <= self._min_deadline:
            return []  # earliest deadline still ahead: nothing to scan
        expired = [r for r in self._q if r.expired(now)]
        if expired:
            self._q = collections.deque(
                r for r in self._q if not r.expired(now))
            if not self._q:
                self.deficit = 0.0
        # the min is maintained as a floor (pops leave it stale-low);
        # recompute here, on the rare slow path
        self._min_deadline = min(
            (r.deadline for r in self._q if r.deadline is not None),
            default=math.inf)
        if expired:
            for req in expired:
                self.metrics.on_reject("deadline")
                _obs.instant("fleet.reject", cat="fleet", cid=req.cid,
                             tenant=self.name, reason="deadline")
                req.future.set_error(DeadlineExceeded(
                    f"tenant {self.name!r} deadline passed after "
                    f"{1e3 * (now - req.t_enqueue):.1f} ms in fleet queue"))
        return expired

    def fail_all(self, err: BaseException, reason: str = "shutdown") -> int:
        n = 0
        while self._q:
            req = self._q.popleft()
            self.metrics.on_reject(reason)
            req.future.set_error(err)
            n += 1
        self.deficit = 0.0
        return n


class FairShareScheduler:
    """Strict tier priority + per-tier deficit-weighted round robin."""

    # A head request never exceeds the largest serving bucket, so a few
    # rounds of quantum top-ups always afford it; the bound is a pure
    # backstop against a misconfigured quantum ≪ bucket.
    MAX_ROUNDS = 64

    def __init__(self, quantum_rows: float = 8.0):
        if quantum_rows <= 0:
            raise ValueError(f"quantum_rows must be > 0, got {quantum_rows}")
        self.quantum = float(quantum_rows)
        self._ptr: Dict[str, str] = {}  # tier -> name of current DRR holder

    def pick_next(self, queues: Sequence[TenantQueue]) -> Optional[TenantQueue]:
        """Choose the tenant whose head request dispatches next.

        Caller passes the non-empty queues and holds the router lock;
        the pick SPENDS the head's rows from the winner's deficit, so
        call it once per dispatched request.
        """
        by_tier: Dict[str, List[TenantQueue]] = {}
        for q in queues:
            if len(q):
                by_tier.setdefault(q.config.tier, []).append(q)
        for tier in TIERS:  # strict priority: first populated tier wins
            qs = by_tier.get(tier)
            if qs:
                return self._pick_drr(tier, qs)
        return None

    def _pick_drr(self, tier: str, qs: List[TenantQueue]) -> TenantQueue:
        qs = sorted(qs, key=lambda q: q.name)  # deterministic ring order
        names = [q.name for q in qs]
        cur = self._ptr.get(tier)
        if cur in names:
            q = qs[names.index(cur)]
            if q.deficit >= q.head_rows():  # holder keeps the floor while
                q.deficit -= q.head_rows()  # its credit affords the head
                return q
            start = names.index(cur) + 1
        else:
            start = 0
        for hop in range(len(qs) * self.MAX_ROUNDS):
            q = qs[(start + hop) % len(qs)]
            q.deficit += self.quantum * q.config.weight  # fresh-visit top-up
            if q.deficit >= q.head_rows():
                q.deficit -= q.head_rows()
                self._ptr[tier] = q.name
                return q
        q = max(qs, key=lambda q: q.deficit)  # backstop: misconfigured quantum
        q.deficit = max(0.0, q.deficit - q.head_rows())
        self._ptr[tier] = q.name
        return q
