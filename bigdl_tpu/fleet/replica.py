"""Replica: one serving backend under the fleet router.

Wraps a `ServingRuntime` (or anything with `submit(x, deadline_ms)` →
future and `close(drain, timeout)`) with the lifecycle the router needs:

    READY ──drain()──> DRAINING ──idle──> DEAD
      └──────────────── kill() ────────────> DEAD

  * READY     — the dispatcher may route new work here.
  * DRAINING  — no new picks; outstanding requests finish normally
    (graceful retirement: scale-in, hot maintenance).
  * DEAD      — `kill()` is the SIGKILL analogue: every outstanding
    inner future is failed with `ReplicaDead` IMMEDIATELY, which fires
    the router's done-callbacks and requeues the requests onto their
    tenant queues for redispatch.  The backing runtime is then torn down
    off the dispatch path (the router's reaper thread).

Outstanding accounting is a set of inner futures guarded by the
replica's own lock; the dispatcher reads `outstanding()` for its
least-loaded pick, and `wait_idle()` is the drain barrier.

Kill/complete races are benign by construction: `_Future` fires its
done-callbacks exactly once (first settle wins), so a request that
completes in the same instant the replica dies either returns its real
result or redispatches and recomputes — predictions are deterministic,
so at-least-once redispatch never changes an answer, and an accepted
request is never silently dropped.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Callable, Optional, Set

from bigdl_tpu.serving.batcher import _Future

READY = "ready"
DRAINING = "draining"
DEAD = "dead"


class ReplicaDead(RuntimeError):
    """The replica holding this request died before completing it; the
    router requeues the request onto its tenant queue (not an SLO
    failure — redispatch preserves the original deadline)."""


class Replica:
    """One backend runtime + lifecycle state + outstanding accounting."""

    def __init__(self, name: str, runtime, *, max_inflight: int = 64):
        self.name = name
        self.runtime = runtime
        self.max_inflight = int(max_inflight)
        self.state = READY
        self.created_at = time.perf_counter()
        self._lock = threading.Lock()
        self._outstanding: Set[_Future] = set()
        self._idle = threading.Event()
        self._idle.set()
        # trace stitching: pass the router's cid down only to runtimes
        # whose submit() takes it (decided once here — duck-typed
        # backends predating the cid contract keep working)
        try:
            params = inspect.signature(runtime.submit).parameters
            self._fwd_cid = "cid" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values())
            # failover resume only goes to runtimes that NAME the param
            # (no VAR_KEYWORD fallback: a progress dict surprising a
            # duck-typed backend's **kw would fail inside the runtime,
            # after the budget was burned)
            self._fwd_resume = "resume" in params
        except (TypeError, ValueError):  # pragma: no cover - C callables
            self._fwd_cid = False
            self._fwd_resume = False

    # -- dispatch path (router's dispatcher thread) -------------------------

    def available(self) -> bool:
        return self.state == READY and self.outstanding() < self.max_inflight

    def outstanding(self) -> int:
        with self._lock:
            return len(self._outstanding)

    def submit(self, x, deadline_ms: Optional[float],
               cid: Optional[str] = None,
               resume: Optional[dict] = None) -> _Future:
        """Route one request into the backing runtime.  Raises
        `ReplicaDead` if the replica is no longer READY (the dispatcher
        rechecks, but kill can win the race) and lets the runtime's own
        admission errors (`Rejected`, `ServingClosed`) propagate.
        `resume` is a dead peer's progress snapshot; it reaches only
        runtimes whose submit() names the param — others recompute from
        scratch (at-least-once semantics are unchanged)."""
        with self._lock:
            if self.state != READY:
                raise ReplicaDead(f"replica {self.name!r} is {self.state}")
            kw = {}
            if cid is not None and self._fwd_cid:
                kw["cid"] = cid
            if resume is not None and self._fwd_resume:
                kw["resume"] = resume
            inner = self.runtime.submit(x, deadline_ms=deadline_ms, **kw)
            self._outstanding.add(inner)
            self._idle.clear()
        inner.add_done_callback(self._forget)
        return inner

    def _forget(self, fut: _Future) -> None:
        with self._lock:
            self._outstanding.discard(fut)
            if not self._outstanding:
                self._idle.set()

    # -- lifecycle ----------------------------------------------------------

    def drain(self) -> None:
        """Stop new picks; outstanding work finishes normally."""
        with self._lock:
            if self.state == READY:
                self.state = DRAINING

    def wait_idle(self, timeout: Optional[float] = 30.0) -> bool:
        return self._idle.wait(timeout)

    def kill(self) -> int:
        """SIGKILL analogue: mark DEAD and fail every outstanding inner
        future with `ReplicaDead` NOW — their done-callbacks (the
        router's completion chain) requeue the requests.  Returns how
        many futures were failed.  Does NOT close the runtime — a dead
        process doesn't run its own destructor; the router's reaper
        does that off-path."""
        with self._lock:
            if self.state == DEAD:
                return 0
            self.state = DEAD
            doomed = list(self._outstanding)
            self._outstanding.clear()
            self._idle.set()
        err = ReplicaDead(f"replica {self.name!r} killed with "
                          f"{len(doomed)} requests in flight")
        for fut in doomed:
            fut.set_error(err)
        return len(doomed)

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Graceful teardown of the backing runtime."""
        with self._lock:
            self.state = DEAD
        self.runtime.close(drain=drain, timeout=timeout)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Replica({self.name!r}, {self.state}, "
                f"outstanding={self.outstanding()})")


class GenerationAdapter:
    """Duck-type a `GenerationEngine` to the replica runtime contract
    (`submit(x, deadline_ms)` / `close(drain, timeout)`).

    The fleet enforces deadlines at its OWN queues (pre-dispatch expiry
    in tenancy.py); a dispatched generation runs to completion — an
    autoregressive request cannot be meaningfully truncated by a
    deadline without changing its answer, so `deadline_ms` stops
    applying once the prompt reaches the engine.  Fixed sampling
    settings for the tenant ride in `submit_kw`."""

    def __init__(self, engine, **submit_kw):
        self.engine = engine
        self.submit_kw = submit_kw
        self.config = getattr(engine, "config", None)

    def submit(self, x, deadline_ms: Optional[float] = None,
               cid: Optional[str] = None,
               resume: Optional[dict] = None) -> _Future:
        kw = dict(self.submit_kw)
        if resume is not None and resume.get("tokens"):
            tokens = resume["tokens"]
            cfg = self.config
            n_eff = len(x) + len(tokens) if hasattr(x, "__len__") else None
            if (cfg is not None and n_eff is not None
                    and not getattr(cfg, "prefill_chunk", 0)
                    and n_eff > cfg.buckets[-1]):
                # the effective prompt (prompt + salvaged tokens) would
                # not fit any bucket on an unchunked engine: drop the
                # snapshot and recompute cold rather than bounce the
                # request off admission — the original prompt fit, so
                # this always dispatches
                pass
            else:
                kw["resume_tokens"] = tokens
                if resume.get("rng_uid") is not None:
                    kw["rng_uid"] = resume["rng_uid"]
        return self.engine.submit(x, cid=cid, **kw)

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        self.engine.close(drain=drain, timeout=timeout)


ReplicaFactory = Callable[[str], object]
