"""FleetRouter: the multi-tenant front door over N serving replicas.

One dispatcher thread (`fleet-router`) pulls from the per-tenant bounded
queues (tenancy.py) in strict-tier + deficit-weighted fair-share order
and places each request on the least-loaded READY replica.  Completion
is CHAINED, not polled: the replica's inner future fires a done-callback
(`_Future.add_done_callback`) that settles the caller's outer future —
no per-request waiter threads, so a thousand in-flight requests cost a
thousand closures, not a thousand stacks.

Failure semantics — the part worth being precise about:

  * An ACCEPTED request (admit() returned a future) has exactly three
    endings: a result, a `DeadlineExceeded`, or a loud `Rejected` after
    the redispatch budget.  Silently dropped is not an ending.
  * A replica dying mid-flight (`kill_replica`, the chaos
    `ReplicaKillFault`) fails its outstanding inner futures with
    `ReplicaDead`; the done-callbacks requeue those requests at the
    HEAD of their tenant queues and the dispatcher places them on a
    surviving replica with their ORIGINAL deadline.  At-least-once
    redispatch: a kill/complete photo-finish may recompute a request on
    the new replica — deterministic forwards make that invisible.
  * Runtime backpressure (inner queue full) requeues without burning
    redispatch budget; replica loss burns budget (`max_redispatch`,
    then a loud `Rejected`).
  * A GENERATION request bounced off a dead replica salvages the
    victim's `gen_progress` snapshot (emitted tokens + sampling-stream
    id, published in the inner future's meta at every settle-safe
    boundary) and re-admits with it: the survivor treats the salvaged
    tokens as prompt tail (prefix-warm prefill when the store is hot)
    and continues the same RNG stream — zero lost tokens, and
    exactly-once emission because the outer future settles once with
    the FULL token list.  `min_recovery_ms` optionally fails
    interactive requests fast when the remaining deadline cannot cover
    a recovery (docs/fleet.md, "Failure semantics").

The dead replica's runtime is torn down on a `fleet-reaper-*` thread —
never on the dispatcher (a stuck XLA teardown must not stall dispatch).

Scale-out is warm by construction: `add_replica` builds the runtime
through the caller's factory, and because every replica warms through
`compilecache.load_or_compile(..., process_scope=...)`, the second
replica of a model family reuses the first one's live executables —
the observed `compile/cache_hits` delta lands in `fleet/warmup_reused`.

`pause()/resume()` freeze dispatch (admission stays open) so tests can
stage an exact queue state and then observe pure scheduler order in
`dispatch_log`.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple, Union

from bigdl_tpu import obs as _obs
from bigdl_tpu.compilecache import enabled as _cc_enabled
from bigdl_tpu.serving.batcher import (DeadlineExceeded, Rejected,
                                       ServingClosed, _Future)
from bigdl_tpu.fleet.replica import (DEAD, READY, Replica, ReplicaDead,
                                     ReplicaFactory)
from bigdl_tpu.fleet.tenancy import (FairShareScheduler, FleetRequest,
                                     TenantConfig, TenantQueue)

logger = logging.getLogger("bigdl_tpu.fleet")


from bigdl_tpu.optim.predictor import _batch_rows  # noqa: E402 — shared
# row-count helper (Table/tuple/array aware); serving uses the same one


class FleetRouter:
    """Front-door router: per-tenant admission -> fair share -> replicas.

    All queue/scheduler/replica-list state is owned by `self._lock` (a
    Condition); the dispatcher thread is the only consumer of the
    queues, done-callbacks only requeue/notify under the same lock, so
    tpu_lint's thread-ownership rules see one lock per shared container.
    """

    def __init__(self, replica_factory: ReplicaFactory, *,
                 n_replicas: int = 1,
                 tenants: Sequence[Union[TenantConfig, dict]] = (),
                 quantum_rows: float = 8.0,
                 max_redispatch: int = 5,
                 max_inflight_per_replica: int = 64,
                 min_recovery_ms: float = 0.0,
                 name: str = "fleet"):
        self.name = name
        self._factory = replica_factory
        self._scheduler = FairShareScheduler(quantum_rows=quantum_rows)
        self._max_redispatch = int(max_redispatch)
        self._max_inflight = int(max_inflight_per_replica)
        # deadline-aware recovery admission: an INTERACTIVE-tier request
        # bounced off a dead replica with less than this much deadline
        # left is failed loudly (Rejected) instead of redispatched — a
        # recovery that cannot possibly land inside the SLO is a zombie
        # retry burning survivor capacity.  0 = resume whenever the
        # deadline has not already passed (queue expiry still applies).
        self._min_recovery_ms = float(min_recovery_ms)
        self._tenants: Dict[str, TenantQueue] = {}
        self._replicas: List[Replica] = []
        self._replica_seq = 0
        self._closed = False
        self._stop = False
        self._paused = False
        self._dispatched = 0
        self._chaos = None
        self._reapers: List[threading.Thread] = []
        # the dispatch decision record: (tenant, cid, replica) per pick,
        # appended under the lock — tests read scheduler order off it
        self.dispatch_log: List[Tuple[str, int, str]] = []
        self._lock = threading.Condition()
        # settlement queue: inner done-callbacks (which run on the
        # replica BATCHER threads, i.e. the compute-critical path) only
        # enqueue here; the fleet-complete thread does the per-request
        # bookkeeping (tenant metrics, meta, outer settle) off-path
        self._done_lock = threading.Condition()
        self._done_q: deque = deque()
        self._settling = 0
        self._stop_done = False
        for t in tenants:
            self.add_tenant(t)
        for _ in range(int(n_replicas)):
            self.add_replica()
        self._done_thread = threading.Thread(target=self._complete_loop,
                                             name="fleet-complete",
                                             daemon=True)
        self._done_thread.start()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-router", daemon=True)
        self._thread.start()

    # -- tenancy ------------------------------------------------------------

    def add_tenant(self, config: Union[TenantConfig, dict]) -> TenantQueue:
        if isinstance(config, dict):
            config = TenantConfig(**config)
        with self._lock:
            if config.name in self._tenants:
                raise ValueError(f"tenant {config.name!r} already registered")
            q = TenantQueue(config)
            self._tenants[config.name] = q
            return q

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(self._tenants)

    # -- replica lifecycle --------------------------------------------------

    def add_replica(self) -> str:
        """Grow the fleet by one replica.  The factory builds (and
        warms) the runtime; with the compilecache on, warmup resolves
        through the process-scoped live layer, so the cache-hit delta
        observed here IS the work scale-out did not repeat
        (`fleet/warmup_reused`)."""
        reg = _obs.registry()
        with self._lock:
            self._replica_seq += 1
            rname = f"{self.name}-r{self._replica_seq}"
        hits_before = reg.get("compile/cache_hits")
        t0 = time.perf_counter()
        runtime = self._factory(rname)
        warm_ms = (time.perf_counter() - t0) * 1e3
        reused = reg.get("compile/cache_hits") - hits_before
        if reused > 0:
            reg.inc("fleet/warmup_reused", reused)
        cap = getattr(getattr(runtime, "config", None), "capacity", None)
        max_inflight = (min(self._max_inflight, int(cap))
                        if cap else self._max_inflight)
        replica = Replica(rname, runtime, max_inflight=max_inflight)
        with self._lock:
            self._replicas.append(replica)
            self._set_replica_gauge_locked()
            self._lock.notify_all()
        reg.set_gauge("fleet/scaleout_warm_ms", warm_ms)
        logger.info("fleet %s: replica %s up in %.1f ms (cache %s, "
                    "%d executables reused)", self.name, rname, warm_ms,
                    "on" if _cc_enabled() else "off", int(reused))
        return rname

    def retire_replica(self, name: Optional[str] = None,
                       timeout: Optional[float] = 30.0) -> Optional[str]:
        """Graceful scale-in: drain the least-loaded READY replica (or
        `name`), wait for its in-flight work, close it.  Returns the
        retired name, or None if no replica was eligible."""
        with self._lock:
            ready = [r for r in self._replicas if r.state == READY]
            if name is not None:
                ready = [r for r in ready if r.name == name]
            if not ready or (name is None and len(ready) <= 1):
                return None  # never drain the last replica implicitly
            cand = min(ready, key=lambda r: r.outstanding())
            cand.drain()
        if not cand.wait_idle(timeout):
            logger.warning("fleet %s: replica %s did not drain in %.0fs",
                           self.name, cand.name, timeout or 0)
        cand.close(drain=True, timeout=timeout)
        with self._lock:
            if cand in self._replicas:
                self._replicas.remove(cand)
            self._set_replica_gauge_locked()
        _obs.registry().inc("fleet/replicas_retired")
        logger.info("fleet %s: replica %s retired", self.name, cand.name)
        return cand.name

    def kill_replica(self, name: Optional[str] = None) -> Optional[str]:
        """SIGKILL analogue (the chaos lane): drop a replica NOW.  Its
        outstanding requests fail with `ReplicaDead`, requeue through
        the done-callbacks, and redispatch to survivors; the dead
        runtime is torn down on a reaper thread, off the dispatch
        path."""
        with self._lock:
            cands = [r for r in self._replicas if r.state != DEAD]
            if name is not None:
                cands = [r for r in cands if r.name == name]
            if not cands:
                return None
            # default target: the busiest replica (kill where it hurts)
            cand = max(cands, key=lambda r: r.outstanding())
            self._replicas.remove(cand)
            self._set_replica_gauge_locked()
        n_inflight = cand.kill()  # callbacks requeue under self._lock
        _obs.registry().inc("fleet/replica_kills")
        _obs.instant("fleet.replica_kill", cat="fleet", replica=cand.name,
                     inflight=n_inflight)
        _obs.flight_notify("fleet.replica_death", replica=cand.name,
                           inflight=n_inflight)
        reaper = threading.Thread(
            target=self._reap, args=(cand,),
            name=f"fleet-reaper-{cand.name}", daemon=True)
        reaper.start()
        with self._lock:
            self._reapers.append(reaper)
            self._lock.notify_all()
        logger.warning("fleet %s: replica %s KILLED with %d in flight",
                       self.name, cand.name, n_inflight)
        return cand.name

    @staticmethod
    def _reap(replica: Replica) -> None:
        try:
            replica.runtime.close(drain=False, timeout=10.0)
        except Exception:  # noqa: BLE001 — a dead replica's teardown may rot
            logger.exception("fleet: reaping replica %s failed", replica.name)

    def replicas(self) -> List[str]:
        with self._lock:
            return [r.name for r in self._replicas]

    def tenant_metrics(self, name: str):
        """Live `ServingMetrics` for one tenant (the SloMonitor source),
        or None for an unknown tenant."""
        with self._lock:
            q = self._tenants.get(name)
        return q.metrics if q is not None else None

    def n_replicas(self) -> int:
        with self._lock:
            return sum(1 for r in self._replicas if r.state == READY)

    def _set_replica_gauge_locked(self) -> None:
        _obs.registry().set_gauge(
            "fleet/replicas",
            sum(1 for r in self._replicas if r.state == READY))

    # -- chaos / test hooks -------------------------------------------------

    def set_chaos(self, hook) -> None:
        """`hook.on_dispatch(n_dispatched, router)` fires after every
        dispatch decision, outside the lock (it may kill replicas)."""
        self._chaos = hook

    def pause(self) -> None:
        """Freeze dispatch (admission stays open) — tests stage a queue
        state, then `resume()` and read pure scheduler order from
        `dispatch_log`."""
        with self._lock:
            self._paused = True

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._lock.notify_all()

    # -- admission ----------------------------------------------------------

    def submit(self, tenant: str, x,
               deadline_ms: Optional[float] = None) -> _Future:
        """Async admission for `tenant`: returns the OUTER future.
        Deadline defaults to the tenant's tier class; it is absolute
        from now and survives redispatch."""
        rows = _batch_rows(x)
        with self._lock:
            q = self._tenants.get(tenant)
            if q is None:
                raise KeyError(f"unknown tenant {tenant!r}; "
                               f"registered: {sorted(self._tenants)}")
            if self._closed:
                q.metrics.on_reject("shutdown")
                raise ServingClosed("fleet router is closed")
            if deadline_ms is None:
                deadline_ms = q.config.effective_deadline_ms
            deadline = (time.perf_counter() + deadline_ms / 1e3
                        if deadline_ms is not None else None)
            req = FleetRequest(tenant, x, rows, deadline)
            q.admit(req)  # raises Rejected when the tenant queue is full
            self._lock.notify_all()
        _obs.instant("fleet.admit", cat="fleet", cid=req.cid, tenant=tenant,
                     rows=rows)
        _obs.registry().inc(q.k_admitted)
        return req.future

    def predict(self, tenant: str, x, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = 60.0):
        """Blocking single-request predict through the front door."""
        return self.submit(tenant, x, deadline_ms).result(timeout)

    def queue_depth_total(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._tenants.values())

    # -- dispatcher ---------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._lock:
                pick = None
                while pick is None:
                    if self._stop:
                        return
                    now = time.perf_counter()
                    for q in self._tenants.values():
                        q.expire(now)
                    if not self._paused:
                        pick = self._pick_locked()
                    if pick is None:
                        self._lock.wait(0.02)
                req, replica = pick
                req.t_dispatch = time.perf_counter()
                self.dispatch_log.append((req.tenant, req.cid, replica.name))
                self._dispatched += 1
                n = self._dispatched
            self._dispatch_one(req, replica, n)

    def _pick_locked(self) -> Optional[Tuple[FleetRequest, Replica]]:
        queues = [q for q in self._tenants.values() if len(q)]
        if not queues:
            return None
        replica = None
        for r in self._replicas:  # least-loaded READY replica
            if r.available() and (replica is None
                                  or r.outstanding() < replica.outstanding()):
                replica = r
        if replica is None:
            return None
        q = self._scheduler.pick_next(queues)
        if q is None:
            return None
        return q.pop(), replica

    def _dispatch_one(self, req: FleetRequest, replica: Replica,
                      n: int) -> None:
        """Place one request on a replica — OUTSIDE the lock (the chaos
        hook may kill replicas; runtime.submit takes the batcher's
        queue)."""
        hook = self._chaos
        if hook is not None:
            try:
                hook.on_dispatch(n, self)
            except Exception:  # noqa: BLE001 — chaos must not break dispatch
                logger.exception("fleet chaos hook raised")
        _obs.instant("fleet.dispatch", cat="fleet", cid=req.cid,
                     tenant=req.tenant, replica=replica.name,
                     attempt=req.attempts)
        now = time.perf_counter()
        try:
            inner = replica.submit(req.x, deadline_ms=req.remaining_ms(now),
                                   cid=req.cid, resume=req.resume)
        except ReplicaDead:
            self._requeue(req, replica, burn_budget=True)
            return
        except Rejected:  # inner queue full / runtime closing under us:
            self._requeue(req, replica, burn_budget=False)  # backpressure
            time.sleep(0.001)  # yield so the replica makes progress
            return
        except BaseException as e:  # noqa: BLE001 — e.g. rows > bucket
            self._fail(req, e)
            return
        inner.add_done_callback(
            lambda fut, req=req, rep=replica: self._on_inner_done(
                req, rep, fut))

    # -- completion chain ---------------------------------------------------

    def _on_inner_done(self, req: FleetRequest, replica: Replica,
                       fut: _Future) -> None:
        """Inner done-callback: runs on the replica's batcher thread (or
        the killer's) — hand off to the fleet-complete thread instead of
        doing bookkeeping on the compute-critical path."""
        with self._done_lock:
            self._done_q.append((req, replica, fut))
            self._done_lock.notify()

    def _complete_loop(self) -> None:
        while True:
            with self._done_lock:
                while not self._done_q and not self._stop_done:
                    self._done_lock.wait(0.05)
                if not self._done_q and self._stop_done:
                    return
                req, replica, fut = self._done_q.popleft()
                self._settling += 1
            try:
                self._settle(req, replica, fut)
            finally:
                with self._done_lock:
                    self._settling -= 1
                # no notify: close()'s drain loop polls (wait(0.02)), and
                # waking the dispatcher per settle is pure hot-path churn

    def _done_pending(self) -> bool:
        with self._done_lock:
            return bool(self._done_q) or self._settling > 0

    def _settle(self, req: FleetRequest, replica: Replica,
                fut: _Future) -> None:
        err = fut.error()
        if err is None:
            self._complete(req, replica, fut)
            return
        lost = isinstance(err, ReplicaDead) or (
            isinstance(err, ServingClosed) and replica.state != READY)
        if lost:
            self._requeue(req, replica, burn_budget=True, fut=fut)
            return
        if isinstance(err, DeadlineExceeded):
            with self._lock:
                q = self._tenants.get(req.tenant)
            if q is not None:  # mirror the inner rejection per tenant
                q.metrics.on_reject("deadline")
        self._fail(req, err)

    def _complete(self, req: FleetRequest, replica: Replica,
                  fut: _Future) -> None:
        now = time.perf_counter()
        # lock-free reads: dict.get and len(deque) are atomic under the
        # GIL, and a completion racing a tenant map change only risks a
        # momentarily stale depth gauge — never corrupts queue state
        q = self._tenants.get(req.tenant)  # tpu-lint: disable=unguarded-state
        depth = len(q) if q is not None else 0
        t_disp = getattr(req, "t_dispatch", req.t_enqueue)
        if q is not None:
            q.metrics.on_complete(
                queue_ms=(t_disp - req.t_enqueue) * 1e3,
                total_ms=(now - req.t_enqueue) * 1e3, depth=depth)
            _obs.registry().inc(q.k_completed)
        req.future.meta.update(fut.meta)
        # ONE cid per request across replicas: the router's id (threaded
        # through replica.submit, so it usually already matches the
        # inner meta) wins even over a backend that minted its own
        req.future.meta.update({"tenant": req.tenant, "replica": replica.name,
                                "cid": req.cid, "fleet_cid": req.cid,
                                "attempts": req.attempts + 1})
        if fut.meta.get("recovered"):
            # tenant-labeled mirror of the engine-side recovery counters
            # (each engine's GenerationMetrics is per-replica, unlabeled)
            reg = _obs.registry()
            reg.inc(f"fleet/recovered_requests|tenant={req.tenant}")
            if fut.meta.get("recovery_prefix_tokens"):
                reg.inc("generation/recovery_prefix_hits"
                        f"|tenant={req.tenant}")
        _obs.instant("fleet.complete", cat="fleet", cid=req.cid,
                     tenant=req.tenant, replica=replica.name,
                     attempts=req.attempts + 1)
        req.future.set_result(fut.result(0))

    def _fail(self, req: FleetRequest, err: BaseException) -> None:
        req.future.meta.update({"tenant": req.tenant, "fleet_cid": req.cid,
                                "attempts": req.attempts + 1})
        req.future.set_error(err)

    def _requeue(self, req: FleetRequest, replica: Replica,
                 burn_budget: bool, fut: Optional[_Future] = None) -> None:
        """Put a bounced request back at the head of its tenant queue.
        Replica loss burns redispatch budget; backpressure does not.

        On replica loss, the dead replica's inner future (`fut`) may
        carry a `gen_progress` snapshot in its meta — the tokens the
        victim emitted up to its last settle-safe boundary, plus the
        request's sampling-stream id.  Salvage it into `req.resume` so
        the next dispatch warm-resumes instead of recomputing; a
        snapshot never goes backwards (a stale retry cannot shrink an
        earlier, larger salvage)."""
        if burn_budget:
            req.attempts += 1
            salvaged = 0
            if fut is not None:
                gp = fut.meta.get("gen_progress")
                if gp and gp.get("tokens"):
                    prev = req.resume.get("tokens") if req.resume else ()
                    if len(gp["tokens"]) > len(prev or ()):
                        req.resume = gp
                        salvaged = len(gp["tokens"])
            reg = _obs.registry()
            reg.inc("fleet/failovers")
            reg.inc(f"fleet/failovers|tenant={req.tenant}")
            if salvaged:
                reg.inc("fleet/resumed_tokens", salvaged)
                reg.inc(f"fleet/resumed_tokens|tenant={req.tenant}",
                        salvaged)
            _obs.instant("fleet.failover", cat="fleet", cid=req.cid,
                         tenant=req.tenant, from_replica=replica.name,
                         attempt=req.attempts, resumed_tokens=salvaged)
            if req.attempts >= self._max_redispatch:
                with self._lock:
                    q = self._tenants.get(req.tenant)
                if q is not None:
                    q.metrics.on_reject("replica_lost")
                _obs.flight_notify("fleet.redispatch_budget_exhausted",
                                   tenant=req.tenant, cid=req.cid,
                                   attempts=req.attempts)
                self._fail(req, Rejected(
                    f"request lost its replica {req.attempts} times "
                    "(fleet redispatch budget exhausted)"))
                return
            with self._lock:
                q = self._tenants.get(req.tenant)
            if (self._min_recovery_ms > 0 and q is not None
                    and q.config.tier == "interactive"):
                rem = req.remaining_ms(time.perf_counter())
                if rem is not None and rem < self._min_recovery_ms:
                    # the remaining deadline cannot cover recovery:
                    # fail LOUDLY now instead of a zombie retry that
                    # burns survivor capacity only to expire anyway
                    q.metrics.on_reject("deadline")
                    _obs.flight_notify("fleet.recovery_rejected",
                                       tenant=req.tenant, cid=req.cid,
                                       remaining_ms=round(rem, 1),
                                       min_recovery_ms=self._min_recovery_ms)
                    self._fail(req, Rejected(
                        f"replica died with {rem:.0f} ms of deadline left "
                        f"(< min_recovery_ms={self._min_recovery_ms:.0f}); "
                        "recovery cannot meet the interactive SLO"))
                    return
            _obs.registry().inc("fleet/redispatched")
            _obs.registry().inc(f"fleet/redispatches|tenant={req.tenant}")
            _obs.instant("fleet.redispatch", cat="fleet", cid=req.cid,
                         tenant=req.tenant, from_replica=replica.name,
                         attempt=req.attempts)
        with self._lock:
            q = self._tenants.get(req.tenant)
            if q is None or self._stop:
                # tenant vanished or the dispatcher already stopped
                # (close raced the bounce): fail LOUDLY — a request
                # parked in a queue nobody drains is a silent drop
                self._fail(req, ServingClosed("fleet router closed"))
                return
            q.push_front(req)
            self._lock.notify_all()

    # -- read-back / shutdown -----------------------------------------------

    def snapshot(self) -> Dict:
        with self._lock:
            tenants = {name: q for name, q in self._tenants.items()}
            replicas = [(r.name, r.state, r.outstanding())
                        for r in self._replicas]
            dispatched = self._dispatched
        reg = _obs.registry()
        return {
            "tenants": {name: q.metrics.snapshot()
                        for name, q in tenants.items()},
            "replicas": [{"name": n, "state": s, "outstanding": o}
                         for n, s, o in replicas],
            "dispatched": dispatched,
            "redispatched": reg.get("fleet/redispatched"),
            "replica_kills": reg.get("fleet/replica_kills"),
            "failovers": reg.get("fleet/failovers"),
            "resumed_tokens": reg.get("fleet/resumed_tokens"),
            "warmup_reused": reg.get("fleet/warmup_reused"),
        }

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop admission; `drain=True` completes everything accepted
        (redispatches included), `drain=False` fails still-queued
        requests with ServingClosed."""
        with self._lock:
            if self._stop:
                return
            self._closed = True
            self._lock.notify_all()
        deadline = time.monotonic() + (timeout if timeout is not None else 60.0)
        if drain:
            with self._lock:
                while (any(len(q) for q in self._tenants.values())
                       or any(r.outstanding() for r in self._replicas)
                       or self._done_pending()):
                    if time.monotonic() > deadline:
                        raise TimeoutError("fleet router did not drain in time")
                    self._lock.wait(0.02)
        else:
            with self._lock:
                for q in self._tenants.values():
                    q.fail_all(ServingClosed("fleet router shut down"))
        with self._lock:
            self._stop = True
            self._lock.notify_all()
            # snapshot under the lock: kill_replica appends a reaper (and
            # mutates the replica list) from autoscaler/chaos threads, and
            # an unlocked iteration here can race a late kill
            replicas = list(self._replicas)
            reapers = list(self._reapers)
        self._thread.join(timeout)
        for r in replicas:
            r.close(drain=drain, timeout=timeout)
        # replica close may have bounced last inner futures into the
        # settlement queue — let the fleet-complete thread finish them,
        # then stop it
        while self._done_pending() and time.monotonic() < deadline + 5.0:
            time.sleep(0.005)
        with self._done_lock:
            self._stop_done = True
            self._done_lock.notify_all()
        self._done_thread.join(timeout)
        for reaper in reapers:
            reaper.join(max(0.0, deadline - time.monotonic()) + 5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close(drain=not any(exc))
        return False
