"""bigdl_tpu.fleet — multi-tenant front door over serving replicas.

One process, many models, many SLOs: the fleet layer multiplexes
tenants over N in-process `ServingRuntime`/`GenerationEngine` replicas
(ROADMAP item 5 — the scenario BigDL pitched as "DL as a standard
multi-tenant cluster workload", re-grounded on TPU serving economics).

  * `tenancy`    — admission classes (interactive/batch/best_effort
    tiers), bounded per-tenant queues, deficit-weighted fair share.
  * `replica`    — replica lifecycle (READY/DRAINING/DEAD), the
    SIGKILL-analog `kill()` that bounces in-flight work back to the
    router with zero silent drops.
  * `router`     — the front door: one dispatcher thread, completion
    chaining via future callbacks, redispatch on replica loss, warm
    scale-out accounting.
  * `autoscaler` — hysteretic grow/retire off the obs MetricsRegistry
    signals (queue depth, p99, steady-recompile alarm veto).

See docs/fleet.md for the tenancy model, env vars, and when NOT to
enable the fleet layer (one tenant + one model needs none of this).
"""

from bigdl_tpu.fleet.autoscaler import AutoscalerConfig, FleetAutoscaler
from bigdl_tpu.fleet.replica import (DEAD, DRAINING, READY,
                                     GenerationAdapter, Replica, ReplicaDead)
from bigdl_tpu.fleet.router import FleetRouter
from bigdl_tpu.fleet.tenancy import (TIER_DEADLINES_MS, TIERS,
                                     FairShareScheduler, FleetRequest,
                                     TenantConfig, TenantQueue)

__all__ = [
    "AutoscalerConfig", "DEAD", "DRAINING", "FairShareScheduler",
    "GenerationAdapter",
    "FleetAutoscaler", "FleetRequest", "FleetRouter", "READY", "Replica",
    "ReplicaDead", "TenantConfig", "TenantQueue", "TIERS",
    "TIER_DEADLINES_MS",
]
