"""Runtime lock-order sanitizer (lockdep) for the threaded serving stack.

The static pass in `bigdl_tpu.analysis.concurrency` predicts the
acquired-before graph from source; this module OBSERVES it.  With
`BIGDL_TPU_LOCKDEP=1` (or an explicit `instrument_locks()` call),
`threading.Lock` / `threading.RLock` creation inside `bigdl_tpu.*`
returns a thin wrapper that records, per thread, the set of wrapped
locks currently held and folds every nested acquisition into a
process-global acquired-before graph keyed by the lock's CREATION SITE
(`file:line` — the same key `concurrency.LockGraph.site_index()`
exposes, so runtime edges reconcile 1:1 against static predictions,
see `tools/lockdep_reconcile.py`).

The moment a blocking acquisition would close a cycle in that graph the
wrapper raises `LockOrderViolation` — *before* touching the inner lock,
so tests exercising a real deadlock get an exception with BOTH
acquisition stacks instead of a hang.  Additional checks, counter-only:

  * blocking-op-while-held — `time.sleep`, `queue.Queue.get/put`
    (blocking, no timeout) entered while any instrumented lock is held;
  * held-too-long — a lock held beyond `BIGDL_TPU_LOCKDEP_HELD_MS`
    (default 200 ms) at release time;
  * plain-`Lock` same-thread blocking re-acquire — guaranteed
    self-deadlock, raised immediately.

Semantics kept honest:

  * RLock re-entry by the owning thread is counted, never an edge —
    reentrancy is not an ordering fact.
  * Non-blocking (`acquire(False)`) and bounded-timeout acquisitions
    never add edges and never raise: a trylock cannot deadlock, so it
    creates no ordering dependency (same rule as Linux lockdep).
  * Edges between two locks from the SAME creation site (two instances
    of one class) are recorded for the report but excluded from cycle
    search — instance-level order on sibling locks is a real hazard but
    site-keying cannot distinguish A->B from B->A, so flagging it here
    would be pure noise; the static pass owns that rule.
  * `Condition` support rides the `_release_save` / `_acquire_restore`
    / `_is_owned` forwarding protocol: `cond.wait()` drops the lock
    from the held set for the duration and restores it without
    re-recording edges (the order was established at first acquire).

Cost model: bookkeeping uses one raw `_thread` lock (never itself
instrumented), `time.perf_counter` only, and captures a stack ONLY when
a new edge is first witnessed — steady state is a couple of dict hits
per nested acquire and zero per uncontended leaf acquire.  No device
syncs, no allocation on the hot path beyond the held-list entry.  This
is a TEST/CI tool: keep it off in production serving
(`bench_trainer_overhead --lockdep` quantifies the delta and asserts
the off-switch is free).

Counters surface through the metrics plane as `lockdep/*` via
`publish_metrics()` (called by `export_graph`), pull-style so lock
bookkeeping never recurses into the registry's own (instrumented) lock.
"""

from __future__ import annotations

import _thread
import atexit
import json
import os
import queue
import sys
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "LockOrderViolation",
    "instrument_locks",
    "uninstrument_locks",
    "install_if_enabled",
    "enabled",
    "reset",
    "snapshot",
    "export_graph",
    "publish_metrics",
]

_MAX_EDGES = 4096
_MAX_VIOLATIONS = 64
_MAX_BLOCKING = 256
_STACK_DEPTH = 16

# this module's own source path — frame walks must skip exactly THIS
# file, not anything whose name merely contains "lockdep.py" (a test
# module named test_lockdep.py would match a substring check)
_SELF_FILE = os.path.abspath(__file__)

# raw lock: guards every module-global below and is invisible to the
# instrumentation (allocated via _thread, not threading.Lock)
_state_lock = _thread.allocate_lock()
_tls = threading.local()

_edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
_adj: Dict[str, set] = {}          # cycle-search graph (same-site pairs excluded)
_violations: List[Dict[str, Any]] = []
_blocking: List[Dict[str, Any]] = []
_counters: Dict[str, int] = {}
_orig: Optional[Dict[str, Any]] = None  # saved originals while instrumented
_match: Callable[[str], bool] = lambda path: "bigdl_tpu" in path
_held_ms: float = 200.0


class LockOrderViolation(RuntimeError):
    """A blocking acquisition closed a cycle in the acquired-before
    graph (or a plain Lock was blocking-reacquired by its owner).  The
    message carries the cycle's sites and both acquisition stacks."""


def _counters_init() -> Dict[str, int]:
    return {"edges": 0, "violations": 0,
            "blocking_under_lock": 0, "held_too_long": 0}


_counters = _counters_init()


def _held() -> List[list]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = []
        _tls.held = h
    return h


def _stack(skip: int = 2) -> List[str]:
    frames = traceback.format_stack(sys._getframe(skip), limit=_STACK_DEPTH)
    return [ln for ln in frames if _SELF_FILE not in ln]


def _creation_site() -> str:
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != _SELF_FILE and not fn.endswith("threading.py"):
            return os.path.abspath(fn) + ":" + str(f.f_lineno)
        f = f.f_back
    return "?:0"


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS in the site graph; returns a site path src..dst or None.
    Caller holds `_state_lock`."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _adj.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


class _LockWrapper:
    """Records ordering facts around an inner threading lock.  The
    `_ld_` prefix keeps the namespace clear of anything client code or
    `threading.Condition` might probe for."""

    __slots__ = ("_ld_inner", "_ld_site", "_ld_reentrant")

    def __init__(self, inner, site: str, reentrant: bool):
        self._ld_inner = inner
        self._ld_site = site
        self._ld_reentrant = reentrant

    # -- core protocol -----------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held()
        for ent in held:
            if ent[0] is self:
                if self._ld_reentrant:
                    ok = self._ld_inner.acquire(blocking, timeout)
                    if ok:
                        ent[1] += 1
                    return ok
                if blocking and (timeout is None or timeout < 0):
                    self._ld_raise_self_deadlock()
                # bounded/try re-acquire of an owned plain Lock: let the
                # caller observe the failure it is coded to handle
                return self._ld_inner.acquire(blocking, timeout)
        unbounded = blocking and (timeout is None or timeout < 0)
        if held and unbounded:
            self._ld_check_cycle(held)
        ok = self._ld_inner.acquire(blocking, timeout)
        if ok:
            if held and unbounded:
                self._ld_record_edges(held)
            held.append([self, 1, time.perf_counter()])
        return ok

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            ent = held[i]
            if ent[0] is self:
                ent[1] -= 1
                if ent[1] == 0:
                    dur_ms = (time.perf_counter() - ent[2]) * 1000.0
                    del held[i]
                    if dur_ms > _held_ms:
                        with _state_lock:
                            _counters["held_too_long"] += 1
                break
        # not found: released from a thread that never acquired through
        # the wrapper (signalling pattern) — forward untracked
        self._ld_inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        f = getattr(self._ld_inner, "locked", None)
        if f is not None:
            return f()
        return self._is_owned()

    def __repr__(self) -> str:
        return "<lockdep %s wrapping %r>" % (self._ld_site, self._ld_inner)

    # -- Condition forwarding protocol ------------------------------------

    def _is_owned(self) -> bool:
        f = getattr(self._ld_inner, "_is_owned", None)
        if f is not None:
            return f()
        for ent in _held():
            if ent[0] is self:
                return True
        return False

    def _release_save(self):
        count = 1
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                count = held[i][1]
                del held[i]
                break
        f = getattr(self._ld_inner, "_release_save", None)
        if f is not None:
            return (count, f())
        self._ld_inner.release()
        return (count, None)

    def _acquire_restore(self, saved) -> None:
        count, inner_state = saved
        f = getattr(self._ld_inner, "_acquire_restore", None)
        if f is not None:
            f(inner_state)
        else:
            self._ld_inner.acquire()
        # no edge recording: the wait() round-trip restores an order the
        # original acquire already established
        _held().append([self, count, time.perf_counter()])

    # -- bookkeeping -------------------------------------------------------

    def _ld_check_cycle(self, held: List[list]) -> None:
        b = self._ld_site
        boom = None
        with _state_lock:
            for ent in held:
                a = ent[0]._ld_site
                if a == b:
                    continue
                path = _find_path(b, a)
                if path is not None:
                    first = _edges.get((path[0], path[1]), {})
                    rec = {
                        "kind": "lock-order",
                        "cycle": path + [b],
                        "acquiring": b,
                        "holding": a,
                        "stack": _stack(3),
                        "other_stack": list(first.get("stack", ())),
                        "thread": threading.current_thread().name,
                    }
                    if len(_violations) < _MAX_VIOLATIONS:
                        _violations.append(rec)
                    _counters["violations"] += 1
                    boom = rec
                    break
        if boom is not None:
            raise LockOrderViolation(
                "lock-order cycle: acquiring %s while holding %s would close "
                "%s\n--- this acquisition (thread %s):\n%s"
                "--- first witness of the reverse edge %s -> %s:\n%s"
                % (boom["acquiring"], boom["holding"],
                   " -> ".join(boom["cycle"]), boom["thread"],
                   "".join(boom["stack"]),
                   boom["cycle"][0], boom["cycle"][1],
                   "".join(boom["other_stack"]) or "  (stack not recorded)\n"))

    def _ld_record_edges(self, held: List[list]) -> None:
        b = self._ld_site
        with _state_lock:
            for ent in held:
                a = ent[0]._ld_site
                key = (a, b)
                rec = _edges.get(key)
                if rec is not None:
                    rec["count"] += 1
                    continue
                if len(_edges) >= _MAX_EDGES:
                    continue
                _edges[key] = {"count": 1, "same_site": a == b,
                               "stack": _stack(3),
                               "thread": threading.current_thread().name}
                if a != b:
                    _adj.setdefault(a, set()).add(b)
                _counters["edges"] = len(_edges)

    def _ld_raise_self_deadlock(self) -> None:
        rec = {
            "kind": "self-deadlock",
            "cycle": [self._ld_site, self._ld_site],
            "acquiring": self._ld_site,
            "holding": self._ld_site,
            "stack": _stack(3),
            "other_stack": [],
            "thread": threading.current_thread().name,
        }
        with _state_lock:
            if len(_violations) < _MAX_VIOLATIONS:
                _violations.append(rec)
            _counters["violations"] += 1
        raise LockOrderViolation(
            "self-deadlock: thread %s blocking-reacquired non-reentrant lock "
            "%s it already holds\n%s"
            % (rec["thread"], self._ld_site, "".join(rec["stack"])))


# -- blocking-op hooks -----------------------------------------------------

def _note_blocking(what: str) -> None:
    held = getattr(_tls, "held", None)
    if not held:
        return
    with _state_lock:
        _counters["blocking_under_lock"] += 1
        if len(_blocking) < _MAX_BLOCKING:
            _blocking.append({"what": what,
                              "held": [e[0]._ld_site for e in held],
                              "stack": _stack(3),
                              "thread": threading.current_thread().name})


def _make_sleep(orig):
    def sleep(secs):
        if secs and secs >= 0.0005:
            _note_blocking("time.sleep")
        return orig(secs)
    return sleep


def _make_qget(orig):
    def get(self, block=True, timeout=None):
        if block and timeout is None:
            _note_blocking("queue.get")
        return orig(self, block, timeout)
    return get


def _make_qput(orig):
    def put(self, item, block=True, timeout=None):
        if block and timeout is None:
            _note_blocking("queue.put")
        return orig(self, item, block, timeout)
    return put


# -- factories -------------------------------------------------------------

def _make_factory(orig_factory, reentrant: bool):
    def factory():
        inner = orig_factory()
        site = _creation_site()
        if not _match(site):
            return inner
        return _LockWrapper(inner, site, reentrant)
    return factory


def instrument_locks(path_substr: str = "bigdl_tpu",
                     path_filter: Optional[Callable[[str], bool]] = None,
                     held_ms: Optional[float] = None) -> bool:
    """Patch `threading.Lock`/`threading.RLock` so locks subsequently
    created at matching sites come back wrapped, and hook the blocking
    primitives.  Returns False (and changes nothing) if already
    instrumented.  Only affects locks created AFTER the call — install
    before constructing the stack under test."""
    global _orig, _match, _held_ms
    with _state_lock:
        if _orig is not None:
            return False
        _orig = {
            "Lock": threading.Lock,
            "RLock": threading.RLock,
            "sleep": time.sleep,
            "qget": queue.Queue.get,
            "qput": queue.Queue.put,
        }
        _match = path_filter if path_filter is not None \
            else (lambda p: path_substr in p)
        if held_ms is not None:
            _held_ms = float(held_ms)
        else:
            _held_ms = float(os.environ.get("BIGDL_TPU_LOCKDEP_HELD_MS",
                                            "200"))
    threading.Lock = _make_factory(_orig["Lock"], False)
    threading.RLock = _make_factory(_orig["RLock"], True)
    time.sleep = _make_sleep(_orig["sleep"])
    queue.Queue.get = _make_qget(_orig["qget"])
    queue.Queue.put = _make_qput(_orig["qput"])
    return True


def uninstrument_locks() -> bool:
    """Restore the original factories/primitives.  Locks already
    created while instrumented keep their wrappers (they stay correct,
    just still observed); call `reset()` to drop collected state."""
    global _orig
    with _state_lock:
        orig, _orig = _orig, None
    if orig is None:
        return False
    threading.Lock = orig["Lock"]
    threading.RLock = orig["RLock"]
    time.sleep = orig["sleep"]
    queue.Queue.get = orig["qget"]
    queue.Queue.put = orig["qput"]
    return True


def instrumented() -> bool:
    return _orig is not None


def enabled() -> bool:
    return os.environ.get("BIGDL_TPU_LOCKDEP", "").strip().lower() in (
        "1", "true", "on", "yes")


def install_if_enabled() -> bool:
    """Entry point for smokes/CI: instrument iff `BIGDL_TPU_LOCKDEP` is
    set, and arm an atexit export when `BIGDL_TPU_LOCKDEP_EXPORT` names
    a path."""
    if not enabled():
        return False
    fresh = instrument_locks()
    export = os.environ.get("BIGDL_TPU_LOCKDEP_EXPORT")
    if fresh and export:
        atexit.register(export_graph, export)
    return fresh


# -- reporting -------------------------------------------------------------

def reset() -> None:
    """Drop every collected edge/violation/counter (keeps the patch
    state); the per-thread held lists are live acquisitions and are
    left alone."""
    global _counters
    with _state_lock:
        _edges.clear()
        _adj.clear()
        del _violations[:]
        del _blocking[:]
        _counters = _counters_init()


def snapshot() -> Dict[str, Any]:
    with _state_lock:
        return {
            "instrumented": _orig is not None,
            "counters": dict(_counters),
            "edges": [
                {"src": a, "dst": b, "count": rec["count"],
                 "same_site": rec["same_site"], "thread": rec["thread"]}
                for (a, b), rec in _edges.items()
            ],
            "violations": [dict(v) for v in _violations],
            "blocking": [dict(bk) for bk in _blocking],
        }


def publish_metrics(registry=None) -> None:
    """Mirror the counters into the metrics plane as `lockdep/*`.
    Pull-style (called here and by exporters), never from the acquire
    path — the registry's own lock may itself be instrumented."""
    if registry is None:
        from bigdl_tpu import obs
        registry = obs.registry()
    with _state_lock:
        counters = dict(_counters)
    for name, val in counters.items():
        registry.set_gauge("lockdep/" + name, val)


def export_graph(path: str) -> Dict[str, Any]:
    """Write the observed graph as JSON (the reconciliation input for
    `tools/lockdep_reconcile.py`) and publish counters."""
    snap = snapshot()
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(snap, fh, indent=1, sort_keys=True)
    os.replace(tmp, path)
    try:
        publish_metrics()
    except Exception:
        pass  # exporting from atexit: the obs plane may already be torn down
    return snap
