"""TPU-hostile-pattern linter: AST static analysis over the framework.

The paper's value proposition is hot paths that stay on the accelerator;
the JAX/XLA failure modes that silently break it are host syncs inside
the step/serve/feed loops, avoidable retraces, tracer leaks into Python
state, data races in the background threads, donated buffers read after
the donating call, and blocking I/O under trace.  This module is the
mechanical gate: `tools/tpu_lint.py` runs it over the tree in CI.

Six rule families (ids are what `# tpu-lint: disable=<rule>` takes):

  host-sync    d2h pulls (float/int/bool/.item/.tolist/np.*) of device
               values in hot-path functions, and if/while on traced
               values inside jitted code
  recompile    jitted closures that read `self` at trace time (stale
               closure + retrace hazard) and Python host scalars passed
               to jitted callables inside hot loops (implicit h2d +
               weak-type retrace)
  tracer-leak  traced values stored on `self`, module globals, or
               captured containers from inside jitted code
  concurrency  threads with neither daemon nor join, unbounded
               queue.put/get/join on shutdown paths, shared mutable
               containers touched by both worker and driver methods
               without a lock
  donation     donated buffers read after the donating call
  blocking-io  open/sleep/subprocess/sockets inside jitted code or
               inside loops of hot-path functions

The analysis is a per-function taint walk (DEV / HOST / UNK lattice)
plus name-level cross-file summaries (`returns_device`,
`syncing_params`) iterated to a small fixpoint — precise enough to
catch `float(self._current_lr())` through two calls while staying
quiet on `int(self._resume_skip or 0)`.  Precision choices that keep
the false-positive rate workable on this codebase:

  * explicit transfer APIs (`jax.device_get` / `jax.device_put`) are
    always sanctioned — they are the documented way to cross the
    boundary and the runtime transfer guard allows them too;
  * a sink on a DEV value fires anywhere in a hot or jitted function;
    a sink on an UNK value fires only inside a lexical `for` loop of a
    hot function (per-step pulls are the expensive ones; one-shot
    pulls of unknowns at setup/teardown are noise);
  * `self.<attr>` loads are UNK, so host bookkeeping reads stay quiet
    while method calls with a device-returning summary still taint.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

RULES = (
    "host-sync",
    "recompile",
    "tracer-leak",
    "concurrency",
    "donation",
    "blocking-io",
    "lock-order",
    "unguarded-state",
    "blocking-under-lock",
)

# Rules that guard the hot path itself: a finding is a live perf/correctness
# bug, so the committed baseline may never carry one (CLI enforces).
# lock-order (a deadlock waiting for the right interleaving) and
# blocking-under-lock (defined only on hot roots) join the set: fix or
# suppress inline with a reason, never grandfather.
HOT_PATH_RULES = frozenset({"host-sync", "tracer-leak", "donation",
                            "lock-order", "blocking-under-lock"})

# Functions reachable from these qualnames are "hot": their per-call cost
# multiplies by steps/requests/batches.  Same-module callees inherit the
# flag (depth-bounded BFS below).
DEFAULT_HOT_ROOTS = (
    r"Optimizer\._optimize_impl$",
    r"Optimizer\.validate$",
    r"ParallelOptimizer\._optimize_impl$",
    r"Predictor\.predict$",
    r"Evaluator\.test$",
    r"ServingRuntime\._dispatch$",
    r"MicroBatcher\._loop$",
    r"FleetRouter\._loop$",
    r"FleetRouter\._complete_loop$",
    r"FleetAutoscaler\._loop$",
    r"DeviceFeed\._worker$",
    r"DeviceFeed\.__next__$",
    r"InlineFeed\.__next__$",
    r"AsyncCheckpointer\._run$",
)

_HOT_PROPAGATION_DEPTH = 3
_SUPPRESS_RE = re.compile(r"#\s*tpu-lint:\s*disable=([\w\-,\s]+)")

_SYNC_BUILTINS = {"float", "int", "bool"}
_SYNC_METHODS = {"item", "tolist"}
# numpy entry points that force a d2h copy when handed a jax array
_NP_ROOTS = {"np", "numpy"}
_JNP_ROOTS = {"jnp"}
_BLOCKING_CALLS = {
    "open", "input",
    "time.sleep",
    "os.system", "os.popen",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "requests.get", "requests.post", "requests.put", "requests.request",
    "urllib.request.urlopen",
    "socket.socket", "socket.create_connection",
}
# loggers are async-ish and deliberate; never blocking-io findings
_BLOCKING_EXEMPT_ROOTS = {"logger", "logging"}
# stdlib roots whose calls produce host values (kills the
# `int(_STEP_RE.match(name).group(1))` class of false positives)
_HOST_ROOTS = {
    "os", "time", "re", "json", "math", "random", "itertools",
    "functools", "collections", "string", "pathlib", "logging", "sys",
    "io", "struct", "pickle", "hashlib", "glob", "shutil", "tempfile",
    "threading", "queue", "dataclasses", "copy", "warnings", "enum",
    "len", "range", "enumerate", "zip", "sorted", "min", "max", "sum",
    "abs", "str", "repr", "list", "dict", "set", "tuple", "frozenset",
    "isinstance", "hasattr", "getattr", "type", "id", "deque",
}
# numpy entry points that WRITE rather than convert: their transfer is
# the deliberate spill (async checkpoint writer), not a hot-loop sync
_NP_WRITERS = {"savez", "savez_compressed", "save", "load", "errstate",
               "seterr"}
# jax APIs that return plain host values (topology queries, config) —
# without this, `if jax.process_count() > 1:` reads as a device branch
_JAX_HOST_CALLS = {
    "jax.process_count", "jax.process_index", "jax.device_count",
    "jax.local_device_count", "jax.devices", "jax.local_devices",
    "jax.default_backend",
}


# ---------------------------------------------------------------------------
# taint lattice
# ---------------------------------------------------------------------------

class TS:
    """Taint state: kind in {DEV, HOST, UNK} + originating param indices."""

    __slots__ = ("kind", "params")

    def __init__(self, kind: str, params: frozenset = frozenset()):
        self.kind = kind
        self.params = params

    def __repr__(self):  # pragma: no cover - debug aid
        return f"TS({self.kind},{sorted(self.params)})"


def _join(a: TS, b: TS) -> TS:
    if a.kind == "DEV" or b.kind == "DEV":
        kind = "DEV"
    elif a.kind == "UNK" or b.kind == "UNK":
        kind = "UNK"
    else:
        kind = "HOST"
    return TS(kind, a.params | b.params)


_HOST = TS("HOST")
_UNK = TS("UNK")
_DEV = TS("DEV")


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------

@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    func: str
    message: str
    code: str = ""

    def fingerprint(self) -> str:
        """Stable id: survives line shifts (no line number), breaks when the
        offending code itself changes — the baseline then forces a re-look."""
        key = f"{self.rule}|{self.path}|{self.func}|{self.code.strip()}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def render(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}"
        return f"{loc}: {self.rule} [{self.func}] {self.message}"


# ---------------------------------------------------------------------------
# module indexing
# ---------------------------------------------------------------------------

@dataclass
class FuncInfo:
    qualname: str
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    path: str
    class_name: Optional[str]
    parent: Optional["FuncInfo"]
    is_jit: bool = False
    donate: Tuple[int, ...] = ()
    hot: bool = False
    # summaries (fixpoint over the project)
    returns_device: bool = False
    returns_host: bool = False
    syncing_params: Set[int] = field(default_factory=set)
    calls: Set[str] = field(default_factory=set)  # bare callee names


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'jax.random.fold_in' for nested Attribute/Name, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> Tuple[bool, Tuple[int, ...]]:
    """Is `node` a jit-producing expression?  Returns (is_jit, donate)."""
    if isinstance(node, ast.Call):
        chain = _attr_chain(node.func)
        if chain in ("jax.jit", "jit", "pjit", "jax.pjit", "partial",
                     "functools.partial"):
            inner_jit = chain not in ("partial", "functools.partial")
            if not inner_jit and node.args:
                inner_jit, _ = _is_jit_expr(node.args[0])
            donate: Tuple[int, ...] = ()
            for kw in node.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames") and \
                        isinstance(kw.value, (ast.Tuple, ast.List)):
                    donate = tuple(
                        e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int))
                elif kw.arg == "donate_argnums" and \
                        isinstance(kw.value, ast.Constant) and \
                        isinstance(kw.value.value, int):
                    donate = (kw.value.value,)
            return inner_jit, donate
        if chain in ("jax.shard_map", "shard_map", "jax.experimental."
                     "shard_map.shard_map", "jax.pmap", "pmap"):
            # traced like jit for the purposes of tracer/self rules
            return True, ()
    if isinstance(node, (ast.Name, ast.Attribute)):
        chain = _attr_chain(node)
        if chain in ("jax.jit", "jit", "pjit", "jax.pjit"):
            return True, ()
    return False, ()


class _ModuleIndex(ast.NodeVisitor):
    """One pass per file: functions with qualnames, jit marks, suppressions
    already parsed by the caller, thread/queue bookkeeping for the
    concurrency rules."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path
        self.functions: List[FuncInfo] = []
        self._stack: List[FuncInfo] = []
        self._class: List[str] = []
        # name (local or attr tail) -> donated indices, for call-site checks
        self.donated_names: Dict[str, Tuple[int, ...]] = {}
        self.jit_names: Set[str] = set()
        self.visit(tree)
        self._mark_wrapped(tree)

    # -- function collection ------------------------------------------------
    def _qualname(self, name: str) -> str:
        bits = list(self._class)
        bits += [f.name for f in self._stack]
        bits.append(name)
        return ".".join(bits)

    def visit_ClassDef(self, node: ast.ClassDef):
        self._class.append(node.name)
        self.generic_visit(node)
        self._class.pop()

    def _visit_func(self, node):
        is_jit, donate = False, ()
        for dec in node.decorator_list:
            j, d = _is_jit_expr(dec)
            if j:
                is_jit, donate = True, d
        info = FuncInfo(
            qualname=self._qualname(node.name), name=node.name, node=node,
            path=self.path,
            class_name=self._class[-1] if self._class else None,
            parent=self._stack[-1] if self._stack else None,
            is_jit=is_jit, donate=donate)
        self.functions.append(info)
        self._stack.append(info)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- wrapped jit: `f2 = jax.jit(f)`, `return jax.jit(f, ...)` -----------
    def _mark_wrapped(self, tree: ast.Module):
        by_name: Dict[str, List[FuncInfo]] = {}
        for f in self.functions:
            by_name.setdefault(f.name, []).append(f)

        def mark(call: ast.Call, target: Optional[str]):
            is_jit, donate = _is_jit_expr(call)
            if not is_jit:
                return
            args = call.args
            if _attr_chain(call.func) in ("partial", "functools.partial"):
                args = call.args[1:]
            wrapped = args[0] if args else None
            if isinstance(wrapped, ast.Name) and wrapped.id in by_name:
                for f in by_name[wrapped.id]:
                    f.is_jit = True
                    f.donate = f.donate or donate
            if target:
                self.jit_names.add(target)
                if donate:
                    self.donated_names[target] = donate

        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                for t in node.targets:
                    name = None
                    if isinstance(t, ast.Name):
                        name = t.id
                    elif isinstance(t, ast.Attribute):
                        name = t.attr  # self._fwd = jax.jit(...)
                    mark(node.value, name)
            elif isinstance(node, ast.Return) and \
                    isinstance(node.value, ast.Call):
                mark(node.value, None)
            elif isinstance(node, ast.Call):
                mark(node, None)


def _parse_suppressions(text: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            out[i] = rules
    return out


# ---------------------------------------------------------------------------
# the per-function rule walker
# ---------------------------------------------------------------------------

class _FuncWalker:
    """Taint walk over one function body, emitting findings.

    Flow-insensitive within a statement list (assignments update the env
    in textual order); loop bodies are walked twice so second-iteration
    hazards (donated buffer reuse, cached device values) are seen."""

    def __init__(self, proj: "Project", idx: _ModuleIndex, info: FuncInfo):
        self.proj = proj
        self.idx = idx
        self.info = info
        self.findings: List[Finding] = []
        self.env: Dict[str, TS] = {}
        self.for_depth = 0
        self.loop_depth = 0
        self.lock_depth = 0
        self.return_state: TS = _HOST
        self.param_sinks: Set[int] = set()
        node = info.node
        self.env.update(getattr(idx, "module_env", {}))
        args = getattr(node, "args", None)
        self.param_names: List[str] = []
        if args is not None:
            all_args = list(args.posonlyargs) + list(args.args)
            for i, a in enumerate(all_args):
                self.param_names.append(a.arg)
                base = _DEV if info.is_jit else _UNK
                self.env[a.arg] = TS(base.kind, frozenset({i}))
            for a in list(args.kwonlyargs):
                self.env[a.arg] = _UNK

    # -- driver -------------------------------------------------------------
    def run(self):
        body = getattr(self.info.node, "body", [])
        self.walk_stmts(body)
        return self

    def emit(self, rule: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 0)
        src = self.proj.source_lines.get(self.info.path, [])
        code = src[line - 1] if 0 < line <= len(src) else ""
        self.findings.append(Finding(
            rule=rule, path=self.info.path, line=line,
            col=getattr(node, "col_offset", 0), func=self.info.qualname,
            message=message, code=code))

    # -- statements ---------------------------------------------------------
    def walk_stmts(self, stmts: Sequence[ast.stmt]):
        for st in stmts:
            self.walk_stmt(st)

    def walk_stmt(self, st: ast.stmt):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # nested defs get their own walker
        if isinstance(st, ast.Assign):
            val = self.eval_expr(st.value)
            for t in st.targets:
                self.assign(t, val, st)
            return
        if isinstance(st, ast.AugAssign):
            val = _join(self.eval_expr(st.value),
                        self.eval_expr(st.target))
            self.assign(st.target, val, st)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self.assign(st.target, self.eval_expr(st.value), st)
            return
        if isinstance(st, ast.Return):
            if st.value is not None:
                self.return_state = _join(self.return_state,
                                          self.eval_expr(st.value))
            return
        if isinstance(st, ast.Expr):
            self.eval_expr(st.value)
            return
        if isinstance(st, ast.If):
            self.check_branch(st.test)
            self.eval_expr(st.test)
            self.walk_stmts(st.body)
            self.walk_stmts(st.orelse)
            return
        if isinstance(st, ast.While):
            self.check_branch(st.test)
            self.eval_expr(st.test)
            self.loop_depth += 1
            self.walk_stmts(st.body)
            self.walk_stmts(st.body)  # loop reentry
            self.loop_depth -= 1
            self.walk_stmts(st.orelse)
            return
        if isinstance(st, ast.For):
            it = self.eval_expr(st.iter)
            self.assign(st.target, TS(it.kind, it.params), st)
            self.for_depth += 1
            self.loop_depth += 1
            self.walk_stmts(st.body)
            self.walk_stmts(st.body)  # loop reentry
            self.loop_depth -= 1
            self.for_depth -= 1
            self.walk_stmts(st.orelse)
            return
        if isinstance(st, ast.With):
            locky = any(
                "lock" in (_attr_chain(item.context_expr.func
                           if isinstance(item.context_expr, ast.Call)
                           else item.context_expr) or "").lower()
                for item in st.items)
            for item in st.items:
                v = self.eval_expr(item.context_expr)
                if item.optional_vars is not None:
                    self.assign(item.optional_vars, v, st)
            if locky:
                self.lock_depth += 1
            self.walk_stmts(st.body)
            if locky:
                self.lock_depth -= 1
            return
        if isinstance(st, ast.Try):
            self.walk_stmts(st.body)
            for h in st.handlers:
                self.walk_stmts(h.body)
            self.walk_stmts(st.orelse)
            self.walk_stmts(st.finalbody)
            return
        if isinstance(st, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self.eval_expr(child)
            return
        # Pass/Break/Continue/Import/Global/Nonlocal/Delete: nothing to taint
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self.eval_expr(child)

    def assign(self, target: ast.expr, val: TS, st: ast.stmt):
        if isinstance(target, ast.Name):
            self.env[target.id] = val
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self.assign(e, TS(val.kind, val.params), st)
            return
        if isinstance(target, ast.Attribute):
            # tracer-leak: storing a traced value on long-lived state from
            # inside jitted code leaks the tracer out of the trace
            if self.info.is_jit and val.kind == "DEV":
                self.emit("tracer-leak", st,
                          "traced value stored on "
                          f"`{_attr_chain(target) or 'attribute'}` inside "
                          "jitted code — the tracer escapes the trace")
            self.eval_expr(target.value)
            return
        if isinstance(target, ast.Subscript):
            if self.info.is_jit and val.kind == "DEV":
                base = _attr_chain(target.value)
                if base is None or not self._is_local(target.value):
                    self.emit("tracer-leak", st,
                              "traced value stored into captured container "
                              "inside jitted code")
            self.eval_expr(target.value)
            return

    def _is_local(self, node: ast.expr) -> bool:
        return isinstance(node, ast.Name) and node.id in self.env

    # -- branches -----------------------------------------------------------
    def _branch_taint(self, test: ast.expr) -> TS:
        """Taint of a branch condition, looking THROUGH comparisons and
        boolean combinators: eval_expr deliberately types `a == b` as HOST
        (flagging every comparison is noise), but at a branch site the
        comparison's device operands are what gets concretized."""
        if isinstance(test, ast.Compare):
            out = self.eval_expr(test.left)
            for c in test.comparators:
                out = _join(out, self.eval_expr(c))
            return out
        if isinstance(test, ast.BoolOp):
            out = _HOST
            for v in test.values:
                out = _join(out, self._branch_taint(v))
            return out
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._branch_taint(test.operand)
        return self.eval_expr(test)

    def check_branch(self, test: ast.expr):
        st = self._branch_taint(test)
        if self.info.is_jit and st.kind == "DEV":
            self.emit("host-sync", test,
                      "Python branch on a traced value inside jitted code "
                      "(forces concretization; trace error or silent "
                      "constant-fold)")
        elif self.info.hot and st.kind == "DEV":
            self.emit("host-sync", test,
                      "branch on a device value in a hot-path function "
                      "(implicit bool() device sync)")

    # -- expressions --------------------------------------------------------
    def eval_expr(self, node: ast.expr) -> TS:
        if isinstance(node, ast.Call):
            return self.eval_call(node)
        if isinstance(node, ast.Name):
            return self.env.get(node.id, _UNK)
        if isinstance(node, ast.Constant):
            return _HOST
        if isinstance(node, ast.Attribute):
            base = self.eval_expr(node.value)
            chain = _attr_chain(node)
            if chain and chain.split(".")[0] in _JNP_ROOTS:
                return _DEV
            if isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and self.info.class_name:
                cls_env = getattr(self.idx, "class_envs", {}).get(
                    self.info.class_name, {})
                if node.attr in cls_env:
                    return cls_env[node.attr]
            if base.kind == "DEV" and node.attr in ("at", "T", "real",
                                                    "imag", "mT"):
                return base
            if base.kind == "DEV" and node.attr in ("shape", "ndim",
                                                    "dtype", "size",
                                                    "sharding"):
                return _HOST  # static metadata, no transfer
            return TS("UNK", base.params)
        if isinstance(node, ast.Subscript):
            base = self.eval_expr(node.value)
            self.eval_expr(node.slice)
            return base
        if isinstance(node, ast.BinOp):
            return _join(self.eval_expr(node.left),
                         self.eval_expr(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.eval_expr(node.operand)
        if isinstance(node, ast.BoolOp):
            out = _HOST
            for v in node.values:
                out = _join(out, self.eval_expr(v))
            return out
        if isinstance(node, ast.Compare):
            self.eval_expr(node.left)
            for c in node.comparators:
                self.eval_expr(c)
            return _HOST  # comparison of device values yields a device
            # bool, but flagging every `==` is noise; branch checks catch
            # the harmful consumption
        if isinstance(node, ast.IfExp):
            self.eval_expr(node.test)
            return _join(self.eval_expr(node.body),
                         self.eval_expr(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = _HOST
            for e in node.elts:
                out = _join(out, self.eval_expr(e))
            return out
        if isinstance(node, ast.Dict):
            out = _HOST
            for k, v in zip(node.keys, node.values):
                if k is not None:
                    self.eval_expr(k)
                out = _join(out, self.eval_expr(v))
            return out
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return _UNK  # comprehension envs are their own scope; UNK keeps
            # the in-loop heuristic from firing on summary math
        if isinstance(node, ast.Starred):
            return self.eval_expr(node.value)
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self.eval_expr(v.value)
            return _HOST
        if isinstance(node, ast.Lambda):
            return _HOST
        if isinstance(node, ast.NamedExpr):
            val = self.eval_expr(node.value)
            self.assign(node.target, val, node)
            return val
        return _UNK

    # -- calls (where every rule except concurrency lives) -------------------
    def eval_call(self, node: ast.Call) -> TS:
        chain = _attr_chain(node.func)
        arg_states = [self.eval_expr(a) for a in node.args]
        for kw in node.keywords:
            self.eval_expr(kw.value)
        root = chain.split(".")[0] if chain else None

        # blocking-io
        if chain in _BLOCKING_CALLS and root not in _BLOCKING_EXEMPT_ROOTS:
            if self.info.is_jit:
                self.emit("blocking-io", node,
                          f"blocking call `{chain}` inside jitted code")
            elif self.info.hot and self.loop_depth > 0:
                self.emit("blocking-io", node,
                          f"blocking call `{chain}` inside a hot-path loop")
        if chain == "print" and self.info.is_jit:
            self.emit("blocking-io", node,
                      "print() inside jitted code (runs at trace time "
                      "only, or forces a callback)")

        # explicit transfer APIs: sanctioned, never findings
        if chain in ("jax.device_get",):
            return _HOST
        if chain in _JAX_HOST_CALLS:
            return _HOST
        if chain in ("jax.device_put",
                     "jax.make_array_from_process_local_data"):
            return _DEV

        # host-sync sinks ----------------------------------------------------
        if chain in _SYNC_BUILTINS and len(node.args) >= 1:
            self._sink(node, arg_states[0], f"{chain}()")
            return _HOST
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS:
            obj = self.eval_expr(node.func.value)
            self._sink(node, obj, f".{node.func.attr}()")
            return _HOST
        if root in _NP_ROOTS:
            if chain.split(".")[-1] not in _NP_WRITERS:
                for a_st in arg_states:
                    self._sink(node, a_st, f"{chain}()")
            return _HOST
        if chain in ("jax.tree_util.tree_map", "jax.tree.map",
                     "tree_map") and len(node.args) >= 2:
            f0 = node.args[0]
            f0_chain = _attr_chain(f0)
            if f0_chain and f0_chain.split(".")[0] in _NP_ROOTS:
                for a_st in arg_states[1:]:
                    self._sink(node, a_st, f"tree_map({f0_chain}, ...)")
                return _HOST
            if f0_chain and f0_chain.split(".")[0] in _JNP_ROOTS:
                return _DEV
            return _UNK

        # stdlib / builtin host producers
        if root in _HOST_ROOTS or chain in _HOST_ROOTS:
            return _HOST

        # device producers
        if root in _JNP_ROOTS:
            return _DEV
        if root == "jax":
            return _DEV  # jax.random / jax.lax / jax.nn / grad etc.

        # self/local/method calls: name-level resolution + summaries
        bname = None
        if isinstance(node.func, ast.Name):
            bname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            bname = node.func.attr
        infos = self.proj.by_name.get(bname, []) if bname else []
        jitted_callee = any(f.is_jit for f in infos) or \
            (bname in self.idx.jit_names)
        if bname and infos:
            self.info.calls.add(bname)

        # recompile: host scalar into a known-jitted callable per step.
        # Only confidently-HOST args fire — UNK covers staged batches and
        # `self.<attr>` trees that are device-resident at runtime.
        if jitted_callee and self.info.hot and self.for_depth > 0 \
                and not self.info.is_jit:
            for a, a_st in zip(node.args, arg_states):
                if a_st.kind == "HOST" and not isinstance(a, ast.Constant):
                    self.emit(
                        "recompile", node,
                        f"host value passed to jitted `{bname}` inside "
                        "a hot loop — implicit h2d put per step (stage "
                        "with jax.device_put once, or keep it on device)")

        # call-site host-sync through a syncing callee summary
        if infos and all(f.syncing_params for f in infos):
            common: Set[int] = set.intersection(
                *[f.syncing_params for f in infos])
            for i in common:
                if i < len(arg_states) and \
                        arg_states[i].kind == "DEV" and \
                        (self.info.hot or self.info.is_jit):
                    self.emit(
                        "host-sync", node,
                        f"device value flows into `{bname}` which syncs "
                        f"its argument {i} to host (float()/int()/"
                        ".item() in its body)")
        if jitted_callee or (infos and all(f.returns_device
                                           for f in infos)):
            return _DEV  # jitted callables return device values
        if infos:
            if all(f.returns_host for f in infos):
                return _HOST
            return _UNK

        # method on an object: device stays device, host stays host
        if isinstance(node.func, ast.Attribute):
            obj = self.eval_expr(node.func.value)
            if obj.kind == "DEV":
                return TS("DEV", obj.params)
            if obj.kind == "HOST":
                return _HOST
            return _UNK
        return _UNK

    def _sink(self, node: ast.AST, st: TS, what: str):
        if st.kind == "DEV" and (self.info.hot or self.info.is_jit):
            where = "jitted code" if self.info.is_jit else \
                "a hot-path function"
            self.emit("host-sync", node,
                      f"{what} on a device value in {where} — d2h sync "
                      "stalls the dispatch pipeline (batch into the "
                      "one-transfer summary path or use jax.device_get "
                      "at a sanctioned boundary)")
        elif st.kind == "UNK" and self.info.hot and self.for_depth > 0 \
                and not self.info.is_jit:
            self.emit("host-sync", node,
                      f"{what} on a possibly-device value inside a "
                      "hot-path loop — if this is a jax array it is a "
                      "per-step d2h sync")
        if st.params:
            self.param_sinks |= st.params



# ---------------------------------------------------------------------------
# concurrency rules (class-granular, not taint-based)
# ---------------------------------------------------------------------------

def _concurrency_findings(proj: "Project", idx: _ModuleIndex,
                          tree: ast.Module) -> List[Finding]:
    findings: List[Finding] = []
    src = proj.source_lines.get(idx.path, [])

    def mk(rule, node, func, msg):
        line = getattr(node, "lineno", 0)
        code = src[line - 1] if 0 < line <= len(src) else ""
        findings.append(Finding(rule=rule, path=idx.path, line=line,
                                col=getattr(node, "col_offset", 0),
                                func=func, message=msg, code=code))

    classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
    scopes: List[Tuple[str, List[ast.stmt]]] = [
        (c.name, c.body) for c in classes]
    top = [s for s in tree.body
           if not isinstance(s, (ast.ClassDef,))]
    scopes.append(("<module>", top))

    for scope_name, body in scopes:
        scope_src = ast.Module(body=list(body), type_ignores=[])
        thread_targets: Set[str] = set()      # worker method/fn names
        thread_creations: List[Tuple[ast.Call, str, bool]] = []
        proc_creations: List[Tuple[ast.Call, str, bool]] = []
        queue_attrs: Set[str] = set()
        joined_names: Set[str] = set()
        container_attrs: Set[str] = set()
        # attr -> {method} for container mutations, split by lock coverage
        mut_by_method: Dict[str, Dict[str, bool]] = {}

        # worker creations: threads AND multiprocessing child processes
        # (`multiprocessing.Process`, `mp.Process`, `ctx.Process`, ... —
        # matched by last chain segment so a stored start-method context
        # like `self._ctx.Process(...)` counts too)
        for node in ast.walk(scope_src):
            if isinstance(node, ast.Call):
                chain = _attr_chain(node.func)
                last = chain.split(".")[-1] if chain else ""
                if chain in ("threading.Thread", "Thread") \
                        or last == "Process":
                    daemon = any(
                        kw.arg == "daemon" and
                        isinstance(kw.value, ast.Constant) and
                        kw.value.value is True
                        for kw in node.keywords)
                    for kw in node.keywords:
                        if kw.arg == "target":
                            t = _attr_chain(kw.value)
                            if t:
                                thread_targets.add(t.split(".")[-1])
                    (proc_creations if last == "Process"
                     else thread_creations).append(
                        (node, scope_name, daemon))
                elif chain and chain.endswith(".join"):
                    base = _attr_chain(node.func.value) \
                        if isinstance(node.func, ast.Attribute) else None
                    if base:
                        joined_names.add(base.split(".")[-1])
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            if targets and isinstance(node.value, ast.Call):
                vchain = _attr_chain(node.value.func)
                for t in targets:
                    aname = t.attr if isinstance(t, ast.Attribute) else (
                        t.id if isinstance(t, ast.Name) else None)
                    if aname is None:
                        continue
                    # thread queues AND multiprocessing queues
                    # (mp.Queue / ctx.Queue / JoinableQueue): suffix
                    # match, same bounded put/get discipline either way
                    if vchain and vchain.split(".")[-1] in (
                            "Queue", "SimpleQueue", "LifoQueue",
                            "JoinableQueue"):
                        queue_attrs.add(aname)
                    if vchain in ("list", "dict", "set"):
                        container_attrs.add(aname)
            if targets and isinstance(node.value,
                                      (ast.List, ast.Dict, ast.Set)):
                for t in targets:
                    if isinstance(t, ast.Attribute):
                        container_attrs.add(t.attr)

        # thread/process without daemon and without any .join in scope
        for creations, what, leak in (
                (thread_creations, "thread",
                 "leaks past interpreter exit and test teardown"),
                (proc_creations, "child process",
                 "orphans past parent exit, holding pipes and the "
                 "inherited file descriptors")):
            for call, sname, daemon in creations:
                if daemon:
                    continue
                # the created worker is joined if ANY name in this scope
                # is joined — name-level, deliberately permissive
                if joined_names:
                    continue
                mk("concurrency", call, f"{sname}",
                   f"{what} created with neither daemon=True nor a "
                   f"join() on any shutdown path — {leak}")

        owns_thread = bool(thread_creations) or bool(proc_creations) \
            or bool(thread_targets)
        if not owns_thread:
            continue
        owns_procs = bool(proc_creations)

        def scan_call(e: ast.Call, method_name: str, lock_depth: int):
            if not isinstance(e.func, ast.Attribute):
                return
            base = e.func.value
            aname = base.attr if isinstance(base, ast.Attribute) \
                else (base.id if isinstance(base, ast.Name) else None)
            meth = e.func.attr
            if aname in queue_attrs:
                has_bound = any(kw.arg in ("timeout", "block")
                                for kw in e.keywords) or len(e.args) > 1
                if meth in ("put", "get") and not has_bound:
                    mk("concurrency", e, f"{scope_name}.{method_name}",
                       f"`{aname}.{meth}()` without timeout in a "
                       "thread-owning class — hangs forever if the peer "
                       "thread died (bound it and poll aliveness)")
                if meth == "join" and method_name in (
                        "close", "stop", "shutdown", "wait",
                        "__exit__", "__del__"):
                    mk("concurrency", e, f"{scope_name}.{method_name}",
                       f"`{aname}.join()` (queue join, no timeout "
                       "possible) on a shutdown path — replace with a "
                       "bounded wait on all_tasks_done")
            elif owns_procs and meth == "join" and not e.args and not any(
                    kw.arg == "timeout" for kw in e.keywords) \
                    and method_name in ("close", "stop", "shutdown",
                                        "__exit__", "__del__"):
                # process-owning scope: an unbounded join on a shutdown
                # path deadlocks the parent when a child died mid-put
                # with the queue full (its feeder thread never flushes)
                mk("concurrency", e, f"{scope_name}.{method_name}",
                   f"unbounded `{aname or '<expr>'}.join()` on a "
                   "shutdown path of a process-owning class — a child "
                   "blocked flushing a full mp queue never exits; join "
                   "with a timeout, then terminate()/kill()")
            if aname in container_attrs and meth in (
                    "append", "extend", "pop", "remove", "clear",
                    "update", "add", "insert", "popitem", "setdefault"):
                d = mut_by_method.setdefault(aname, {})
                # True == at least one unlocked mutation in this method
                d[method_name] = d.get(method_name, False) or \
                    lock_depth == 0

        def walk_method(stmts, method_name: str, lock_depth: int):
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, ast.With):
                    locky = any(
                        "lock" in (_attr_chain(
                            i.context_expr.func
                            if isinstance(i.context_expr, ast.Call)
                            else i.context_expr) or "").lower()
                        for i in st.items)
                    for i in st.items:
                        for sub in ast.walk(i.context_expr):
                            if isinstance(sub, ast.Call):
                                scan_call(sub, method_name, lock_depth)
                    walk_method(st.body, method_name,
                                lock_depth + (1 if locky else 0))
                    continue
                if isinstance(st, (ast.If, ast.For, ast.While, ast.Try)):
                    for e in ast.iter_child_nodes(st):
                        if isinstance(e, ast.expr):
                            for sub in ast.walk(e):
                                if isinstance(sub, ast.Call):
                                    scan_call(sub, method_name, lock_depth)
                    for block in (getattr(st, "body", []),
                                  getattr(st, "orelse", []),
                                  getattr(st, "finalbody", [])):
                        walk_method(block, method_name, lock_depth)
                    for h in getattr(st, "handlers", []):
                        walk_method(h.body, method_name, lock_depth)
                    continue
                for sub in ast.walk(st):
                    if isinstance(sub, ast.Call):
                        scan_call(sub, method_name, lock_depth)

        for fn in [n for n in body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]:
            walk_method(fn.body, fn.name, 0)

        # shared container mutated by worker AND driver without lock
        for attr, methods in mut_by_method.items():
            worker_m = {m for m in methods if m in thread_targets}
            driver_m = set(methods) - worker_m
            if worker_m and driver_m:
                unlocked = [m for m, unl in methods.items() if unl]
                if unlocked:
                    first_fn = sorted(methods)[0]
                    node = next(
                        (n for n in body
                         if isinstance(n, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))
                         and n.name in unlocked), body[0])
                    mk("concurrency", node,
                       f"{scope_name}",
                       f"`self.{attr}` is mutated from worker "
                       f"({sorted(worker_m)}) and driver "
                       f"({sorted(driver_m)}) methods; mutation in "
                       f"{sorted(unlocked)} is not under a lock")
    return findings


# ---------------------------------------------------------------------------
# project driver
# ---------------------------------------------------------------------------

class Project:
    """All files under analysis: indexes, summaries, rule walks."""

    def __init__(self, hot_roots: Optional[Sequence[str]] = None):
        self.hot_roots = [re.compile(p)
                          for p in (hot_roots or DEFAULT_HOT_ROOTS)]
        self.indexes: List[_ModuleIndex] = []
        self.trees: Dict[str, ast.Module] = {}
        self.source_lines: Dict[str, List[str]] = {}
        self.suppressions: Dict[str, Dict[int, Set[str]]] = {}
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.lock_graph = None  # set by run() (concurrency.LockGraph)

    def add_source(self, path: str, text: str):
        try:
            tree = ast.parse(text, filename=path)
        except SyntaxError as e:  # pragma: no cover - defensive
            raise ValueError(f"{path}: {e}") from e
        self.trees[path] = tree
        self.source_lines[path] = text.splitlines()
        self.suppressions[path] = _parse_suppressions(text)
        idx = _ModuleIndex(path, tree)
        self.indexes.append(idx)
        for f in idx.functions:
            self.by_name.setdefault(f.name, []).append(f)

    # -- hot propagation ----------------------------------------------------
    def _mark_hot(self):
        for idx in self.indexes:
            for f in idx.functions:
                if any(p.search(f.qualname) for p in self.hot_roots):
                    f.hot = True
        # nested defs inherit the enclosing function's heat
        changed = True
        while changed:
            changed = False
            for idx in self.indexes:
                for f in idx.functions:
                    if not f.hot and f.parent is not None and f.parent.hot:
                        f.hot = True
                        changed = True
        # same-module callee propagation, depth-bounded
        for _ in range(_HOT_PROPAGATION_DEPTH):
            spread = False
            for idx in self.indexes:
                local = {f.name: f for f in idx.functions}
                for f in idx.functions:
                    if not f.hot:
                        continue
                    for callee in f.calls:
                        g = local.get(callee)
                        if g is not None and not g.hot and not g.is_jit:
                            g.hot = True
                            spread = True
            if not spread:
                break

    # -- run ----------------------------------------------------------------
    def _module_env(self, idx: _ModuleIndex) -> Dict[str, TS]:
        """Taint module-level `NAME = expr` bindings so function walks see
        e.g. `_STEP_RE = re.compile(...)` as HOST and module jit wrappers
        as device producers."""
        fake = FuncInfo(qualname="<module>", name="<module>",
                        node=ast.parse("def _m(): pass").body[0],
                        path=idx.path, class_name=None, parent=None)
        w = _FuncWalker(self, idx, fake)
        env: Dict[str, TS] = {}
        for st in self.trees[idx.path].body:
            targets = []
            if isinstance(st, ast.Assign):
                targets = st.targets
            elif isinstance(st, ast.AnnAssign) and st.value is not None:
                targets = [st.target]
            else:
                continue
            val = w.eval_expr(st.value)
            for t in targets:
                if isinstance(t, ast.Name):
                    env[t.id] = val
        return env

    def _class_envs(self, idx: _ModuleIndex) -> Dict[str, Dict[str, TS]]:
        """Taint `self.X = expr` bindings from each class's __init__ so the
        driver-state dict of host ints reads as HOST and jit-wrapped
        callables on self read as device producers."""
        envs: Dict[str, Dict[str, TS]] = {}
        for f in idx.functions:
            if f.name != "__init__" or f.class_name is None:
                continue
            w = _FuncWalker(self, idx, f)
            env = envs.setdefault(f.class_name, {})
            for st in ast.walk(f.node):
                if isinstance(st, ast.Assign):
                    targets = st.targets
                elif isinstance(st, ast.AnnAssign) and st.value is not None:
                    targets = [st.target]
                else:
                    continue
                val = w.eval_expr(st.value)
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        env[t.attr] = val
        return envs

    def run(self) -> List[Finding]:
        # fixpoint over summaries: 3 passes covers the call depth the
        # codebase actually has (test -> to_result, _current_lr ->
        # current_lr -> schedule)
        for _ in range(3):
            for idx in self.indexes:
                idx.module_env = self._module_env(idx)
            for idx in self.indexes:
                idx.class_envs = self._class_envs(idx)
            for idx in self.indexes:
                for f in idx.functions:
                    w = _FuncWalker(self, idx, f).run()
                    f.returns_device = w.return_state.kind == "DEV"
                    f.returns_host = w.return_state.kind == "HOST"
                    f.syncing_params = w.param_sinks
            self._mark_hot()

        findings: List[Finding] = []
        for idx in self.indexes:
            for f in idx.functions:
                w = _FuncWalker(self, idx, f).run()
                self._rule_self_in_jit(w, f)
                self._rule_donation_callsites(w, idx, f)
                findings.extend(w.findings)
            findings.extend(
                _concurrency_findings(self, idx, self.trees[idx.path]))
        # lock-discipline pass (lock-order / unguarded-state /
        # blocking-under-lock) — lazy import avoids a module cycle
        from bigdl_tpu.analysis import concurrency as _lockdisc
        lock_findings, self.lock_graph = \
            _lockdisc.analyze_lock_discipline(self)
        findings.extend(lock_findings)
        return self._apply_suppressions(findings)

    def _rule_self_in_jit(self, w: _FuncWalker, f: FuncInfo):
        """recompile: jitted body reading `self` — the closure is captured
        at trace time, so any later mutation of the object is silently
        stale AND unhashable state invites retraces."""
        if not f.is_jit or "self" in w.param_names:
            return
        # walk the WHOLE body, nested defs included: everything lexically
        # inside a jitted function traces into the same compiled program,
        # so a `self` read in an inner closure is just as frozen
        for node in ast.walk(f.node):
            if isinstance(node, ast.Name) and node.id == "self" and \
                    isinstance(node.ctx, ast.Load):
                w.emit("recompile", node,
                       "jitted code reads `self` at trace time — the "
                       "value is frozen into the compiled program (stale "
                       "closure) and retraces can multiply; hoist it to "
                       "a local before building the step")
                return  # one per function is enough

    def _rule_donation_callsites(self, w: _FuncWalker, idx: _ModuleIndex,
                                 f: FuncInfo):
        """donation: `r = step(a, ...)` where `a` is a donated position and
        `a` is read again before rebinding.  Implemented as a second walk
        that tracks textual order + loop reentry (walk_stmt runs loop
        bodies twice), piggybacking on _FuncWalker.donated_pending."""
        donated = idx.donated_names
        local_jit = {g.name: g.donate for g in idx.functions if g.donate}
        if not donated and not local_jit:
            return

        pending: Dict[str, Tuple[int, str]] = {}

        def scan_stmts(stmts):
            for st in stmts:
                scan(st)

        def process_expr(e: ast.expr):
            """Reads first (donation check), then record new donations."""
            for node in ast.walk(e):
                if isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in pending:
                    line, callee = pending.pop(node.id)
                    w.emit("donation", node,
                           f"`{node.id}` was donated to `{callee}` "
                           f"(line {line}) and read again — donated "
                           "buffers are deallocated after the call; "
                           "rebind the result or drop donate_argnums")
            for node in ast.walk(e):
                if isinstance(node, ast.Call):
                    name = None
                    if isinstance(node.func, ast.Name):
                        name = node.func.id
                    elif isinstance(node.func, ast.Attribute):
                        name = node.func.attr
                    idxs = donated.get(name) or local_jit.get(name)
                    if idxs:
                        for i in idxs:
                            if i < len(node.args) and isinstance(
                                    node.args[i], ast.Name):
                                pending[node.args[i].id] = (
                                    node.lineno, name)

        def clear_targets(targets):
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        pending.pop(sub.id, None)

        def scan(st):
            # compound statements: only their header expressions are
            # processed here; bodies recurse so a rebinding assignment
            # inside a loop clears its own donation before the reentry
            # walk re-reads the names
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                return
            if isinstance(st, ast.For):
                process_expr(st.iter)
                clear_targets([st.target])
                scan_stmts(st.body)
                scan_stmts(st.body)  # reentry: donate in iter 1, read in 2
                scan_stmts(st.orelse)
                return
            if isinstance(st, ast.While):
                process_expr(st.test)
                scan_stmts(st.body)
                scan_stmts(st.body)
                scan_stmts(st.orelse)
                return
            if isinstance(st, ast.If):
                process_expr(st.test)
                scan_stmts(st.body)
                scan_stmts(st.orelse)
                return
            if isinstance(st, ast.With):
                for item in st.items:
                    process_expr(item.context_expr)
                scan_stmts(st.body)
                return
            if isinstance(st, ast.Try):
                scan_stmts(st.body)
                for h in st.handlers:
                    scan_stmts(h.body)
                scan_stmts(st.orelse)
                scan_stmts(st.finalbody)
                return
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                if getattr(st, "value", None) is not None:
                    process_expr(st.value)
                targets = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                clear_targets(targets)
                return
            for node in ast.iter_child_nodes(st):
                if isinstance(node, ast.expr):
                    process_expr(node)

        scan_stmts(getattr(f.node, "body", []))

    def _apply_suppressions(self, findings: List[Finding]) -> List[Finding]:
        out = []
        seen = set()
        for fd in findings:
            key = (fd.rule, fd.path, fd.line, fd.func, fd.message)
            if key in seen:
                continue  # the double loop-body walk can duplicate
            seen.add(key)
            sup = self.suppressions.get(fd.path, {})
            rules = sup.get(fd.line, set())
            if fd.rule in rules or "all" in rules:
                continue
            # a suppression on the `def` line covers the whole function
            f = self._func_at(fd.path, fd.func)
            if f is not None:
                def_rules = sup.get(f.node.lineno, set())
                if fd.rule in def_rules or "all" in def_rules:
                    continue
            out.append(fd)
        out.sort(key=lambda fd: (fd.path, fd.line, fd.rule))
        return out

    def _func_at(self, path: str, qualname: str) -> Optional[FuncInfo]:
        for idx in self.indexes:
            if idx.path != path:
                continue
            for f in idx.functions:
                if f.qualname == qualname:
                    return f
        return None


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def analyze_sources(sources: Dict[str, str],
                    hot_roots: Optional[Sequence[str]] = None
                    ) -> List[Finding]:
    """Lint in-memory sources ({path: text}).  Test entry point."""
    proj = Project(hot_roots=hot_roots)
    for path, text in sources.items():
        proj.add_source(path, text)
    return proj.run()


def project_for_sources(sources: Dict[str, str],
                        hot_roots: Optional[Sequence[str]] = None
                        ) -> Project:
    """Like analyze_sources but returns the Project after the run, for
    callers that also want `project.lock_graph` (CLI dot dump, the
    static-vs-runtime reconciliation)."""
    proj = Project(hot_roots=hot_roots)
    for path, text in sources.items():
        proj.add_source(path, text)
    proj.findings = proj.run()
    return proj


def project_for_paths(paths: Sequence[str],
                      hot_roots: Optional[Sequence[str]] = None
                      ) -> Project:
    proj = Project(hot_roots=hot_roots)
    for fp in iter_python_files(paths):
        with open(fp, "r", encoding="utf-8") as fh:
            proj.add_source(fp, fh.read())
    proj.findings = proj.run()
    return proj


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", ".venv")]
            for name in sorted(files):
                if not name.endswith(".py"):
                    continue
                if name.endswith("_pb2.py") or name.endswith("_pb2_grpc.py"):
                    continue  # generated protobuf code
                out.append(os.path.join(root, name))
    return out


def analyze_paths(paths: Sequence[str],
                  hot_roots: Optional[Sequence[str]] = None
                  ) -> List[Finding]:
    proj = Project(hot_roots=hot_roots)
    for fp in iter_python_files(paths):
        with open(fp, "r", encoding="utf-8") as fh:
            proj.add_source(fp, fh.read())
    return proj.run()
