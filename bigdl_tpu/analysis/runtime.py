"""Runtime sanitizer: strict-transfer guard for hot sections.

The static linter (bigdl_tpu.analysis.linter) models host syncs it can
see in the AST; this module is the runtime backstop for the ones it
can't.  `strict_transfers()` wraps a hot section in
`jax.transfer_guard("disallow")`, so any IMPLICIT transfer inside —
`jnp.asarray(py_scalar)`, a Python scalar handed to a jitted call, a
numpy batch silently put to device mid-step — raises immediately at
the offending line instead of quietly serializing the dispatch
pipeline.

Explicit transfers (`jax.device_put` / `jax.device_get`) stay allowed:
they are the sanctioned boundary APIs the hot paths use deliberately.
Note the asymmetry on current jax (0.4.x): the guard intercepts
implicit host-to-device transfers reliably, while device-to-host pulls
via `__array__`/`float()` may pass — a full sync round-trip still
trips on its h2d half (e.g. `jnp.asarray(float(dev))`), and the static
host-sync rule covers the pull side.

The guard is thread/context-local: enabling it around the driver's
dispatch section does NOT affect the DeviceFeed worker's deliberate
H2D staging in its own thread.

Enable globally with `BIGDL_TPU_STRICT_TRANSFERS=1`, per-run with
`Optimizer.set_strict_transfers()` / `ServingRuntime(strict_transfers=
True)`, or per-test with the `strict_transfers` fixture in conftest.
"""

from __future__ import annotations

import contextlib
import os
from typing import Optional

import jax

ENV_FLAG = "BIGDL_TPU_STRICT_TRANSFERS"

_TRUTHY = ("1", "true", "yes", "on")


def strict_transfers_enabled(override: Optional[bool] = None) -> bool:
    """Resolve the strict-transfer switch: explicit override wins, else the
    BIGDL_TPU_STRICT_TRANSFERS environment variable.

    Reads the environment directly (not Engine config) so tests and
    debugging sessions can flip it without rebuilding cached config."""
    if override is not None:
        return bool(override)
    return os.environ.get(ENV_FLAG, "").strip().lower() in _TRUTHY


@contextlib.contextmanager
def strict_transfers(enabled: Optional[bool] = None):
    """Context manager: disallow implicit device transfers inside.

    `enabled=None` defers to the environment flag; False is a cheap
    no-op so hot loops can wrap their dispatch section unconditionally.
    """
    if not strict_transfers_enabled(enabled):
        yield
        return
    with jax.transfer_guard("disallow"):
        yield
