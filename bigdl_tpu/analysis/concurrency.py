"""Lock-discipline static analysis: lock graph, guarded state, blocking.

The serving stack is now heavily threaded — fleet dispatcher + settle
threads, per-replica MicroBatchers, the generation engine scheduler,
DeviceFeed/reader-pool workers, AsyncCheckpointer, watchdog — with ~20
files holding `threading.Lock/RLock/Condition`.  This module extends
the linter's module-index machinery with a whole-program model of that
locking, validated at runtime by `bigdl_tpu.analysis.lockdep` (every
edge lockdep observes must be predicted here — the reconciliation test
and `tools/lockdep_reconcile.py` enforce it).

Three rule families (`# tpu-lint: disable=<rule>` escapes apply):

  lock-order          the acquired-before graph must be a DAG.  Lock
                      attributes created in `__init__` (or at class /
                      module scope) become nodes; `with self._lock:`
                      blocks plus interprocedural propagation over the
                      call model become edges; a strong cycle or a
                      re-acquisition of a non-reentrant lock on a
                      self-call path is a deadlock waiting for the
                      right interleaving.  Never baselinable.
  unguarded-state     for each `self._x` accessed outside __init__ in a
                      thread-owning class, the guarding lock is
                      inferred by majority of access sites; minority
                      UNGUARDED reads/writes of state that worker and
                      driver threads share are flagged.  Baselinable
                      (some lock-free reads are deliberate — suppress
                      inline with the reason instead when possible).
  blocking-under-lock hot-root code that performs a blocking operation
                      while holding a lock: device dispatch or a
                      `.block_until_ready()`, `queue.get/put` without a
                      bound, `.result()`/`.wait()` without timeout,
                      file I/O / sleep / subprocess.  Every waiter on
                      that lock inherits the stall.  Never baselinable.

Precision model (kept deliberately two-tier):

  * STRONG call resolution — `self.m()` (same class), `self.attr.m()`
    where `attr`'s class is known from `__init__` (direct constructor
    call or a ctor parameter annotation), and uniquely-named module
    functions.  Strong edges feed cycle DETECTION.
  * WEAK resolution — any other `obj.m()` resolves name-level to every
    class method called `m` (bounded fan-out, generic container verbs
    excluded).  Weak edges land in the graph (so runtime reconciliation
    and `--lock-graph` stay complete) but never report cycles: a false
    deadlock from name collisions would train people to ignore the rule.

The runtime half keys locks by creation site (`file:line`), which is
exactly `LockSite.path/line` here — `LockGraph.site_index()` is the
join used for static-vs-runtime reconciliation.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from bigdl_tpu.analysis.linter import (
    _BLOCKING_CALLS,
    _attr_chain,
    Finding,
    FuncInfo,
)

# threading factories that create a lock node; value = reentrant.
# Event carries a hidden Condition(Lock()) that set/clear/wait acquire
# transiently — modelled so runtime edges into an Event's internal lock
# (keyed by the Event's creation site) reconcile against this graph
_LOCK_FACTORIES = {"Lock": False, "RLock": True, "Condition": True,
                   "Event": False}

# methods on an Event attr that acquire its internal lock
_EVENT_OPS = {"set", "clear", "wait"}

# attributes that are thread-plumbing, never guarded application state
_INFRA_SUFFIXES = {
    "Lock", "RLock", "Condition", "Event", "Semaphore",
    "BoundedSemaphore", "Barrier", "Thread", "Timer", "local",
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "JoinableQueue", "Process", "Value", "Array", "Pipe", "Manager",
}
_QUEUE_SUFFIXES = {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
                   "JoinableQueue"}

# method names too generic for name-level (weak) callee resolution —
# resolving `.get()` to every class's `get` would wire the graph into a
# near-clique of false edges
_WEAK_STOP = {
    "get", "put", "pop", "append", "extend", "add", "remove", "clear",
    "update", "insert", "join", "start", "items", "keys", "values",
    "setdefault", "sort", "index", "copy", "count", "read", "write",
    "flush", "close", "send", "recv", "result", "set", "is_set",
    "wait", "notify", "notify_all", "acquire", "release", "cancel",
    "done", "run", "popleft", "appendleft", "format", "split", "strip",
    "encode", "decode", "match", "search", "group",
}
_WEAK_MAX_TARGETS = 6

_ANON = "?"  # a `with <something locky>:` whose lock we cannot name


def norm_site(path: str, line: int) -> str:
    """Canonical creation-site key shared with the runtime half: abspath
    when the file exists (runtime frames always do), raw otherwise (toy
    in-memory sources in tests)."""
    p = os.path.abspath(path) if os.path.exists(path) else path
    return f"{p}:{int(line)}"


@dataclass
class LockSite:
    key: str          # "Class._attr" or "module._NAME"
    path: str
    line: int
    kind: str         # Lock | RLock | Condition

    @property
    def reentrant(self) -> bool:
        return _LOCK_FACTORIES.get(self.kind, False)

    def site(self) -> str:
        return norm_site(self.path, self.line)


@dataclass
class _Edge:
    strong: bool = False
    witness: List[Tuple[str, int, str, str]] = field(default_factory=list)
    # witness entries: (path, line, func qualname, via-description)


class LockGraph:
    """The inferred acquired-before relation over named lock sites."""

    def __init__(self):
        self.nodes: Dict[str, LockSite] = {}
        self.edges: Dict[Tuple[str, str], _Edge] = {}

    def add_node(self, s: LockSite):
        self.nodes.setdefault(s.key, s)

    def add_edge(self, a: str, b: str, strong: bool,
                 wit: Tuple[str, int, str, str]):
        e = self.edges.setdefault((a, b), _Edge())
        e.strong = e.strong or strong
        if len(e.witness) < 8 and wit not in e.witness:
            e.witness.append(wit)

    # -- cycle detection (strong edges only) --------------------------------

    def strong_sccs(self) -> List[List[str]]:
        """Tarjan over the strong subgraph; returns SCCs of size >= 2
        (self-loops are handled separately by the re-acquisition rule)."""
        adj: Dict[str, List[str]] = {}
        for (a, b), e in self.edges.items():
            if e.strong and a != b:
                adj.setdefault(a, []).append(b)
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str):
            # iterative Tarjan: (node, child-iterator) work stack
            work = [(v, iter(adj.get(v, ())))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(adj.get(w, ()))))
                        advanced = True
                        break
                    elif w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        out.append(sorted(scc))

        for v in list(adj):
            if v not in index:
                strongconnect(v)
        return out

    # -- export -------------------------------------------------------------

    def site_index(self) -> Dict[str, str]:
        """creation-site (`abspath:line`) -> lock key; the join key the
        runtime lockdep graph is reconciled through."""
        return {s.site(): k for k, s in self.nodes.items()}

    def to_dot(self) -> str:
        lines = ["digraph lock_order {", "  rankdir=LR;",
                 '  node [shape=box, fontsize=10];']
        for key in sorted(self.nodes):
            s = self.nodes[key]
            lines.append(
                f'  "{key}" [label="{key}\\n'
                f'{os.path.basename(s.path)}:{s.line} ({s.kind})"];')
        for (a, b) in sorted(self.edges):
            e = self.edges[(a, b)]
            wit = e.witness[0] if e.witness else ("?", 0, "?", "?")
            style = "" if e.strong else ", style=dashed"
            lines.append(
                f'  "{a}" -> "{b}" [label="{wit[2]}"'
                f'{style}];')
        lines.append("}")
        return "\n".join(lines) + "\n"

    def to_json(self) -> Dict:
        return {
            "version": 1,
            "nodes": {
                k: {"path": s.path, "line": s.line, "kind": s.kind,
                    "site": s.site()}
                for k, s in self.nodes.items()},
            "edges": [
                {"src": a, "dst": b, "strong": e.strong,
                 "witness": [
                     {"path": w[0], "line": w[1], "func": w[2],
                      "via": w[3]} for w in e.witness]}
                for (a, b), e in sorted(self.edges.items())],
        }


# ---------------------------------------------------------------------------
# per-class facts
# ---------------------------------------------------------------------------

@dataclass
class _ClassFacts:
    name: str
    path: str
    locks: Dict[str, LockSite] = field(default_factory=dict)  # attr -> site
    aliases: Dict[str, str] = field(default_factory=dict)     # attr -> attr
    infra_attrs: Set[str] = field(default_factory=set)
    queue_attrs: Set[str] = field(default_factory=set)
    attr_types: Dict[str, Set[str]] = field(default_factory=dict)
    method_names: Set[str] = field(default_factory=set)
    thread_targets: Set[str] = field(default_factory=set)  # worker methods
    owns_threads: bool = False


def _factory_kind(call: ast.Call) -> Optional[str]:
    chain = _attr_chain(call.func)
    if not chain:
        return None
    last = chain.split(".")[-1]
    if last in _LOCK_FACTORIES and (
            chain == last or chain == f"threading.{last}"):
        return last
    return None


def _ann_class_names(ann: ast.AST, classes: Set[str]) -> Set[str]:
    """Project-class names mentioned anywhere in an annotation — covers
    `C`, `Optional[C]`, `Union[A, B]` and string forms."""
    out: Set[str] = set()
    for node in ast.walk(ann):
        if isinstance(node, ast.Name) and node.id in classes:
            out.add(node.id)
        elif isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and node.value in classes:
            out.add(node.value)
    return out


def _iter_nodes(root: ast.AST) -> Iterable[ast.AST]:
    """ast.walk that does not descend into nested function/class bodies
    (those are analyzed as their own FuncInfo) nor lambdas."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            stack.append(child)


# ---------------------------------------------------------------------------
# the held-set walker (one per function)
# ---------------------------------------------------------------------------

class _Scan:
    """One pass over a function body tracking the set of held lock keys;
    records acquisitions, direct nestings, call sites (with held sets),
    `self.<attr>` accesses, and blocking operations under a lock."""

    def __init__(self, disc: "_Discipline", info: FuncInfo):
        self.disc = disc
        self.info = info
        self.cls = disc.class_facts.get(info.class_name) \
            if info.class_name else None
        self.acq_direct: Set[str] = set()
        self.pairs: List[Tuple[str, str, ast.AST]] = []
        # (held, targets, strong, self_call, node, via)
        self.calls: List[Tuple[Tuple[str, ...], Tuple[int, ...], bool,
                               bool, ast.AST, str]] = []
        self.accesses: List[Tuple[str, bool, Tuple[str, ...], ast.AST]] = []
        # (node, what, held, wait-receiver-key-or-None)
        self.blocking: List[Tuple[ast.AST, str, Tuple[str, ...],
                                  Optional[str]]] = []
        # (event key, held, node, method) — set/clear/wait acquire the
        # Event's internal lock transiently
        self.event_ops: List[Tuple[str, Tuple[str, ...], ast.AST,
                                   str]] = []

    # -- lock resolution ----------------------------------------------------

    def _lock_site(self, expr: ast.AST) -> Optional[LockSite]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id in ("self", "cls") and self.cls is not None:
            attr = self.cls.aliases.get(expr.attr, expr.attr)
            return self.cls.locks.get(attr)
        if isinstance(expr, ast.Name):
            mod = self.disc.module_locks.get(self.info.path, {})
            return mod.get(expr.id)
        return None

    def _lock_key(self, expr: ast.AST) -> Optional[str]:
        s = self._lock_site(expr)
        return s.key if s is not None else None

    def _with_entries(self, st: ast.With) -> List[Optional[str]]:
        out: List[Optional[str]] = []
        for item in st.items:
            key = self._lock_key(item.context_expr)
            if key is not None:
                out.append(key)
                continue
            chain = _attr_chain(
                item.context_expr.func
                if isinstance(item.context_expr, ast.Call)
                else item.context_expr) or ""
            out.append(_ANON if "lock" in chain.lower() else None)
        return out

    # -- walking ------------------------------------------------------------

    def run(self):
        self.walk(getattr(self.info.node, "body", []), ())
        return self

    def walk(self, stmts: Sequence[ast.stmt], held: Tuple[str, ...]):
        for st in stmts:
            self.stmt(st, held)

    def stmt(self, st: ast.stmt, held: Tuple[str, ...]):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return
        if isinstance(st, ast.With):
            keys = self._with_entries(st)
            for item in st.items:
                self.exprs(item.context_expr, held)
            inner = held
            for k in keys:
                if k is None:
                    continue
                if k != _ANON:
                    self.acq_direct.add(k)
                    for h in inner:
                        if h != _ANON and h != k:
                            self.pairs.append((h, k, st))
                inner = inner + (k,)
            self.walk(st.body, inner)
            return
        if isinstance(st, ast.If):
            self.exprs(st.test, held)
            self.walk(st.body, held)
            self.walk(st.orelse, held)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self.exprs(st.iter, held)
            self.walk(st.body, held)
            self.walk(st.orelse, held)
            return
        if isinstance(st, ast.While):
            self.exprs(st.test, held)
            self.walk(st.body, held)
            self.walk(st.orelse, held)
            return
        if isinstance(st, ast.Try):
            self.walk(st.body, held)
            for h in st.handlers:
                self.walk(h.body, held)
            self.walk(st.orelse, held)
            self.walk(st.finalbody, held)
            return
        self.exprs(st, held)

    def exprs(self, root: ast.AST, held: Tuple[str, ...]):
        """Process every expression node under `root` (no nested defs):
        calls and self-attribute accesses, in held-lock context."""
        call_funcs: Set[int] = set()
        nodes = list(_iter_nodes(root))
        for node in nodes:
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                call_funcs.add(id(node.func))
        for node in nodes:
            if isinstance(node, ast.Call):
                self.call(node, held)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self" and \
                    id(node) not in call_funcs:
                self.access(node, held)

    def access(self, node: ast.Attribute, held: Tuple[str, ...]):
        if self.cls is None:
            return
        attr = node.attr
        if attr in self.cls.infra_attrs or attr in self.cls.method_names \
                or attr in self.cls.locks or attr in self.cls.aliases:
            return
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        self.accesses.append((attr, write, held, node))

    # -- calls --------------------------------------------------------------

    def _resolve(self, node: ast.Call) -> Tuple[List[FuncInfo], bool, bool,
                                                str]:
        """-> (targets, strong, self_call, via-description)."""
        f = node.func
        if isinstance(f, ast.Name):
            cands = [g for g in self.disc.proj.by_name.get(f.id, [])
                     if g.class_name is None]
            return cands, len(cands) == 1, False, f.id
        if not isinstance(f, ast.Attribute):
            return [], False, False, ""
        meth = f.attr
        base = f.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                and self.info.class_name:
            cands = self.disc.methods_of(self.info.class_name, meth)
            return cands, True, True, f"self.{meth}"
        if isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and self.cls is not None:
            types = self.cls.attr_types.get(base.attr, set())
            if types:
                cands = [g for t in sorted(types)
                         for g in self.disc.methods_of(t, meth)]
                if cands:
                    return cands, True, False, \
                        f"self.{base.attr}.{meth}"
        # weak: name-level over every class method with this name
        if meth in _WEAK_STOP:
            return [], False, False, meth
        cands = [g for g in self.disc.proj.by_name.get(meth, [])
                 if g.class_name is not None]
        if 0 < len(cands) <= _WEAK_MAX_TARGETS:
            return cands, False, False, f"<any>.{meth}"
        return [], False, False, meth

    def call(self, node: ast.Call, held: Tuple[str, ...]):
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _EVENT_OPS:
            site = self._lock_site(node.func.value)
            if site is not None and site.kind == "Event":
                self.event_ops.append(
                    (site.key, held, node, node.func.attr))
        targets, strong, self_call, via = self._resolve(node)
        if targets:
            self.calls.append((
                held, tuple(id(t) for t in targets), strong, self_call,
                node, via))
        if self.info.hot:
            self._check_blocking(node, held)

    def _check_blocking(self, node: ast.Call, held: Tuple[str, ...]):
        """Record blocking-op candidates; whether a lock is actually held
        (including locks the CALLER holds, per caller_held inference) is
        decided at the findings phase."""
        chain = _attr_chain(node.func)

        def emit(what: str, rkey: Optional[str] = None):
            self.blocking.append((node, what, held, rkey))

        if chain in _BLOCKING_CALLS:
            emit(f"blocking call `{chain}`")
            return
        if isinstance(node.func, ast.Attribute):
            meth = node.func.attr
            recv = node.func.value
            bounded = any(kw.arg in ("timeout", "block")
                          for kw in node.keywords) or len(node.args) >= 1
            if meth in ("get", "put") and isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self" and self.cls is not None \
                    and recv.attr in self.cls.queue_attrs:
                qbound = any(kw.arg in ("timeout", "block")
                             for kw in node.keywords) or len(node.args) > 1
                if not qbound:
                    emit(f"unbounded `self.{recv.attr}.{meth}()`")
                return
            if meth == "result" and not bounded:
                emit("`.result()` without timeout")
                return
            if meth == "block_until_ready":
                emit("device sync `.block_until_ready()`")
                return
            if meth == "wait" and not bounded:
                # cond.wait() releases its own lock; the findings phase
                # flags it only if OTHER locks stay held across the wait
                emit("unbounded `.wait()`", rkey=self._lock_key(recv))
                return
        # device dispatch: a jitted callee traced/executed under the lock
        bname = None
        if isinstance(node.func, ast.Name):
            bname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            bname = node.func.attr
        jitted = False
        if bname:
            idx = self.disc.index_of.get(self.info.path)
            if idx is not None and bname in idx.jit_names:
                jitted = True
            infos = self.disc.proj.by_name.get(bname, [])
            if infos and any(g.is_jit for g in infos):
                jitted = True
        if jitted:
            emit(f"device dispatch `{bname}(...)`")


# ---------------------------------------------------------------------------
# the whole-program pass
# ---------------------------------------------------------------------------

class _Discipline:
    def __init__(self, proj):
        self.proj = proj
        self.class_facts: Dict[str, _ClassFacts] = {}
        self.module_locks: Dict[str, Dict[str, LockSite]] = {}
        self.index_of = {idx.path: idx for idx in proj.indexes}
        self.graph = LockGraph()
        self.findings: List[Finding] = []
        self._methods: Dict[Tuple[str, str], List[FuncInfo]] = {}

    def methods_of(self, cls: str, name: str) -> List[FuncInfo]:
        return self._methods.get((cls, name), [])

    # -- fact collection ----------------------------------------------------

    def collect(self):
        class_names: Set[str] = set()
        for path, tree in self.proj.trees.items():
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    class_names.add(node.name)
        for idx in self.proj.indexes:
            for f in idx.functions:
                if f.class_name:
                    self._methods.setdefault(
                        (f.class_name, f.name), []).append(f)

        for path, tree in self.proj.trees.items():
            stem = os.path.splitext(os.path.basename(path))[0]
            if stem == "__init__":  # package locks: name by the package
                stem = os.path.basename(os.path.dirname(path)) or stem
            mod: Dict[str, LockSite] = {}
            self.module_locks[path] = mod
            # module-level locks
            for st in tree.body:
                if isinstance(st, ast.Assign) and \
                        isinstance(st.value, ast.Call):
                    kind = _factory_kind(st.value)
                    if kind is None:
                        continue
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            site = LockSite(f"{stem}.{t.id}", path,
                                            st.value.lineno, kind)
                            mod[t.id] = site
                            self.graph.add_node(site)
            for cnode in [n for n in ast.walk(tree)
                          if isinstance(n, ast.ClassDef)]:
                cf = self.class_facts.setdefault(
                    cnode.name, _ClassFacts(cnode.name, path))
                self._collect_class(cf, cnode, class_names, stem)

    def _collect_class(self, cf: _ClassFacts, cnode: ast.ClassDef,
                       class_names: Set[str], stem: str):
        for st in cnode.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cf.method_names.add(st.name)
            elif isinstance(st, ast.Assign) and \
                    isinstance(st.value, ast.Call):
                kind = _factory_kind(st.value)
                for t in st.targets:
                    if isinstance(t, ast.Name) and kind is not None:
                        site = LockSite(f"{cf.name}.{t.id}", cf.path,
                                        st.value.lineno, kind)
                        cf.locks[t.id] = site
                        self.graph.add_node(site)

        init_params: Dict[str, Set[str]] = {}
        for meth in [n for n in cnode.body
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]:
            if meth.name == "__init__":
                args = meth.args
                for a in list(args.posonlyargs) + list(args.args) + \
                        list(args.kwonlyargs):
                    if a.annotation is not None:
                        types = _ann_class_names(a.annotation, class_names)
                        if types:
                            init_params[a.arg] = types
            for node in ast.walk(meth):
                # threads this class owns
                if isinstance(node, ast.Call):
                    chain = _attr_chain(node.func)
                    if chain in ("threading.Thread", "Thread"):
                        cf.owns_threads = True
                        for kw in node.keywords:
                            if kw.arg == "target":
                                t = _attr_chain(kw.value)
                                if t:
                                    cf.thread_targets.add(
                                        t.split(".")[-1])
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (isinstance(t, ast.Attribute) and
                            isinstance(t.value, ast.Name) and
                            t.value.id in ("self", "cls")):
                        continue
                    attr = t.attr
                    if isinstance(node.value, ast.Call):
                        kind = _factory_kind(node.value)
                        if kind is not None:
                            # Condition(self._other) aliases the wrapped
                            # lock: acquiring the condition IS acquiring it
                            if kind == "Condition" and node.value.args:
                                a0 = node.value.args[0]
                                if isinstance(a0, ast.Attribute) and \
                                        isinstance(a0.value, ast.Name) and \
                                        a0.value.id == "self":
                                    cf.aliases[attr] = a0.attr
                                    continue
                            if attr not in cf.locks:
                                site = LockSite(
                                    f"{cf.name}.{attr}", cf.path,
                                    node.value.lineno, kind)
                                cf.locks[attr] = site
                                self.graph.add_node(site)
                            continue
                        chain = _attr_chain(node.value.func) or ""
                        last = chain.split(".")[-1]
                        if last in _INFRA_SUFFIXES:
                            cf.infra_attrs.add(attr)
                            if last in _QUEUE_SUFFIXES:
                                cf.queue_attrs.add(attr)
                        if last in class_names and meth.name == "__init__":
                            cf.attr_types.setdefault(attr, set()).add(last)
                    elif isinstance(node.value, ast.Name) and \
                            meth.name == "__init__":
                        types = init_params.get(node.value.id)
                        if types:
                            cf.attr_types.setdefault(attr, set()) \
                                .update(types)

    # -- analysis -----------------------------------------------------------

    def run(self) -> Tuple[List[Finding], LockGraph]:
        self.collect()
        scans: Dict[int, _Scan] = {}
        infos: Dict[int, FuncInfo] = {}
        for idx in self.proj.indexes:
            for f in idx.functions:
                s = _Scan(self, f).run()
                scans[id(f)] = s
                infos[id(f)] = f

        # caller-held inference: a `_locked` helper only ever invoked
        # under `with self._lock:` inherits that lock at every site —
        # without this, the guarded-field rule flags the helper's reads
        # as unguarded.  Intersection over all STRONG SELF call sites
        # (same instance, so the caller's self-locks genuinely cover);
        # any bare call site empties it.
        sites_of: Dict[int, List[Tuple[int, Tuple[str, ...]]]] = {}
        called: Set[int] = set()
        for fid, s in scans.items():
            for held, targets, strong, self_call, node, via in s.calls:
                known = tuple(h for h in held if h != _ANON)
                for t in targets:
                    if strong and self_call:
                        sites_of.setdefault(t, []).append((fid, known))
                    called.add(t)
        caller_held: Dict[int, Set[str]] = {fid: set() for fid in scans}
        for _ in range(4):
            for fid in scans:
                sites = sites_of.get(fid)
                # a function that is ALSO reachable some other way (weak
                # call, thread target, public entry) gets no credit: only
                # purely-internal helpers qualify.  Heuristic: every
                # known call is a strong self-call and the name is
                # private ("_x"), i.e. not an external entry point.
                if not sites or not infos[fid].name.startswith("_") or \
                        infos[fid].hot and infos[fid].name in (
                            "_loop", "_run", "_worker"):
                    continue
                acc: Optional[Set[str]] = None
                for caller_fid, held in sites:
                    eff = set(held) | caller_held.get(caller_fid, set())
                    acc = eff if acc is None else (acc & eff)
                caller_held[fid] = acc or set()

        # transitive acquires: strong (typed chains), all (incl. weak),
        # and self (same-instance `self.m()` chains only)
        strong_acq = {fid: set(s.acq_direct) for fid, s in scans.items()}
        all_acq = {fid: set(s.acq_direct) for fid, s in scans.items()}
        self_acq = {fid: set(s.acq_direct) for fid, s in scans.items()}
        for _ in range(24):
            changed = False
            for fid, s in scans.items():
                for held, targets, strong, self_call, node, via in s.calls:
                    for t in targets:
                        if t not in all_acq:
                            continue
                        if not all_acq[t] <= all_acq[fid]:
                            all_acq[fid] |= all_acq[t]
                            changed = True
                        if strong and not strong_acq[t] <= strong_acq[fid]:
                            strong_acq[fid] |= strong_acq[t]
                            changed = True
                        if self_call and \
                                not self_acq[t] <= self_acq[fid]:
                            self_acq[fid] |= self_acq[t]
                            changed = True
            if not changed:
                break

        # edges
        self_deadlocks: List[Tuple[FuncInfo, ast.AST, str, str]] = []
        for fid, s in scans.items():
            f = infos[fid]
            for (a, b, node) in s.pairs:
                self.graph.add_edge(a, b, True,
                                    (f.path, getattr(node, "lineno", 0),
                                     f.qualname, "nested with"))
            for (ek, held, node, meth) in s.event_ops:
                eff = {h for h in held if h != _ANON} \
                    | caller_held.get(fid, set())
                for h in eff:
                    if h != ek:
                        self.graph.add_edge(
                            h, ek, True,
                            (f.path, getattr(node, "lineno", 0),
                             f.qualname, f"event .{meth}()"))
            for held, targets, strong, self_call, node, via in s.calls:
                known = [h for h in held if h != _ANON]
                if not known:
                    continue
                for t in targets:
                    sacq = self_acq.get(t, set())
                    tstrong = strong_acq.get(t, set())
                    for L in all_acq.get(t, set()):
                        for h in known:
                            if h == L:
                                site = self.graph.nodes.get(h)
                                if self_call and L in sacq and \
                                        site is not None and \
                                        not site.reentrant:
                                    self_deadlocks.append(
                                        (f, node, h, via))
                                continue
                            self.graph.add_edge(
                                h, L, strong and L in tstrong,
                                (f.path, getattr(node, "lineno", 0),
                                 f.qualname, f"{via} -> {L}"))

        self._findings_lock_order(self_deadlocks)
        self._findings_unguarded(scans, infos, caller_held)
        self._findings_blocking(scans, infos, caller_held)
        return self.findings, self.graph

    # -- findings -----------------------------------------------------------

    def _emit(self, rule: str, path: str, line: int, func: str, msg: str):
        src = self.proj.source_lines.get(path, [])
        code = src[line - 1] if 0 < line <= len(src) else ""
        self.findings.append(Finding(
            rule=rule, path=path, line=line, col=0, func=func,
            message=msg, code=code))

    def _findings_lock_order(self, self_deadlocks):
        for f, node, key, via in self_deadlocks:
            self._emit(
                "lock-order", f.path, getattr(node, "lineno", 0),
                f.qualname,
                f"re-acquisition of non-reentrant `{key}` on a self-call "
                f"path (via `{via}`) — self-deadlock; make the inner "
                "path lock-free or split a _locked variant")
        for scc in self.graph.strong_sccs():
            cyc = " <-> ".join(scc)
            for (a, b), e in sorted(self.graph.edges.items()):
                if not e.strong or a not in scc or b not in scc:
                    continue
                for w in e.witness[:1]:
                    self._emit(
                        "lock-order", w[0], w[1], w[2],
                        f"lock-order cycle: `{a}` is held while acquiring "
                        f"`{b}` (via {w[3]}), closing the cycle {cyc} — "
                        "acquired-before edges must form a DAG; pick one "
                        "global order or drop the nested acquisition")

    def _findings_unguarded(self, scans, infos, caller_held):
        # per (class, attr): access sites across all methods
        by_attr: Dict[Tuple[str, str],
                      List[Tuple[FuncInfo, bool, Tuple[str, ...],
                                 ast.AST]]] = {}
        for fid, s in scans.items():
            f = infos[fid]
            if f.class_name is None or f.name == "__init__":
                continue
            cf = self.class_facts.get(f.class_name)
            if cf is None or not (cf.owns_threads or cf.thread_targets):
                continue
            inherited = tuple(sorted(caller_held.get(fid, set())))
            for attr, write, held, node in s.accesses:
                by_attr.setdefault((f.class_name, attr), []).append(
                    (f, write, held + inherited, node))

        for (cls, attr), sites in by_attr.items():
            cf = self.class_facts[cls]
            workers = cf.thread_targets
            methods = {f.name for f, *_ in sites}
            cross = (methods & workers and methods - workers) or \
                len(methods & workers) >= 2
            if not cross:
                continue
            counts: Dict[str, int] = {}
            for _, _, held, _ in sites:
                for h in held:
                    if h != _ANON:
                        counts[h] = counts.get(h, 0) + 1
            if not counts:
                continue
            guard = max(counts, key=lambda k: counts[k])
            guarded = [s for s in sites if guard in s[2]]
            unguarded = [s for s in sites if guard not in s[2]]
            if len(guarded) < 2 or not unguarded or \
                    len(guarded) <= len(unguarded):
                continue
            for f, write, held, node in unguarded:
                kind = "written" if write else "read"
                self._emit(
                    "unguarded-state", f.path,
                    getattr(node, "lineno", 0), f.qualname,
                    f"`self.{attr}` is {kind} without `{guard}` here, but "
                    f"{len(guarded)}/{len(sites)} access sites hold it and "
                    f"the attribute is shared with the "
                    f"{sorted(methods & workers)} worker thread(s) — take "
                    "the lock or suppress with the reason the race is "
                    "benign")

    def _findings_blocking(self, scans, infos, caller_held):
        for fid, s in scans.items():
            f = infos[fid]
            inherited = caller_held.get(fid, set())
            for node, what, held, rkey in s.blocking:
                eff = [h for h in held if h != _ANON] + \
                    sorted(inherited - set(held))
                anon_only = not eff and _ANON in held
                if not eff and not anon_only:
                    continue
                if rkey is not None:
                    # a cond.wait() releases its own lock while waiting
                    others = [h for h in eff if h != rkey]
                    if not others and not anon_only:
                        continue
                    eff = others
                locks = ", ".join(f"`{h}`" for h in eff) or "a lock"
                self._emit(
                    "blocking-under-lock", f.path,
                    getattr(node, "lineno", 0), f.qualname,
                    f"{what} while holding {locks} in a hot-path "
                    "function — every thread contending on the lock "
                    "inherits the stall")


def analyze_lock_discipline(proj) -> Tuple[List[Finding], LockGraph]:
    """Entry point called from `linter.Project.run()`: returns the three
    rule families' findings plus the inferred acquired-before graph."""
    d = _Discipline(proj)
    return d.run()
