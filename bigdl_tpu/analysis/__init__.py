"""bigdl_tpu.analysis: TPU-hostile-pattern linter + runtime sanitizers.

Static side: `analyze_paths` / `analyze_sources` run six AST rule
families (host-sync, recompile, tracer-leak, concurrency, donation,
blocking-io) over the tree; `tools/tpu_lint.py` is the CLI and CI
gate.  Runtime side: `strict_transfers` wraps hot sections in
`jax.transfer_guard("disallow")` so implicit transfers fail loudly
(env `BIGDL_TPU_STRICT_TRANSFERS`).  See docs/analysis.md.
"""

from bigdl_tpu.analysis.linter import (
    DEFAULT_HOT_ROOTS,
    Finding,
    HOT_PATH_RULES,
    RULES,
    analyze_paths,
    analyze_sources,
    iter_python_files,
)
from bigdl_tpu.analysis.runtime import (
    ENV_FLAG,
    strict_transfers,
    strict_transfers_enabled,
)

__all__ = [
    "DEFAULT_HOT_ROOTS",
    "ENV_FLAG",
    "Finding",
    "HOT_PATH_RULES",
    "RULES",
    "analyze_paths",
    "analyze_sources",
    "iter_python_files",
    "strict_transfers",
    "strict_transfers_enabled",
]
