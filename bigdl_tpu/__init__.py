"""bigdl_tpu — a TPU-native distributed deep-learning framework.

A ground-up JAX/XLA/pjit/pallas re-design of the capabilities of BigDL
(the Spark/Scala distributed DL library; see SURVEY.md): a Torch-style
layer/criterion zoo with containers and graph execution, data-parallel
synchronous SGD over a TPU mesh (XLA collectives over ICI/DCN replacing
BigDL's Spark-BlockManager parameter server), composable host-side data
pipelines, a full optimizer/LR-schedule suite with triggers and validation
metrics, checkpoint/resume, observability, and Keras-style high-level APIs.

Nothing here is a port: BigDL's hand-written autograd
(reference: spark/dl/.../nn/abstractnn/AbstractModule.scala:58) is replaced
by jax.grad over pure module applications; its MKL/MKL-DNN native kernels
(reference: tensor/TensorNumeric.scala, nn/mkldnn/) are replaced by XLA
fusion inside one jitted train step; its AllReduceParameter BlockManager
shuffle (reference: parameters/AllReduceParameter.scala:84) is replaced by
`lax.psum`/sharding-propagated collectives over a `jax.sharding.Mesh`.
"""

__version__ = "0.1.0"

from bigdl_tpu.core.engine import Engine  # noqa: F401
from bigdl_tpu import obs  # noqa: F401  (metrics plane is default-on)
from bigdl_tpu.obs import set_observability  # noqa: F401
