"""Table-routing containers.

Reference: nn/ConcatTable.scala (one input -> Table of branch outputs),
nn/ParallelTable.scala (Table in -> Table out, childwise),
nn/MapTable.scala (same module over each element), nn/SelectTable.scala,
nn/FlattenTable.scala.
"""

from __future__ import annotations

from typing import Optional

import jax

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Container, Module, child_rng


class ConcatTable(Container):
    """Apply each child to the same input; output a Table.
    reference: nn/ConcatTable.scala."""

    def __init__(self, *modules: Module, name: Optional[str] = None):
        super().__init__(name)
        for m in modules:
            self.add(m)

    def build(self, rng, input_shape):
        params, state = {}, {}
        shapes = Table()
        for i, (key, m) in enumerate(self.children.items()):
            p, s, out = m.build(jax.random.fold_in(rng, i), input_shape)
            params[key], state[key] = p, s
            shapes[i + 1] = out
        return params, state, shapes

    def apply(self, params, state, x, *, training=False, rng=None):
        out = Table()
        new_state = {}
        for i, (key, m) in enumerate(self.children.items()):
            y, new_state[key] = m.apply(params[key], state[key], x,
                                        training=training, rng=child_rng(rng, i))
            out[i + 1] = y
        return out, new_state

    def output_shape(self, input_shape):
        t = Table()
        for i, m in enumerate(self.children.values()):
            t[i + 1] = m.output_shape(input_shape)
        return t


class ParallelTable(Container):
    """i-th child consumes i-th table element. reference: nn/ParallelTable.scala."""

    def __init__(self, *modules: Module, name: Optional[str] = None):
        super().__init__(name)
        for m in modules:
            self.add(m)

    def build(self, rng, input_shape):
        params, state = {}, {}
        shapes = Table()
        inputs = list(input_shape)
        for i, (key, m) in enumerate(self.children.items()):
            p, s, out = m.build(jax.random.fold_in(rng, i), inputs[i])
            params[key], state[key] = p, s
            shapes[i + 1] = out
        return params, state, shapes

    def apply(self, params, state, x, *, training=False, rng=None):
        items = list(x)
        out = Table()
        new_state = {}
        for i, (key, m) in enumerate(self.children.items()):
            y, new_state[key] = m.apply(params[key], state[key], items[i],
                                        training=training, rng=child_rng(rng, i))
            out[i + 1] = y
        return out, new_state


class MapTable(Container):
    """Same module applied to each table element (shared params).
    reference: nn/MapTable.scala."""

    def __init__(self, module: Module, name: Optional[str] = None):
        super().__init__(name)
        self.add(module)

    def build(self, rng, input_shape):
        inner = self[0]
        items = list(input_shape)
        p, s, _ = inner.build(rng, items[0])
        shapes = Table(*[inner.output_shape(sh) for sh in items])
        return {"0": p}, {"0": s}, shapes

    def apply(self, params, state, x, *, training=False, rng=None):
        inner = self[0]
        items = list(x)
        out = Table()
        s = state["0"]
        for i, item in enumerate(items):
            y, s = inner.apply(params["0"], s, item, training=training,
                               rng=child_rng(rng, i))
            out[i + 1] = y
        return out, {"0": s}


class SelectTable(Module):
    """Pick the k-th (1-based, like the reference) element.
    reference: nn/SelectTable.scala."""

    def __init__(self, index: int, name: Optional[str] = None):
        super().__init__(name)
        self.index = index

    def apply(self, params, state, x, *, training=False, rng=None):
        if isinstance(x, Table):
            return x[self.index], state
        return x[self.index - 1], state

    def output_shape(self, input_shape):
        if isinstance(input_shape, Table):
            return input_shape[self.index]
        return input_shape[self.index - 1]


class FlattenTable(Module):
    """Flatten nested Tables into one flat Table. reference: nn/FlattenTable.scala."""

    def apply(self, params, state, x, *, training=False, rng=None):
        flat = []

        def rec(t):
            if isinstance(t, (Table, list, tuple)):
                for v in t:
                    rec(v)
            else:
                flat.append(t)

        rec(x)
        return Table(*flat), state
