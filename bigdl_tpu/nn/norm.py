"""Normalization layers.

Reference: nn/BatchNormalization.scala, nn/SpatialBatchNormalization.scala,
nn/Normalize.scala, nn/SpatialCrossMapLRN.scala.

Sync-BN: the reference synchronizes batch statistics across intra-node model
replicas via `setParallism` + ParameterSynchronizer thread barriers
(models/resnet/TrainImageNet.scala:151-158, utils/ParameterSynchronizer.scala).
On TPU there are two regimes, both cleaner:
  * under pjit with a batch-sharded global array, the mean/var reductions are
    global automatically — sync-BN is the default semantics;
  * under shard_map (per-shard code), pass `axis_name` and the layer inserts
    `lax.pmean` over that mesh axis.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module


class BatchNormalization(Module):
    """BN over the last axis of (N, C) input.
    reference: nn/BatchNormalization.scala (momentum=0.1, eps=1e-5, affine)."""

    _reduce_axes: Tuple[int, ...] = (0,)

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, axis_name: Optional[str] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.axis_name = axis_name

    def set_axis_name(self, axis_name: Optional[str]) -> "BatchNormalization":
        """Cross-replica stat sync under shard_map (the `setParallism`
        analogue, survey §2.10 Sync-BN row)."""
        self.axis_name = axis_name
        return self

    def build(self, rng, input_shape):
        c = self.n_output
        params = {}
        if self.affine:
            params = {"weight": jnp.ones((c,), jnp.float32),
                      "bias": jnp.zeros((c,), jnp.float32)}
        state = {"running_mean": jnp.zeros((c,), jnp.float32),
                 "running_var": jnp.ones((c,), jnp.float32)}
        return params, state, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        if training:
            mean = jnp.mean(x, axis=self._reduce_axes)
            mean2 = jnp.mean(jnp.square(x), axis=self._reduce_axes)
            n = 1
            for ax in self._reduce_axes:
                n *= x.shape[ax]
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
                n = n * lax.psum(1, self.axis_name)
            var = mean2 - jnp.square(mean)
            m = self.momentum
            # running stats use the UNBIASED variance (n/(n-1)), matching
            # torch and the reference's runningVar semantics
            unbiased = var * (n / jnp.maximum(n - 1, 1))
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        y = (x - mean) * inv
        if self.affine:
            y = y * params["weight"] + params["bias"]
        return y.astype(x.dtype), new_state

    def output_shape(self, input_shape):
        return input_shape


class TemporalBatchNormalization(BatchNormalization):
    """BN over (N, T) of (N, T, C) input — per-feature stats for sequence
    activations (the Keras BatchNormalization semantics on 3-D input)."""

    _reduce_axes = (0, 1)


class SpatialBatchNormalization(BatchNormalization):
    """BN over (N, H, W) of NHWC input.
    reference: nn/SpatialBatchNormalization.scala."""

    _reduce_axes = (0, 1, 2)


class LayerNormalization(Module):
    """LayerNorm over the last axis (reference keras-style LayerNorm;
    also the building block the TPU transformer stack uses)."""

    def __init__(self, hidden_size: int, eps: float = 1e-5, name: Optional[str] = None):
        super().__init__(name)
        self.hidden_size = hidden_size
        self.eps = eps

    def build(self, rng, input_shape):
        params = {"weight": jnp.ones((self.hidden_size,), jnp.float32),
                  "bias": jnp.zeros((self.hidden_size,), jnp.float32)}
        return params, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
        y = (x - mean) * lax.rsqrt(var + self.eps)
        return y * params["weight"] + params["bias"], state


class Normalize(Module):
    """Lp-normalize along the last axis. reference: nn/Normalize.scala."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10, name: Optional[str] = None):
        super().__init__(name)
        self.p = p
        self.eps = eps

    def apply(self, params, state, x, *, training=False, rng=None):
        if self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=-1, keepdims=True) ** (1.0 / self.p)
        return x / jnp.maximum(norm, self.eps), state


class SpatialCrossMapLRN(Module):
    """Local response normalization across channels (NHWC).
    reference: nn/SpatialCrossMapLRN.scala (AlexNet/Inception-v1 era).

    y = x / (k + alpha/size * sum_{local window} x^2)^beta
    Implemented as a channel-axis reduce_window — XLA fuses it; no explicit
    ring buffers like the reference's scale-tensor bookkeeping."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def apply(self, params, state, x, *, training=False, rng=None):
        half = (self.size - 1) // 2
        sq = jnp.square(x)
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add, (1, 1, 1, self.size), (1, 1, 1, 1),
            [(0, 0), (0, 0), (0, 0), (half, self.size - 1 - half)])
        scale = (self.k + self.alpha / self.size * window_sum) ** self.beta
        return x / scale, state


class NormalizeScale(Module):
    """Lp-normalize then multiply by a learnable per-channel scale — the
    Caffe `Normalize` layer used by SSD conv4_3.
    reference: nn/NormalizeScale.scala (Normalize + CMul(size) with the
    scale weight initialised to a constant)."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10, scale: float = 1.0,
                 size: Optional[Sequence[int]] = None, name: Optional[str] = None,
                 across_spatial: bool = False):
        super().__init__(name)
        self.p = p
        self.eps = eps
        self.scale = scale
        self.size = tuple(size) if size is not None else None
        # across_spatial: the norm is taken over ALL non-batch axes (caffe
        # norm_param.across_spatial=true, the proto default) instead of the
        # channel axis only (the SSD conv4_3 configuration)
        self.across_spatial = across_spatial

    def build(self, rng, input_shape):
        size = self.size if self.size is not None else (input_shape[-1],)
        return {"weight": jnp.full(size, self.scale, jnp.float32)}, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        axes = tuple(range(1, x.ndim)) if self.across_spatial else (-1,)
        if self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True))
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=axes, keepdims=True) ** (1.0 / self.p)
        return (x / jnp.maximum(norm, self.eps)) * params["weight"], state


class SpatialWithinChannelLRN(Module):
    """LRN within each channel over a size x size spatial window (NHWC).
    reference: nn/SpatialWithinChannelLRN.scala:40-48 — composed there as
    x * (1 + alpha * avgpool(x^2, size, pad=(size-1)/2))^(-beta); here one
    fused reduce_window expression."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = size
        self.alpha = alpha
        self.beta = beta

    def apply(self, params, state, x, *, training=False, rng=None):
        half = (self.size - 1) // 2
        hi = self.size - 1 - half
        window_sum = lax.reduce_window(
            jnp.square(x), 0.0, lax.add, (1, self.size, self.size, 1),
            (1, 1, 1, 1), [(0, 0), (half, hi), (half, hi), (0, 0)])
        avg = window_sum / (self.size * self.size)
        return x * (1.0 + self.alpha * avg) ** (-self.beta), state


def _gaussian_kernel(size: int, sigma_frac: float = 0.25) -> jnp.ndarray:
    """Default 2-D gaussian kernel matching torch's image.gaussian default
    (the reference's default 9x9 kernel)."""
    sigma = sigma_frac * size
    r = jnp.arange(size, dtype=jnp.float32) - (size - 1) / 2.0
    g = jnp.exp(-0.5 * jnp.square(r / sigma))
    k = jnp.outer(g, g)
    return k / jnp.max(k)


class _LocalMeanEstimator(Module):
    """Shared machinery: weighted local mean across a spatial window AND all
    channels, with border-coefficient correction (the conv-over-ones trick
    the reference caches as `coef`)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input_plane
        if kernel is None:
            kernel = _gaussian_kernel(9)
        kernel = jnp.asarray(kernel, jnp.float32)
        if kernel.ndim == 1:  # separable 1-D kernel -> outer product
            kernel = jnp.outer(kernel, kernel)
        # normalise so the window+channel weighted sum is a mean
        self.kernel = kernel / (jnp.sum(kernel) * n_input_plane)

    def _mean(self, x):
        kh, kw = self.kernel.shape
        w = jnp.broadcast_to(self.kernel[:, :, None, None],
                             (kh, kw, self.n_input, 1))
        pads = [((kh - 1) // 2, kh // 2), ((kw - 1) // 2, kw // 2)]
        mean = lax.conv_general_dilated(
            x, w, (1, 1), pads, dimension_numbers=("NHWC", "HWIO", "NHWC"))
        ones = jnp.ones((1,) + x.shape[1:3] + (self.n_input,), x.dtype)
        coef = lax.conv_general_dilated(
            ones, w, (1, 1), pads, dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return mean / coef


class SpatialSubtractiveNormalization(_LocalMeanEstimator):
    """Subtract the kernel-weighted neighborhood mean (across space and all
    channels) from every channel.
    reference: nn/SpatialSubtractiveNormalization.scala."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return x - self._mean(x), state


class SpatialDivisiveNormalization(_LocalMeanEstimator):
    """Divide by the kernel-weighted neighborhood standard deviation,
    thresholded from below.
    reference: nn/SpatialDivisiveNormalization.scala (threshold/thresval)."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4,
                 name: Optional[str] = None):
        super().__init__(n_input_plane, kernel, name)
        self.threshold = threshold
        self.thresval = thresval

    def apply(self, params, state, x, *, training=False, rng=None):
        stds = jnp.sqrt(jnp.maximum(self._mean(jnp.square(x)), 0.0))
        stds = jnp.where(stds <= self.threshold, self.thresval, stds)
        return x / stds, state


class SpatialContrastiveNormalization(Module):
    """Subtractive then divisive normalization with one shared kernel.
    reference: nn/SpatialContrastiveNormalization.scala."""

    def __init__(self, n_input_plane: int = 1, kernel=None,
                 threshold: float = 1e-4, thresval: float = 1e-4,
                 name: Optional[str] = None):
        super().__init__(name)
        self.sub = SpatialSubtractiveNormalization(n_input_plane, kernel)
        self.div = SpatialDivisiveNormalization(n_input_plane, kernel,
                                                threshold, thresval)

    def apply(self, params, state, x, *, training=False, rng=None):
        y, _ = self.sub.apply({}, {}, x)
        return self.div.apply({}, {}, y)[0], state
