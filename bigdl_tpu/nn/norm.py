"""Normalization layers.

Reference: nn/BatchNormalization.scala, nn/SpatialBatchNormalization.scala,
nn/Normalize.scala, nn/SpatialCrossMapLRN.scala.

Sync-BN: the reference synchronizes batch statistics across intra-node model
replicas via `setParallism` + ParameterSynchronizer thread barriers
(models/resnet/TrainImageNet.scala:151-158, utils/ParameterSynchronizer.scala).
On TPU there are two regimes, both cleaner:
  * under pjit with a batch-sharded global array, the mean/var reductions are
    global automatically — sync-BN is the default semantics;
  * under shard_map (per-shard code), pass `axis_name` and the layer inserts
    `lax.pmean` over that mesh axis.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module


class BatchNormalization(Module):
    """BN over the last axis of (N, C) input.
    reference: nn/BatchNormalization.scala (momentum=0.1, eps=1e-5, affine)."""

    _reduce_axes: Tuple[int, ...] = (0,)

    def __init__(self, n_output: int, eps: float = 1e-5, momentum: float = 0.1,
                 affine: bool = True, axis_name: Optional[str] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_output = n_output
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.axis_name = axis_name

    def set_axis_name(self, axis_name: Optional[str]) -> "BatchNormalization":
        """Cross-replica stat sync under shard_map (the `setParallism`
        analogue, survey §2.10 Sync-BN row)."""
        self.axis_name = axis_name
        return self

    def build(self, rng, input_shape):
        c = self.n_output
        params = {}
        if self.affine:
            params = {"weight": jnp.ones((c,), jnp.float32),
                      "bias": jnp.zeros((c,), jnp.float32)}
        state = {"running_mean": jnp.zeros((c,), jnp.float32),
                 "running_var": jnp.ones((c,), jnp.float32)}
        return params, state, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        if training:
            mean = jnp.mean(x, axis=self._reduce_axes)
            mean2 = jnp.mean(jnp.square(x), axis=self._reduce_axes)
            n = 1
            for ax in self._reduce_axes:
                n *= x.shape[ax]
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
                n = n * lax.psum(1, self.axis_name)
            var = mean2 - jnp.square(mean)
            m = self.momentum
            # running stats use the UNBIASED variance (n/(n-1)), matching
            # torch and the reference's runningVar semantics
            unbiased = var * (n / jnp.maximum(n - 1, 1))
            new_state = {
                "running_mean": (1 - m) * state["running_mean"] + m * mean,
                "running_var": (1 - m) * state["running_var"] + m * unbiased,
            }
        else:
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        y = (x - mean) * inv
        if self.affine:
            y = y * params["weight"] + params["bias"]
        return y.astype(x.dtype), new_state

    def output_shape(self, input_shape):
        return input_shape


class TemporalBatchNormalization(BatchNormalization):
    """BN over (N, T) of (N, T, C) input — per-feature stats for sequence
    activations (the Keras BatchNormalization semantics on 3-D input)."""

    _reduce_axes = (0, 1)


class SpatialBatchNormalization(BatchNormalization):
    """BN over (N, H, W) of NHWC input.
    reference: nn/SpatialBatchNormalization.scala."""

    _reduce_axes = (0, 1, 2)


class LayerNormalization(Module):
    """LayerNorm over the last axis (reference keras-style LayerNorm;
    also the building block the TPU transformer stack uses)."""

    def __init__(self, hidden_size: int, eps: float = 1e-5, name: Optional[str] = None):
        super().__init__(name)
        self.hidden_size = hidden_size
        self.eps = eps

    def build(self, rng, input_shape):
        params = {"weight": jnp.ones((self.hidden_size,), jnp.float32),
                  "bias": jnp.zeros((self.hidden_size,), jnp.float32)}
        return params, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
        y = (x - mean) * lax.rsqrt(var + self.eps)
        return y * params["weight"] + params["bias"], state


class Normalize(Module):
    """Lp-normalize along the last axis. reference: nn/Normalize.scala."""

    def __init__(self, p: float = 2.0, eps: float = 1e-10, name: Optional[str] = None):
        super().__init__(name)
        self.p = p
        self.eps = eps

    def apply(self, params, state, x, *, training=False, rng=None):
        if self.p == 2.0:
            norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=-1, keepdims=True))
        else:
            norm = jnp.sum(jnp.abs(x) ** self.p, axis=-1, keepdims=True) ** (1.0 / self.p)
        return x / jnp.maximum(norm, self.eps), state


class SpatialCrossMapLRN(Module):
    """Local response normalization across channels (NHWC).
    reference: nn/SpatialCrossMapLRN.scala (AlexNet/Inception-v1 era).

    y = x / (k + alpha/size * sum_{local window} x^2)^beta
    Implemented as a channel-axis reduce_window — XLA fuses it; no explicit
    ring buffers like the reference's scale-tensor bookkeeping."""

    def __init__(self, size: int = 5, alpha: float = 1.0, beta: float = 0.75,
                 k: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k

    def apply(self, params, state, x, *, training=False, rng=None):
        half = (self.size - 1) // 2
        sq = jnp.square(x)
        window_sum = lax.reduce_window(
            sq, 0.0, lax.add, (1, 1, 1, self.size), (1, 1, 1, 1),
            [(0, 0), (0, 0), (0, 0), (half, self.size - 1 - half)])
        scale = (self.k + self.alpha / self.size * window_sum) ** self.beta
        return x / scale, state
