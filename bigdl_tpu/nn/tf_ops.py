"""TF-graph structural layers (the reference's nn/tf/ package).

Reference: nn/tf/ — ArrayOps.scala (Const/Fill/InvertPermutation/
ConcatOffset), StateOps.scala (Variable/Assign), ParsingOps.scala
(ParseExample/ParseSingleExample over tf.train.Example protos),
SplitAndSelect.scala, NoOp / Assert / ControlDependency, BiasAdd
(nn/tf/BiasAdd.scala), Log1p, TensorModuleWrapper, DataFlowOps.scala
(TensorArray*/Stack*), ImageOps.scala (DecodeRaw/DecodeJpeg/DecodePng).

The reference also carries ~20 hand-written *Grad ops (NNOps.scala:600-1149
— ReluGrad, FusedBatchNormGrad, MaxPoolGrad, ...) because its autograd is
manual and imported TF training graphs need explicit backward nodes.  Under
JAX those nodes are unnecessary: `jax.grad` differentiates the imported
forward graph directly (utils/session.py trains loaded graphs this way), so
no Grad ops exist here by design.

The tf.train.Example codec below is a from-scratch protobuf wire-format
implementation (like the repo's other hand-written schemas in proto/);
strings/bytes stay host-side, numeric features become jnp arrays.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.ops import Operation, _pair


# ---------------------------------------------------------------------------
# ArrayOps (reference: nn/tf/ArrayOps.scala)
# ---------------------------------------------------------------------------


class Const(Operation):
    """Emit a constant tensor regardless of input.
    reference: nn/tf/ArrayOps.scala:32."""

    def __init__(self, value, name: Optional[str] = None):
        super().__init__(name)
        self.value = jnp.asarray(value)

    def compute(self, x):
        return self.value

    def output_shape(self, input_shape):
        return tuple(self.value.shape)


class Fill(Operation):
    """{shape, scalar} -> filled tensor. reference: nn/tf/ArrayOps.scala:132.
    Host-side shape read (value-dependent shape cannot live under jit)."""

    def compute(self, x):
        shape, value = _pair(x)
        dims = tuple(int(v) for v in np.asarray(shape).reshape(-1))
        return jnp.full(dims, jnp.asarray(value))


class InvertPermutation(Operation):
    """y[x[i]] = i. reference: nn/tf/ArrayOps.scala:64."""

    def compute(self, x):
        idx = jnp.asarray(x, jnp.int32)
        return jnp.zeros_like(idx).at[idx].set(jnp.arange(idx.shape[0],
                                                          dtype=jnp.int32))


class ConcatOffset(Operation):
    """{concat_dim, shape_1..shape_N} -> per-input start offsets along the
    concat axis. reference: nn/tf/ArrayOps.scala:102."""

    def compute(self, x):
        items = list(x)
        dim = int(np.asarray(items[0]).item())
        shapes = [np.asarray(s).astype(np.int32) for s in items[1:]]
        outs, acc = [], 0
        for s in shapes:
            off = np.zeros_like(s)
            off[dim] = acc
            acc += int(s[dim])
            outs.append(jnp.asarray(off))
        return Table(*outs)


class BroadcastGradientArgs(Operation):
    """{shape_a, shape_b} -> axes each side must reduce over to undo numpy
    broadcasting. reference: nn/tf/ArrayOps.scala:197."""

    def compute(self, x):
        sa, sb = [list(np.asarray(v).astype(int)) for v in _pair(x)]
        n = max(len(sa), len(sb))
        pa = [1] * (n - len(sa)) + sa
        pb = [1] * (n - len(sb)) + sb
        # TF bcast rule: each side reduces every axis whose (1-padded) dim
        # is 1 — including axes where both are 1 (harmless, matches TF)
        return Table(jnp.asarray([i for i in range(n) if pa[i] == 1], jnp.int32),
                     jnp.asarray([i for i in range(n) if pb[i] == 1], jnp.int32))


# ---------------------------------------------------------------------------
# structural / control (NoOp, Assert, ControlDependency, SplitAndSelect,
# BiasAdd, Log1p, TensorModuleWrapper)
# ---------------------------------------------------------------------------


class NoOp(Operation):
    """Pass-through marker node. reference: nn/tf/NoOp.scala."""

    def compute(self, x):
        return x


class Assert(Operation):
    """{condition, data} -> data, raising when the host-readable condition
    is false. reference: nn/tf/Assert.scala.  Uses checkify-style host
    check via jax.debug outside jit; inside jit it is a no-op passthrough
    (XLA has no exceptions)."""

    def __init__(self, message: str = "Assert failed", name: Optional[str] = None):
        super().__init__(name)
        self.message = message

    def compute(self, x):
        cond, data = _pair(x)
        if isinstance(cond, jax.core.Tracer):  # under jit: passthrough
            return data
        if not bool(np.asarray(cond).all()):
            raise AssertionError(self.message)
        return data


class ControlDependency(Operation):
    """Order-only edge: forwards input 1, ignores the rest.
    reference: nn/tf/ControlDependency.scala (under XLA, ordering is data
    dependence — this survives only as a graph-shape adapter)."""

    def compute(self, x):
        return list(x)[0] if isinstance(x, (Table, list, tuple)) else x


class SplitAndSelect(Operation):
    """Split along `dimension` into `num_split` parts, emit part `index`.
    reference: nn/tf/SplitAndSelect.scala."""

    def __init__(self, dimension: int, index: int, num_split: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dimension = dimension
        self.index = index
        self.num_split = num_split

    def compute(self, x):
        return jnp.split(jnp.asarray(x), self.num_split,
                         axis=self.dimension)[self.index]

    def output_shape(self, input_shape):
        s = list(input_shape)
        s[self.dimension] //= self.num_split
        return tuple(s)


class BiasAdd(Module):
    """{value, bias} -> value + bias broadcast over the channel axis.
    reference: nn/tf/BiasAdd.scala.  A Module (not Operation): imported TF
    training graphs need gradients through it."""

    def apply(self, params, state, x, *, training=False, rng=None):
        value, bias = _pair(x)
        return value + bias, state

    def output_shape(self, input_shape):
        return list(input_shape)[0]


class Log1p(Module):
    """log(1 + x), differentiable. reference: nn/tf/Log1p.scala."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.log1p(x), state


class TensorModuleWrapper(Module):
    """Adapt a Tensor-in/Tensor-out module into an op-graph node.
    reference: nn/tf/TensorModuleWrapper.scala.  Our modules already take
    arrays, so this is a transparent delegator kept for name parity."""

    def __init__(self, module: Module, name: Optional[str] = None):
        super().__init__(name)
        self.module = module

    def build(self, rng, input_shape):
        return self.module.build(rng, input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        return self.module.apply(params, state, x, training=training, rng=rng)

    def output_shape(self, input_shape):
        return self.module.output_shape(input_shape)


# ---------------------------------------------------------------------------
# StateOps (reference: nn/tf/StateOps.scala) — mutable TF variables.
# Functionally: the variable lives in `state`, Assign returns updated state.
# ---------------------------------------------------------------------------


class Variable(Module):
    """A stateful value node.  reference: nn/tf/StateOps.scala:27 —
    there the tensor mutates in place; here it lives in `state` and
    Assign produces the next state (functional, jit-safe)."""

    def __init__(self, value, trainable: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.initial = jnp.asarray(value)
        self.trainable = trainable

    def build(self, rng, input_shape):
        if self.trainable:
            return {"value": self.initial}, {}, tuple(self.initial.shape)
        return {}, {"value": self.initial}, tuple(self.initial.shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        return (params if self.trainable else state)["value"], state

    def output_shape(self, input_shape):
        return tuple(self.initial.shape)


class Assign(Module):
    """{ref_state, value} -> value, with the new value also returned as
    state (the functional reading of TF Assign).
    reference: nn/tf/StateOps.scala:71."""

    def apply(self, params, state, x, *, training=False, rng=None):
        _, value = _pair(x)
        return value, {"value": value}


class DynamicConv2D(Module):
    """{x(NHWC), w(HWIO)} -> conv2d where the filter is a LIVE tensor —
    the import lowering for Conv2D whose filter is an unfrozen graph
    Variable (reference: TensorflowLoader.scala:456 binds VariableV2
    endpoints as trainable weights; here the conv consumes the Variable
    module's value so autodiff trains it)."""

    def __init__(self, strides: Sequence[int], padding: str,
                 dilations: Sequence[int] = (1, 1),
                 groups: int = 1, name: Optional[str] = None):
        super().__init__(name)
        self.strides = tuple(strides)
        self.padding = padding
        self.dilations = tuple(dilations)
        self.groups = groups

    def build(self, rng, input_shape):
        xs, ws = tuple(input_shape)
        n, h, w_, _ = xs
        kh, kw, _, co = ws
        co = co * (self.groups if self.groups > 1 else 1)

        def out_dim(size, k, s, d):
            eff = (k - 1) * d + 1
            if self.padding == "SAME":
                return -(-size // s)
            return -(-(size - eff + 1) // s)

        oh = out_dim(h, kh, self.strides[0], self.dilations[0])
        ow = out_dim(w_, kw, self.strides[1], self.dilations[1])
        return {}, {}, (n, oh, ow, co)

    def apply(self, params, state, x, *, training=False, rng=None):
        x, w = _pair(x)
        if self.groups > 1:  # depthwise: HWIM -> HWI'(I*M) grouped filter
            kh, kw, ci, mult = w.shape
            w = jnp.reshape(w, (kh, kw, 1, ci * mult))
        y = jax.lax.conv_general_dilated(
            x, w, window_strides=self.strides, padding=self.padding,
            rhs_dilation=self.dilations,
            feature_group_count=self.groups,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y, state


class DynamicFusedBatchNorm(Module):
    """{x, gamma, beta, mean, var} -> batch norm with LIVE parameters —
    the import lowering for FusedBatchNorm(V2/V3) whose scale/offset are
    unfrozen graph Variables.  is_training=True computes batch moments
    over N,H,W (TF semantics: the incoming mean/var inputs are ignored)."""

    def __init__(self, eps: float, is_training: bool,
                 name: Optional[str] = None):
        super().__init__(name)
        self.eps = eps
        self.is_training = is_training

    def build(self, rng, input_shape):
        return {}, {}, tuple(tuple(input_shape)[0])

    def apply(self, params, state, x, *, training=False, rng=None):
        x, g, b, m, v = tuple(x)
        if self.is_training:
            m = jnp.mean(x, axis=(0, 1, 2))
            v = jnp.var(x, axis=(0, 1, 2))
        y = (x - m) * (g * jax.lax.rsqrt(v + self.eps)) + b
        return y, state


# ---------------------------------------------------------------------------
# tf.train.Example wire-format codec + ParsingOps
# (reference: nn/tf/ParsingOps.scala:36-93)
# ---------------------------------------------------------------------------


def _varint(buf: bytes, off: int) -> Tuple[int, int]:
    r = s = 0
    while True:
        b = buf[off]
        off += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, off
        s += 7


def _enc_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _fields(buf: bytes):
    off = 0
    while off < len(buf):
        key, off = _varint(buf, off)
        field, wire = key >> 3, key & 7
        if wire == 2:  # length-delimited
            ln, off = _varint(buf, off)
            yield field, buf[off:off + ln]
            off += ln
        elif wire == 0:
            v, off = _varint(buf, off)
            yield field, v
        elif wire == 5:
            yield field, buf[off:off + 4]
            off += 4
        elif wire == 1:
            yield field, buf[off:off + 8]
            off += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")


def parse_example_proto(buf: bytes) -> Dict[str, Union[np.ndarray, List[bytes]]]:
    """Decode a serialized tf.train.Example into {name: ndarray | [bytes]}.

    Schema (tensorflow/core/example/{example,feature}.proto): Example{1:
    Features}, Features{1: map<string, Feature>}, Feature = oneof
    {1: BytesList, 2: FloatList, 3: Int64List}, each list field 1 repeated.
    """
    out: Dict[str, Any] = {}
    for f, features in _fields(buf):
        if f != 1:
            continue
        for f2, entry in _fields(features):
            if f2 != 1:
                continue
            key, feature = None, b""
            for f3, v in _fields(entry):
                if f3 == 1:
                    key = v.decode()
                elif f3 == 2:
                    feature = v
            if key is None:
                continue
            for f4, payload in _fields(feature):
                if f4 == 1:  # BytesList
                    out[key] = [v for f5, v in _fields(payload) if f5 == 1]
                elif f4 == 2:  # FloatList (packed floats)
                    vals: List[float] = []
                    for f5, v in _fields(payload):
                        if f5 != 1:
                            continue
                        if isinstance(v, bytes):  # packed
                            vals.extend(struct.unpack(f"<{len(v)//4}f", v))
                        else:
                            vals.append(struct.unpack("<f", struct.pack("<I", v))[0])
                    out[key] = np.asarray(vals, np.float32)
                elif f4 == 3:  # Int64List (packed varints)
                    ivals: List[int] = []
                    if isinstance(payload, bytes):
                        o = 0
                        # field 1 entries: either packed buffer or repeated varint
                        for f5, v in _fields(payload):
                            if f5 != 1:
                                continue
                            if isinstance(v, bytes):
                                o = 0
                                while o < len(v):
                                    iv, o = _varint(v, o)
                                    ivals.append(iv)
                            else:
                                ivals.append(v)
                    out[key] = np.asarray(ivals, np.int64)
    return out


def build_example_proto(features: Dict[str, Any]) -> bytes:
    """Encode {name: ndarray | bytes | [bytes]} as tf.train.Example."""
    def ld(field: int, payload: bytes) -> bytes:
        return _enc_varint(field << 3 | 2) + _enc_varint(len(payload)) + payload

    entries = b""
    for key, value in features.items():
        if isinstance(value, bytes):
            value = [value]
        if isinstance(value, (list, tuple)) and all(
                isinstance(v, bytes) for v in value):
            blist = b"".join(ld(1, v) for v in value)
            feature = ld(1, blist)
        else:
            arr = np.asarray(value)
            if np.issubdtype(arr.dtype, np.integer):
                packed = b"".join(_enc_varint(int(v) & (2**64 - 1))
                                  for v in arr.reshape(-1))
                feature = ld(3, ld(1, packed))
            else:
                packed = struct.pack(f"<{arr.size}f",
                                     *arr.astype(np.float32).reshape(-1))
                feature = ld(2, ld(1, packed))
        entries += ld(1, ld(1, key.encode()) + ld(2, feature))
    return ld(1, entries)


class ParseSingleExample(Operation):
    """Parse ONE serialized tf.train.Example into a Table of dense tensors
    in `dense_keys` order.  reference: nn/tf/ParsingOps.scala:93."""

    def __init__(self, dense_keys: Sequence[str],
                 dense_shapes: Optional[Sequence[Sequence[int]]] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dense_keys = list(dense_keys)
        self.dense_shapes = ([tuple(s) for s in dense_shapes]
                             if dense_shapes else None)

    def _one_np(self, buf: bytes) -> List[Any]:
        """Numpy-only per-record parse: the host half of `_one`.  Reader
        worker PROCESSES (dataset/readers.py) assemble batches with this —
        a forked child must never touch the inherited jax backend."""
        feats = parse_example_proto(bytes(buf))
        row = []
        for i, k in enumerate(self.dense_keys):
            v = feats[k]
            if isinstance(v, list):  # bytes feature
                row.append(np.asarray(v, dtype=object))
                continue
            if self.dense_shapes:
                v = v.reshape(self.dense_shapes[i])
            row.append(np.asarray(v))
        return row

    def _one(self, buf: bytes) -> List[Any]:
        return [r if r.dtype == object else jnp.asarray(r)
                for r in self._one_np(buf)]

    def compute(self, x):
        buf = x if isinstance(x, (bytes, bytearray)) else bytes(
            np.asarray(x, dtype=object).item())
        return Table(*self._one(buf))


class ParseExample(ParseSingleExample):
    """Parse a BATCH of serialized Examples; dense features are stacked
    along axis 0.  reference: nn/tf/ParsingOps.scala:36."""

    def compute(self, x):
        bufs = [bytes(b) for b in np.asarray(x, dtype=object).reshape(-1)]
        rows = [self._one(b) for b in bufs]
        cols = []
        for i in range(len(self.dense_keys)):
            vals = [r[i] for r in rows]
            if isinstance(vals[0], np.ndarray) and vals[0].dtype == object:
                cols.append(np.stack(vals))
            else:
                cols.append(jnp.stack(vals))
        return Table(*cols)

    def compute_np(self, bufs: Sequence[bytes]) -> List[np.ndarray]:
        """Batch parse with HOST stacking only: same `_one_np` rows as
        `compute`, but no jnp — values land on device later via the
        feed's staging put (bitwise-equal after dtype canonicalization).
        This is the reader-process assembly path."""
        rows = [self._one_np(bytes(b)) for b in bufs]
        return [np.stack([r[i] for r in rows])
                for i in range(len(self.dense_keys))]


# ---------------------------------------------------------------------------
# DataFlowOps: TensorArray / Stack (reference: nn/tf/DataFlowOps.scala).
# Host-side containers used when executing imported TF graphs eagerly; under
# jit, loops carry arrays through lax.scan instead.
# ---------------------------------------------------------------------------


class TensorArrayReadOp(Module):
    """{buffer (T, ...), index} -> buffer[index].  The traced form of
    TensorArrayReadV3: flow values ARE dense buffers in this import
    (reference: DataFlowOps.scala TensorArrayRead).  A differentiable
    Module (not a stop-gradient Operation): imported loops must
    fine-tune through Session.train."""

    def apply(self, params, state, x, *, training=False, rng=None):
        buf, idx = list(x)[:2]
        return jax.lax.dynamic_index_in_dim(
            jnp.asarray(buf), jnp.asarray(idx).reshape(()), 0,
            keepdims=False), state

    def output_shape(self, input_shape):
        buf_shape = list(input_shape)[0]
        return tuple(buf_shape[1:]) if buf_shape else None


class TensorArrayWriteOp(Module):
    """{buffer (T, ...), index, value} -> buffer with row `index` replaced.
    The traced TensorArrayWriteV3 (the returned 'flow' IS the updated
    buffer).  Differentiable, like TensorArrayReadOp."""

    def apply(self, params, state, x, *, training=False, rng=None):
        buf, idx, val = list(x)[:3]
        return jax.lax.dynamic_update_index_in_dim(
            jnp.asarray(buf), jnp.asarray(val),
            jnp.asarray(idx).reshape(()), 0), state

    def output_shape(self, input_shape):
        return list(input_shape)[0]


class TakeRows(Module):
    """Select rows along axis 0 by a CONST index vector (TensorArray
    gather/scatter permutations; identity when idx == arange).
    Differentiable."""

    def __init__(self, indices, name: Optional[str] = None):
        super().__init__(name)
        self.indices = np.asarray(indices, np.int32)

    def apply(self, params, state, x, *, training=False, rng=None):
        if np.array_equal(self.indices, np.arange(len(self.indices))):
            return jnp.asarray(x), state
        return jnp.take(jnp.asarray(x), jnp.asarray(self.indices),
                        axis=0), state

    def output_shape(self, input_shape):
        if input_shape is None:
            return None
        return (len(self.indices),) + tuple(input_shape[1:])


class TFWhile(Module):
    """Structured import of a TF v1 while frame (Enter/Merge/Switch/Exit/
    NextIteration, reference: nn/tf/ControlOps.scala + utils/tf/loaders/
    ControlFlowOps.scala).

    Input: Table(init_1..n, capture_1..m); output Table(final_1..n).
    `cond_graph`/`body_graph` map Table(var_1..n, capture_1..m) to a scalar
    bool / Table(new var_1..n).  When the frame is a counted loop
    (cond = Less(counter, const), counter += 1) the importer passes
    `trip_count` and the loop lowers to `lax.scan` — REVERSE-MODE
    DIFFERENTIABLE, so imported dynamic_rnn graphs fine-tune through
    Session.train; otherwise it lowers to `lax.while_loop` (forward-only).
    """

    _constructor_children = True

    def __init__(self, cond_graph: Module, body_graph: Module, n_vars: int,
                 trip_count: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.cond_graph = cond_graph
        self.body_graph = body_graph
        self.n_vars = n_vars
        self.trip_count = trip_count

    def build(self, rng, input_shape):
        shapes = list(input_shape) if isinstance(input_shape, Table) \
            else [input_shape]
        k1, k2 = jax.random.split(jnp.asarray(rng)) if rng is not None \
            else (None, None)
        pc, sc = {}, {}
        if self.cond_graph is not None:
            pc, sc, _ = self.cond_graph.build(k1, Table(*shapes))
        pb, sb, _ = self.body_graph.build(k2, Table(*shapes))
        out = Table(*shapes[:self.n_vars])
        return {"cond": pc, "body": pb}, {"cond": sc, "body": sb}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        items = list(x) if isinstance(x, Table) else [x]
        vars0 = tuple(jnp.asarray(v) for v in items[:self.n_vars])
        caps = tuple(items[self.n_vars:])

        def run_body(vs):
            out, _ = self.body_graph.apply(
                params["body"], state["body"], Table(*vs, *caps),
                training=training, rng=rng)
            outs = list(out) if isinstance(out, Table) else [out]
            # preserve loop-var dtypes (weak-typed consts can promote)
            return tuple(jnp.asarray(o).astype(v.dtype)
                         for o, v in zip(outs, vars0))

        if self.trip_count is not None:
            def sbody(vs, _):
                return run_body(vs), None

            final, _ = jax.lax.scan(sbody, vars0, None,
                                    length=self.trip_count)
        else:
            def cond_fn(vs):
                c, _ = self.cond_graph.apply(
                    params["cond"], state["cond"], Table(*vs, *caps),
                    training=training, rng=rng)
                return jnp.asarray(c).reshape(())

            final = jax.lax.while_loop(cond_fn, run_body, vars0)
        return Table(*final), state


class TFCond(Module):
    """Structured import of a v1 tf.cond region (standalone Switch/Merge,
    reference: nn/tf/ControlOps.scala SwitchOps/MergeOps +
    utils/tf/loaders/ControlFlowOps.scala) lowered to `lax.cond`.

    Input Table(pred, d_1..d_n); `then_graph`/`else_graph` map the data
    inputs (Table when n > 1) to the branch value."""

    _constructor_children = True

    def __init__(self, then_graph: Module, else_graph: Module,
                 name: Optional[str] = None):
        super().__init__(name)
        self.then_graph = then_graph
        self.else_graph = else_graph

    def _data(self, items):
        data = items[1:]
        return Table(*data) if len(data) > 1 else data[0]

    def build(self, rng, input_shape):
        shapes = list(input_shape) if isinstance(input_shape, Table) \
            else [input_shape]
        dshape = self._data(shapes)
        k1, k2 = jax.random.split(jnp.asarray(rng))
        pt, st, out = self.then_graph.build(k1, dshape)
        pe, se, _ = self.else_graph.build(k2, dshape)
        return ({"then": pt, "else": pe}, {"then": st, "else": se}, out)

    def apply(self, params, state, x, *, training=False, rng=None):
        items = list(x) if isinstance(x, Table) else [x]
        pred = jnp.asarray(items[0]).reshape(())
        data = tuple(jnp.asarray(v) for v in items[1:])

        def run(graph, pkey):
            def fn(d):
                arg = Table(*d) if len(d) > 1 else d[0]
                out, _ = graph.apply(params[pkey], state[pkey], arg,
                                     training=training, rng=rng)
                return out

            return fn

        out = jax.lax.cond(pred, run(self.then_graph, "then"),
                           run(self.else_graph, "else"), data)
        return out, state


class MergeSelect(Module):
    """{pred, true_value, false_value} -> where(pred, t, f).  The import
    lowering of a standalone v1 Switch/Merge cond region: both branches
    compute (pure graphs — same math), Merge selects.  Differentiable
    (gradients flow through the taken branch; the paired SwitchGate
    double-where keeps the untaken branch's reverse-mode finite)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        pred, t, f = list(x)[:3]
        return jnp.where(jnp.asarray(pred).reshape(()), jnp.asarray(t),
                         jnp.asarray(f)), state

    def output_shape(self, input_shape):
        return list(input_shape)[1]


class SwitchGate(Module):
    """One output side of a v1 Switch in the eager cond fallback:
    (data, pred) -> data when this side is TAKEN, a ones fill otherwise.

    This is the double-where clamp that pairs with MergeSelect: the
    untaken branch still executes (eager fallback — both branches are
    plain graph nodes), but on in-domain ones instead of out-of-domain
    real data, so its local derivatives are finite and the masked-zero
    cotangent coming back from MergeSelect's `where` cannot turn into
    0*NaN (guard-style conds like cond(x>0, sqrt(x), c) fine-tune
    without NaN gradients).  Forward values of the taken branch are
    unchanged; the untaken branch's value is discarded by MergeSelect —
    and in real TF it would be a dead tensor, so the fill is closer to
    TF semantics than the old pass-through alias.
    reference: nn/tf/ControlOps.scala SwitchOps."""

    def __init__(self, side: int, name: Optional[str] = None):
        super().__init__(name)
        self.side = side  # 1 = true output (:1), 0 = false output (:0)

    def apply(self, params, state, x, *, training=False, rng=None):
        data, pred = list(x)[:2]
        data = jnp.asarray(data)
        taken = jnp.asarray(pred).reshape(())
        if not self.side:
            taken = jnp.logical_not(taken)
        return jnp.where(taken, data, jnp.ones_like(data)), state

    def output_shape(self, input_shape):
        if isinstance(input_shape, Table):
            return input_shape[1]
        return list(input_shape)[0]


class TensorArray:
    """Growable list of tensors keyed by index
    (reference: DataFlowOps.scala:176-576 TensorArray* ops)."""

    def __init__(self, size: int = 0, dynamic_size: bool = True):
        self._items: Dict[int, Any] = {}
        self.size_hint = size
        self.dynamic_size = dynamic_size

    def write(self, index: int, value):
        if not self.dynamic_size and index >= self.size_hint:
            raise IndexError(f"index {index} out of fixed size {self.size_hint}")
        self._items[index] = value
        return self

    def read(self, index: int):
        return self._items[index]

    def size(self) -> int:
        return max(self.size_hint, (max(self._items) + 1) if self._items else 0)

    def gather(self, indices=None):
        idx = range(self.size()) if indices is None else [int(i) for i in indices]
        return jnp.stack([self._items[i] for i in idx])

    def scatter(self, values):
        for i, v in enumerate(values):
            self.write(i, v)
        return self

    def concat(self):
        return jnp.concatenate([self._items[i] for i in range(self.size())])

    def split(self, value, lengths):
        off = 0
        for i, ln in enumerate(int(v) for v in lengths):
            self.write(i, value[off:off + ln])
            off += ln
        return self

    def close(self):
        self._items.clear()


class Stack:
    """LIFO of tensors (reference: DataFlowOps.scala:579-676 Stack*)."""

    def __init__(self, max_size: int = -1):
        self._items: List[Any] = []
        self.max_size = max_size

    def push(self, v):
        if 0 <= self.max_size <= len(self._items):
            raise OverflowError("stack full")
        self._items.append(v)
        return v

    def pop(self):
        return self._items.pop()


# ---------------------------------------------------------------------------
# ImageOps (reference: nn/tf/ImageOps.scala) — host-side decoders
# ---------------------------------------------------------------------------


class DecodeRaw(Operation):
    """Bytes -> flat tensor of `out_type`.
    reference: nn/tf/ImageOps.scala:150 (little_endian semantics)."""

    def __init__(self, out_type=np.uint8, little_endian: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.out_type = np.dtype(out_type)
        self.little_endian = little_endian

    def compute(self, x):
        buf = x if isinstance(x, (bytes, bytearray)) else bytes(
            np.asarray(x, dtype=object).item())
        dt = self.out_type.newbyteorder("<" if self.little_endian else ">")
        return jnp.asarray(np.frombuffer(buf, dt).astype(self.out_type))


class DecodeImage(Operation):
    """Compressed image bytes -> (H, W, C) uint8 via PIL (host-side).
    reference: nn/tf/ImageOps.scala:36 (DecodeImage base; DecodeJpeg/
    DecodePng/DecodeBmp/DecodeGif below are format-pinned aliases)."""

    _format: Optional[str] = None

    def __init__(self, channels: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.channels = channels

    def compute(self, x):
        import io

        from PIL import Image

        buf = x if isinstance(x, (bytes, bytearray)) else bytes(
            np.asarray(x, dtype=object).item())
        img = Image.open(io.BytesIO(buf))
        if self._format and img.format != self._format:
            raise ValueError(f"expected {self._format}, got {img.format}")
        if self.channels == 1:
            img = img.convert("L")
        elif self.channels == 3:
            img = img.convert("RGB")
        elif self.channels == 4:
            img = img.convert("RGBA")
        # channels == 0: keep the image's native channel count (TF semantics)
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return jnp.asarray(arr)


class DecodeJpeg(DecodeImage):
    _format = "JPEG"


class DecodePng(DecodeImage):
    _format = "PNG"


class DecodeBmp(DecodeImage):
    _format = "BMP"


class DecodeGif(DecodeImage):
    _format = "GIF"


class RandomShuffleOp(Module):
    """TF RandomShuffle: permute along dim 0.  The REFERENCE lowers this
    op to Identity (utils/tf/loaders/RandomShuffle.scala — its graphs use
    it only on input pipelines it replaces anyway); here eval mode keeps
    that identity parity and TRAINING mode genuinely shuffles with the
    step rng (a documented capability delta)."""

    def __init__(self, seed: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.seed = seed

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or rng is None:
            return x, state
        key = jax.random.fold_in(jnp.asarray(rng), self.seed)
        perm = jax.random.permutation(key, x.shape[0])
        return jnp.take(x, perm, axis=0), state
