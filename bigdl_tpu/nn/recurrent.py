"""Recurrent layers.

Reference: nn/Cell.scala (cell contract), nn/RnnCell.scala, nn/LSTM.scala,
nn/GRU.scala, nn/Recurrent.scala:47,241 (timestep loop over CLONED cells
sharing weights), nn/BiRecurrent.scala, nn/TimeDistributed.scala.

TPU-native redesign: the reference unrolls python/JVM-side over timesteps
with per-step cell clones; here the time loop is a single `lax.scan`, so the
whole sequence compiles to one XLA while-loop with the cell body fused.
The 4 gate matmuls of LSTM/GRU are packed into one (in+hidden, 4H) matmul to
keep the MXU busy (the reference computes them as separate gemms).

Input layout: (batch, time, features) — batchNormParams/maskZero options of
the reference's Recurrent are not carried over (capability delta: masking is
done with explicit length masks at the criterion level).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module


class Cell(Module):
    """Single-timestep recurrent cell.

    Protocol: `step(params, x_t, hidden) -> (out_t, new_hidden)` where
    `hidden` is a pytree (array or Table).  reference: nn/Cell.scala.
    """

    hidden_size: int

    def init_hidden(self, batch_size: int, dtype=jnp.float32) -> Any:
        return jnp.zeros((batch_size, self.hidden_size), dtype)

    def init_hidden_for(self, x_t) -> Any:
        """Zero hidden matching a per-step input (convolutional cells use
        its spatial dims)."""
        return self.init_hidden(x_t.shape[0], x_t.dtype)

    def step(self, params, x_t, hidden):
        raise NotImplementedError

    def apply(self, params, state, x, *, training=False, rng=None):
        # standalone use: x is Table(x_t, hidden)
        x_t, hidden = x[1], x[2]
        out, new_hidden = self.step(params, x_t, hidden)
        return Table(out, new_hidden), state


class RnnCell(Cell):
    """Elman RNN cell: h' = act(W x + U h + b). reference: nn/RnnCell.scala."""

    def __init__(self, input_size: int, hidden_size: int,
                 activation="tanh", name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation

    def build(self, rng, input_shape):
        k1, k2, k3 = jax.random.split(rng, 3)
        xavier = init_mod.Xavier()
        params = {
            "w_ih": xavier(k1, (self.input_size, self.hidden_size),
                           self.input_size, self.hidden_size),
            "w_hh": xavier(k2, (self.hidden_size, self.hidden_size),
                           self.hidden_size, self.hidden_size),
            "bias": jnp.zeros((self.hidden_size,), jnp.float32),
        }
        n = input_shape[0]
        return params, {}, (n, self.hidden_size)

    def step(self, params, x_t, hidden):
        act = _resolve_activation(self.activation)
        h = act(x_t @ params["w_ih"] + hidden @ params["w_hh"] + params["bias"])
        return h, h


def _resolve_activation(name):
    """String activation names for cells (serializer-friendly).
    'hard_sigmoid' is the keras-1 variant: clip(0.2x + 0.5, 0, 1)."""
    if callable(name):
        return name
    return {"sigmoid": jax.nn.sigmoid,
            "hard_sigmoid": lambda x: jnp.clip(0.2 * x + 0.5, 0.0, 1.0),
            "tanh": jnp.tanh,
            "relu": jax.nn.relu}[name]


class LSTMCell(Cell):
    """LSTM cell, gates packed in one matmul (order: i, f, g, o).
    reference: nn/LSTM.scala.  Hidden is Table(h, c).
    `gate_activation`/`activation` accept string names so imported keras-1
    models (default inner_activation='hard_sigmoid') compute exactly."""

    def __init__(self, input_size: int, hidden_size: int,
                 forget_bias: float = 0.0,
                 gate_activation: str = "sigmoid",
                 activation: str = "tanh", name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.forget_bias = forget_bias
        self.gate_activation = gate_activation
        self.activation = activation

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        xavier = init_mod.Xavier()
        h = self.hidden_size
        params = {
            "w_ih": xavier(k1, (self.input_size, 4 * h), self.input_size, h),
            "w_hh": xavier(k2, (h, 4 * h), h, h),
            "bias": jnp.zeros((4 * h,), jnp.float32),
        }
        n = input_shape[0]
        return params, {}, (n, h)

    def init_hidden(self, batch_size: int, dtype=jnp.float32):
        z = jnp.zeros((batch_size, self.hidden_size), dtype)
        return Table(z, z)

    def step(self, params, x_t, hidden):
        h_prev, c_prev = hidden[1], hidden[2]
        sig = _resolve_activation(self.gate_activation)
        act = _resolve_activation(self.activation)
        gates = x_t @ params["w_ih"] + h_prev @ params["w_hh"] + params["bias"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = sig(i)
        f = sig(f + self.forget_bias)
        g = act(g)
        o = sig(o)
        c = f * c_prev + i * g
        h = o * act(c)
        return h, Table(h, c)


class GRUCell(Cell):
    """GRU cell, all gates packed (order: r, z, n).

    reset_after=True (default, torch convention): the reset gate applies
    AFTER the hidden matmul, so the three hidden projections fuse into one
    (H, 3H) MXU matmul.  reset_after=False (keras-1 convention,
    keras/layers/recurrent.py GRU: tanh(x W + (r*h) U)): the n-gate hidden
    projection runs on r*h — one extra (H, H) matmul, but keras-1.2.2 GRU
    weights import EXACTLY.  reference: nn/GRU.scala."""

    def __init__(self, input_size: int, hidden_size: int, *,
                 reset_after: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.reset_after = reset_after

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        xavier = init_mod.Xavier()
        h = self.hidden_size
        params = {
            "w_ih": xavier(k1, (self.input_size, 3 * h), self.input_size, h),
            "w_hh": xavier(k2, (h, 3 * h), h, h),
            "bias": jnp.zeros((3 * h,), jnp.float32),
        }
        if self.reset_after:
            # torch's inner n-gate bias: n = tanh(.. + r*(h W_hn + b_hn)).
            # Separate because r multiplies it — folding into `bias` is
            # only exact when b_hn = 0 (zero init keeps that default).
            params["bias_hn"] = jnp.zeros((h,), jnp.float32)
        n = input_shape[0]
        return params, {}, (n, h)

    def step(self, params, x_t, hidden):
        gi = x_t @ params["w_ih"] + params["bias"]
        gi_r, gi_z, gi_n = jnp.split(gi, 3, axis=-1)
        if self.reset_after:
            gh = hidden @ params["w_hh"]
            gh_r, gh_z, gh_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(gi_r + gh_r)
            z = jax.nn.sigmoid(gi_z + gh_z)
            n = jnp.tanh(gi_n + r * (gh_n + params["bias_hn"]))
        else:
            h2 = self.hidden_size * 2
            gh_rz = hidden @ params["w_hh"][:, :h2]
            gh_r, gh_z = jnp.split(gh_rz, 2, axis=-1)
            r = jax.nn.sigmoid(gi_r + gh_r)
            z = jax.nn.sigmoid(gi_z + gh_z)
            n = jnp.tanh(gi_n + (r * hidden) @ params["w_hh"][:, h2:])
        h = (1.0 - z) * n + z * hidden
        return h, h


class Recurrent(Module):
    """Scan a cell over the time axis.
    reference: nn/Recurrent.scala (JVM-side unroll -> lax.scan here)."""

    def __init__(self, cell: Cell, return_state: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.cell = cell
        self.return_state = return_state

    def build(self, rng, input_shape):
        # rank-agnostic: (B, T, F) for dense cells, (B, T, H, W, C) for
        # convolutional cells
        n, t = input_shape[0], input_shape[1]
        p, s, out = self.cell.build(rng, (n,) + tuple(input_shape[2:]))
        return {"cell": p}, {"cell": s}, (n, t) + tuple(out[1:])

    def apply(self, params, state, x, *, training=False, rng=None):
        xs = jnp.swapaxes(x, 0, 1)  # (T, B, ...)
        h0 = self.cell.init_hidden_for(xs[0])

        def body(hidden, x_t):
            out, new_hidden = self.cell.step(params["cell"], x_t, hidden)
            return new_hidden, out

        last_hidden, outs = lax.scan(body, h0, xs)
        y = jnp.swapaxes(outs, 0, 1)  # (B, T, ...)
        if self.return_state:
            return Table(y, last_hidden), state
        return y, state

    def output_shape(self, input_shape):
        n, t = input_shape[0], input_shape[1]
        if len(input_shape) == 3:
            return (n, t, self.cell.hidden_size)
        # convolutional cell: SAME-padded, spatial dims preserved
        return (n, t) + tuple(input_shape[2:-1]) + (self.cell.hidden_size,)


def LSTM(input_size: int, hidden_size: int, name: Optional[str] = None) -> Recurrent:
    """reference: nn/LSTM.scala (used inside Recurrent)."""
    return Recurrent(LSTMCell(input_size, hidden_size), name=name)


def GRU(input_size: int, hidden_size: int, *, reset_after: bool = True,
        name: Optional[str] = None) -> Recurrent:
    return Recurrent(GRUCell(input_size, hidden_size,
                             reset_after=reset_after), name=name)


def RnnLayer(input_size: int, hidden_size: int, activation=jnp.tanh,
             name: Optional[str] = None) -> Recurrent:
    return Recurrent(RnnCell(input_size, hidden_size, activation), name=name)


class BiRecurrent(Module):
    """Bidirectional scan; merge = 'concat' | 'add'.
    reference: nn/BiRecurrent.scala."""

    def __init__(self, cell_fwd: Cell, cell_bwd: Cell, merge: str = "concat",
                 return_sequences: bool = True, name: Optional[str] = None):
        super().__init__(name)
        assert merge in ("concat", "add", "sum", "mul", "ave")
        self.fwd = Recurrent(cell_fwd)
        self.bwd = Recurrent(cell_bwd)
        self.merge = merge
        # return_sequences=False: merge the two FINAL outputs (fwd at t-1,
        # bwd at original index 0 — the backward cell's full-sequence
        # output), matching Keras Bidirectional semantics
        self.return_sequences = return_sequences

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        p1, s1, _ = self.fwd.build(k1, input_shape)
        p2, s2, _ = self.bwd.build(k2, input_shape)
        return ({"fwd": p1, "bwd": p2}, {"fwd": s1, "bwd": s2},
                self.output_shape(input_shape))

    def apply(self, params, state, x, *, training=False, rng=None):
        y_f, _ = self.fwd.apply(params["fwd"], state["fwd"], x, training=training)
        x_rev = jnp.flip(x, axis=1)
        y_b, _ = self.bwd.apply(params["bwd"], state["bwd"], x_rev, training=training)
        y_b = jnp.flip(y_b, axis=1)
        if not self.return_sequences:
            y_f, y_b = y_f[:, -1], y_b[:, 0]
        if self.merge == "concat":
            return jnp.concatenate([y_f, y_b], axis=-1), state
        if self.merge == "mul":
            return y_f * y_b, state
        if self.merge == "ave":
            return (y_f + y_b) / 2.0, state
        return y_f + y_b, state

    def output_shape(self, input_shape):
        n, t, _ = input_shape
        h = 2 * self.fwd.cell.hidden_size if self.merge == "concat" \
            else self.fwd.cell.hidden_size
        return (n, t, h) if self.return_sequences else (n, h)


class TimeDistributed(Module):
    """Apply a module independently at each timestep by folding time into
    batch. reference: nn/TimeDistributed.scala."""

    def __init__(self, module: Module, name: Optional[str] = None):
        super().__init__(name)
        self.inner = module

    def build(self, rng, input_shape):
        n, t = input_shape[0], input_shape[1]
        p, s, out = self.inner.build(rng, (n * t,) + tuple(input_shape[2:]))
        return {"inner": p}, {"inner": s}, (n, t) + tuple(out[1:])

    def apply(self, params, state, x, *, training=False, rng=None):
        n, t = x.shape[0], x.shape[1]
        flat = jnp.reshape(x, (n * t,) + x.shape[2:])
        y, s = self.inner.apply(params["inner"], state["inner"], flat,
                                training=training, rng=rng)
        return jnp.reshape(y, (n, t) + y.shape[1:]), {"inner": s}


class LSTMPeephole(Cell):
    """LSTM with peephole connections: i and f gates see c_prev, o sees the
    new c.  reference: nn/LSTMPeephole.scala.  Hidden is Table(h, c)."""

    def __init__(self, input_size: int, hidden_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size

    def build(self, rng, input_shape):
        k1, k2, k3 = jax.random.split(rng, 3)
        xavier = init_mod.Xavier()
        h = self.hidden_size
        params = {
            "w_ih": xavier(k1, (self.input_size, 4 * h), self.input_size, h),
            "w_hh": xavier(k2, (h, 4 * h), h, h),
            # per-channel peephole weights (the reference's CMul vectors)
            "peep": xavier(k3, (3, h), h, h),
            "bias": jnp.zeros((4 * h,), jnp.float32),
        }
        n = input_shape[0]
        return params, {}, (n, h)

    def init_hidden(self, batch_size: int, dtype=jnp.float32):
        z = jnp.zeros((batch_size, self.hidden_size), dtype)
        return Table(z, z)

    def step(self, params, x_t, hidden):
        h_prev, c_prev = hidden[1], hidden[2]
        gates = x_t @ params["w_ih"] + h_prev @ params["w_hh"] + params["bias"]
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        p_i, p_f, p_o = params["peep"][0], params["peep"][1], params["peep"][2]
        i = jax.nn.sigmoid(i + p_i * c_prev)
        f = jax.nn.sigmoid(f + p_f * c_prev)
        g = jnp.tanh(g)
        c = f * c_prev + i * g
        o = jax.nn.sigmoid(o + p_o * c)
        h = o * jnp.tanh(c)
        return h, Table(h, c)


class ConvLSTMPeephole(Cell):
    """Convolutional LSTM with optional peepholes over NHWC maps.
    reference: nn/ConvLSTMPeephole.scala (kernelI over input, kernelC over
    hidden, SAME padding so spatial dims are preserved).  The spatial rank
    is a class attribute so the 3-D twin (nn/ConvLSTMPeephole3D.scala)
    shares the gate wiring."""

    _rank = 2
    _dimspec = ("NHWC", "HWIO", "NHWC")

    def __init__(self, input_size: int, output_size: int, kernel_i: int = 3,
                 kernel_c: int = 3, stride: int = 1, with_peephole: bool = True,
                 gate_activation: str = "sigmoid", activation: str = "tanh",
                 name: Optional[str] = None):
        super().__init__(name)
        assert stride == 1, "ConvLSTM hidden recurrence requires stride 1"
        self.input_size = input_size
        self.hidden_size = output_size
        self.kernel_i = kernel_i
        self.kernel_c = kernel_c
        self.with_peephole = with_peephole
        # string names so imported keras-1 ConvLSTM2D models (default
        # inner_activation='hard_sigmoid') compute exactly
        self.gate_activation = gate_activation
        self.activation = activation
        self._spatial: Optional[Tuple[int, ...]] = None

    def build(self, rng, input_shape):
        # input_shape: (B, *spatial, C_in)
        k1, k2, k3 = jax.random.split(rng, 3)
        xavier = init_mod.Xavier()
        ci, co = self.input_size, self.hidden_size
        ki, kc = self.kernel_i, self.kernel_c
        r = self._rank
        params = {
            "w_ih": xavier(k1, (ki,) * r + (ci, 4 * co), ki**r * ci, ki**r * co),
            "w_hh": xavier(k2, (kc,) * r + (co, 4 * co), kc**r * co, kc**r * co),
            "bias": jnp.zeros((4 * co,), jnp.float32),
        }
        if self.with_peephole:
            params["peep"] = xavier(k3, (3, co), co, co)
        self._spatial = tuple(input_shape[1:1 + r])
        n = input_shape[0]
        return params, {}, (n,) + self._spatial + (co,)

    def init_hidden(self, batch_size: int, dtype=jnp.float32):
        assert self._spatial is not None, "build() first"
        z = jnp.zeros((batch_size,) + self._spatial + (self.hidden_size,), dtype)
        return Table(z, z)

    def init_hidden_for(self, x_t):
        z = jnp.zeros(x_t.shape[:-1] + (self.hidden_size,), x_t.dtype)
        return Table(z, z)

    def step(self, params, x_t, hidden):
        h_prev, c_prev = hidden[1], hidden[2]
        sig = _resolve_activation(self.gate_activation)
        act = _resolve_activation(self.activation)
        ones = (1,) * self._rank
        gates = (
            lax.conv_general_dilated(x_t, params["w_ih"], ones, "SAME",
                                     dimension_numbers=self._dimspec)
            + lax.conv_general_dilated(h_prev, params["w_hh"], ones, "SAME",
                                       dimension_numbers=self._dimspec)
            + params["bias"])
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if self.with_peephole:
            p_i, p_f, p_o = params["peep"][0], params["peep"][1], params["peep"][2]
            i = sig(i + p_i * c_prev)
            f = sig(f + p_f * c_prev)
        else:
            i = sig(i)
            f = sig(f)
        g = act(g)
        c = f * c_prev + i * g
        if self.with_peephole:
            o = sig(o + p_o * c)
        else:
            o = sig(o)
        h = o * act(c)
        return h, Table(h, c)


class MultiRNNCell(Cell):
    """Stack of cells applied in sequence within one timestep; hidden is a
    Table of each cell's hidden.  reference: nn/MultiRNNCell.scala."""

    def __init__(self, cells, name: Optional[str] = None):
        super().__init__(name)
        self.cells = list(cells)
        self.hidden_size = self.cells[-1].hidden_size

    def build(self, rng, input_shape):
        keys = jax.random.split(rng, len(self.cells))
        params, states = {}, {}
        shape = input_shape
        for idx, (k, cell) in enumerate(zip(keys, self.cells)):
            p, s, shape = cell.build(k, shape)
            params[str(idx)] = p
            states[str(idx)] = s
        return params, states, shape

    def init_hidden(self, batch_size: int, dtype=jnp.float32):
        return Table(*[c.init_hidden(batch_size, dtype) for c in self.cells])

    def init_hidden_for(self, x_t):
        return Table(*[c.init_hidden_for(x_t) for c in self.cells])

    def step(self, params, x_t, hidden):
        new_hiddens = []
        out = x_t
        for idx, cell in enumerate(self.cells):
            out, h = cell.step(params[str(idx)], out, hidden[idx + 1])
            new_hiddens.append(h)
        return out, Table(*new_hiddens)


class RecurrentDecoder(Module):
    """Autoregressive decoder: scans `seq_length` steps feeding each step's
    output back as the next input (cell output size must equal its input
    size).  Input is the first-step input (B, F) or (B, H, W, C); output is
    (B, T, ...).  reference: nn/RecurrentDecoder.scala."""

    def __init__(self, cell: Cell, seq_length: int, name: Optional[str] = None):
        super().__init__(name)
        self.cell = cell
        self.seq_length = seq_length

    def build(self, rng, input_shape):
        p, s, out = self.cell.build(rng, input_shape)
        if tuple(out) != tuple(input_shape):
            raise ValueError(
                f"RecurrentDecoder feeds outputs back as inputs; the cell "
                f"output shape {tuple(out)} must equal its input shape "
                f"{tuple(input_shape)} (reference: RecurrentDecoder.scala "
                f"requires outputSize == inputSize)")
        return {"cell": p}, {"cell": s}, (out[0], self.seq_length) + tuple(out[1:])

    def apply(self, params, state, x, *, training=False, rng=None):
        h0 = self.cell.init_hidden_for(x)

        def body(carry, _):
            inp, hidden = carry
            out, new_hidden = self.cell.step(params["cell"], inp, hidden)
            return (out, new_hidden), out

        _, outs = lax.scan(body, (x, h0), None, length=self.seq_length)
        return jnp.swapaxes(outs, 0, 1), state

    def output_shape(self, input_shape):
        return (input_shape[0], self.seq_length) + tuple(input_shape[1:])


class ConvLSTMPeephole3D(ConvLSTMPeephole):
    """Convolutional LSTM over NDHWC volumes with optional peepholes.
    reference: nn/ConvLSTMPeephole3D.scala — same gate wiring as the 2-D
    cell, volumetric kernels."""

    _rank = 3
    _dimspec = ("NDHWC", "DHWIO", "NDHWC")
