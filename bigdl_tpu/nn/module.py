"""Module base classes.

Replaces `AbstractModule[A, B, T]` (reference:
nn/abstractnn/AbstractModule.scala:58).  The reference API is stateful and
autograd-by-hand (`forward` caches `output`, `backward` =
`updateGradInput` + `accGradParameters`); here the core protocol is pure:

    params, state, out_shape = module.build(rng, input_shape)
    output, new_state       = module.apply(params, state, x, training=...)

`params` are trainable leaves (pytree), `state` is non-trained buffers
(BatchNorm running stats — the analogue of runningMean/runningVar).  Autograd
is `jax.grad` of a loss over `apply`; there is no per-layer backward.

A thin stateful convenience layer (`init` / `forward`) mirrors the reference
ergonomics for interactive use and the Keras-style frontend; trainers use the
functional protocol so the whole step jits into one XLA program.

Shapes are tuples INCLUDING the batch dimension, NHWC layout for images
(TPU-native; the reference is NCHW — documented capability-parity delta).
Multi-activity inputs/outputs are `Table`s (see core/table.py), matching the
reference's `Activity = Tensor | Table` union.
"""

from __future__ import annotations

import functools
import inspect
import itertools
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu.core.random import RandomGenerator
from bigdl_tpu.core.table import Table

_counter = itertools.count()

Shape = Tuple[int, ...]


def capture_init(cls) -> None:
    """Wrap `cls.__init__` to record the bound constructor arguments on the
    instance (`_captured_config`, `_captured_vararg`).

    This is the substrate for the reflection-driven model serializer
    (reference: utils/serializer/ModuleSerializer.scala:34-107 walks class
    constructors via scala reflection at LOAD time; here the same
    information is captured at CONSTRUCTION time, which also covers
    default arguments).  Only the outermost (most-derived) __init__ call
    records; nested super().__init__ calls are ignored.
    """
    orig = cls.__dict__.get("__init__")
    if orig is None or getattr(orig, "_config_capture", False):
        return
    sig = inspect.signature(orig)

    @functools.wraps(orig)
    def wrapped(self, *args, **kwargs):
        if not hasattr(self, "_captured_config"):
            self._captured_config = None
            self._captured_vararg = None
            try:
                bound = sig.bind(self, *args, **kwargs)
            except TypeError:
                pass
            else:
                cfg = OrderedDict()
                for p in list(sig.parameters.values())[1:]:
                    if p.name in bound.arguments:
                        v = bound.arguments[p.name]
                    elif p.default is not inspect.Parameter.empty:
                        v = p.default
                    else:
                        continue
                    if p.kind is inspect.Parameter.VAR_POSITIONAL:
                        self._captured_vararg = (p.name, list(v))
                    elif p.kind is inspect.Parameter.VAR_KEYWORD:
                        cfg.update(v)
                    else:
                        cfg[p.name] = v
                self._captured_config = cfg
        orig(self, *args, **kwargs)

    wrapped._config_capture = True
    cls.__init__ = wrapped


def shape_of(x: Any) -> Any:
    """Structure-preserving shape extraction (arrays -> shape tuples)."""
    if isinstance(x, Table):
        t = Table()
        for k, v in x.items():
            t[k] = shape_of(v)
        return t
    if isinstance(x, (list, tuple)):
        return type(x)(shape_of(v) for v in x)
    return tuple(x.shape)


def _is_shape(s: Any) -> bool:
    return isinstance(s, tuple) and all(isinstance(i, int) for i in s)


class Module:
    """Base module. Subclasses implement `build` and `apply`."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        capture_init(cls)

    def __init__(self, name: Optional[str] = None):
        self.name = name or f"{type(self).__name__.lower()}_{next(_counter)}"
        # stateful convenience slots (not used by the functional path)
        self.params: Any = None
        self.state: Any = None
        self.training: bool = True

    # ------------------------------------------------------------------
    # Functional protocol
    # ------------------------------------------------------------------

    def build(self, rng: jax.Array, input_shape: Any):
        """Create (params, state) for `input_shape`; return output shape too.

        Analogue of the reference's lazy build + `computeOutputShape`
        (nn/abstractnn/InferShape.scala).
        """
        return {}, {}, self.output_shape(input_shape)

    def apply(self, params: Any, state: Any, x: Any, *, training: bool = False,
              rng: Optional[jax.Array] = None):
        raise NotImplementedError(type(self).__name__)

    def flattened_modules(self) -> List["Module"]:
        """Every module in the `children` subtree, depth-first, self
        included — for passes that must reach nested structure (e.g.
        sync-BN patching of BNs inside residual Graph blocks).  Modules
        held as plain attributes (a TFWhile's body graph, a KerasLayer's
        lazily built inner) are NOT traversed."""
        out: List["Module"] = [self]
        for c in getattr(self, "children", {}).values():
            out.extend(c.flattened_modules())
        return out

    def output_shape(self, input_shape: Any) -> Any:
        """Shape inference for stateless modules; stateful ones override
        `build` and may compute it there."""
        return input_shape

    # ------------------------------------------------------------------
    # Stateful convenience (mirrors reference forward/evaluate ergonomics)
    # ------------------------------------------------------------------

    def init(self, input_shape: Any, rng: Optional[jax.Array] = None):
        if rng is None:
            rng = RandomGenerator.next_key()
        self.params, self.state, out = self.build(rng, input_shape)
        return self.params, self.state

    def forward(self, x: Any, rng: Optional[jax.Array] = None) -> Any:
        """Stateful forward using stored params (lazy-inits from x)."""
        if self.params is None:
            self.init(shape_of(x))
        y, new_state = self.apply(self.params, self.state, x,
                                  training=self.training, rng=rng)
        self.state = new_state
        return y

    def evaluate(self) -> "Module":
        """Eval mode (reference: AbstractModule.evaluate, :438-447)."""
        self.training = False
        return self

    def train_mode(self) -> "Module":
        self.training = True
        return self

    # -- inference sugar over stored params (reference: the predict*/
    # evaluate(rdd)/quantize convenience API on AbstractModule) -----------

    def _predictor(self, x: Any, batch_size: int, mesh):
        """Cached Predictor (a fresh one per call would re-jit every time);
        invalidated when params/state/batch/mesh change identity."""
        from bigdl_tpu.optim.predictor import Predictor  # avoid cycle

        if self.params is None:
            self.init(shape_of(x))
        # strong refs in the key: `is` checks on live objects, never ids
        # (a freed dict's id can be reused, which would serve stale weights)
        cached = getattr(self, "_predictor_cache", None)
        if (cached is None or cached[0] is not self.params
                or cached[1] is not self.state or cached[2] != batch_size
                or cached[3] is not mesh):
            self._predictor_cache = (self.params, self.state, batch_size, mesh,
                                     Predictor(self, self.params, self.state,
                                               mesh=mesh, batch_size=batch_size))
        return self._predictor_cache[4]

    def predict(self, x: Any, batch_size: int = 32, mesh=None):
        """Batched jitted inference (reference: AbstractModule.predict,
        :636 — the RDD is just host arrays here)."""
        return self._predictor(x, batch_size, mesh).predict(x)

    def predict_class(self, x: Any, batch_size: int = 32, mesh=None):
        """reference: AbstractModule.predictClass (:693)."""
        return self._predictor(x, batch_size, mesh).predict_class(x)

    def quantize(self) -> "Module":
        """Int8 inference copy of this (trained) module; weights must be on
        `.params`. reference: AbstractModule.quantize (:918)."""
        from bigdl_tpu.nn.quantized import quantize as _quantize  # avoid cycle

        if self.params is None:
            raise ValueError("quantize() needs trained weights on .params "
                             "(run init()/optimize() first)")
        qm, qp = _quantize(self, self.params)
        qm.params = qp
        qm.state = self.state
        return qm

    # ------------------------------------------------------------------
    # Graph-building sugar: calling a module on Node(s) records an edge
    # (reference: `layer.inputs(node)`, nn/Graph.scala:72)
    # ------------------------------------------------------------------

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if args and all(isinstance(a, Node) for a in args):
            return self.inputs(*args)
        return self.forward(*args, **kwargs)

    def inputs(self, *nodes: "Node") -> "Node":
        return Node(self, list(nodes))

    # ------------------------------------------------------------------

    def param_count(self, params: Any = None) -> int:
        p = params if params is not None else self.params
        return sum(int(leaf.size) for leaf in jax.tree_util.tree_leaves(p))

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.name})"


capture_init(Module)


class Node:
    """A node in a model DAG under construction (reference: utils/Node.scala
    + nn/Graph.scala node wiring)."""

    def __init__(self, module: Optional[Module], prevs: List["Node"]):
        self.module = module
        self.prevs = prevs
        self.name = module.name if module else f"input_{next(_counter)}"


def Input(name: Optional[str] = None) -> Node:
    """Graph input placeholder (reference: nn/Input.scala)."""
    n = Node(None, [])
    if name:
        n.name = name
    return n


class Container(Module):
    """Module with named children (reference: nn/Container.scala)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(name)
        self.children: "OrderedDict[str, Module]" = OrderedDict()

    def add(self, module: Module) -> "Container":
        key = str(len(self.children))
        self.children[key] = module
        return self

    def __getitem__(self, i: int) -> Module:
        return list(self.children.values())[i]

    def __len__(self) -> int:
        return len(self.children)

    def modules(self) -> List[Module]:
        """DIRECT children (reference: Container.scala `modules` buffer)."""
        return list(self.children.values())

    def __repr__(self) -> str:
        inner = ", ".join(repr(m) for m in self.children.values())
        return f"{type(self).__name__}[{inner}]"


def child_rng(rng: Optional[jax.Array], i: int) -> Optional[jax.Array]:
    if rng is None:
        return None
    return jax.random.fold_in(rng, i)


class Sequential(Container):
    """Feed-forward chain (reference: nn/Sequential.scala)."""

    def __init__(self, *modules: Module, name: Optional[str] = None):
        super().__init__(name)
        for m in modules:
            self.add(m)

    def build(self, rng, input_shape):
        params, state = {}, {}
        shape = input_shape
        for i, (key, m) in enumerate(self.children.items()):
            p, s, shape = m.build(jax.random.fold_in(rng, i), shape)
            params[key] = p
            state[key] = s
        return params, state, shape

    def apply(self, params, state, x, *, training=False, rng=None):
        new_state = {}
        for i, (key, m) in enumerate(self.children.items()):
            x, new_state[key] = m.apply(params[key], state[key], x,
                                        training=training, rng=child_rng(rng, i))
        return x, new_state

    def output_shape(self, input_shape):
        shape = input_shape
        for m in self.children.values():
            shape = m.output_shape(shape)
        return shape
