"""Elementwise/table arithmetic layers.

Reference: nn/CAddTable.scala and friends (CSubTable, CMulTable, CDivTable,
CMaxTable, CMinTable, CAveTable), nn/MM.scala, nn/Mul.scala, nn/Add.scala,
nn/CMul.scala, nn/CAdd.scala, nn/Scale.scala, nn/MulConstant.scala,
nn/AddConstant.scala, nn/Power.scala, nn/Sqrt.scala, nn/Square.scala,
nn/Log.scala, nn/Exp.scala, nn/Abs.scala, nn/Clamp.scala, nn/Mean.scala,
nn/Sum.scala, nn/Max.scala, nn/Min.scala, nn/Cosine.scala,
nn/DotProduct.scala.  All fuse into neighbouring ops under XLA.

Table-op inputs are `Table`s (or plain sequences) of tensors.
"""

from __future__ import annotations

import functools
import operator
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module


def _items(x):
    return list(x) if isinstance(x, (Table, list, tuple)) else [x]


class _TableReduce(Module):
    _op = None

    def apply(self, params, state, x, *, training=False, rng=None):
        items = _items(x)
        return functools.reduce(type(self)._op, items), state

    def output_shape(self, input_shape):
        shapes = _items(input_shape)
        return shapes[0]


class CAddTable(_TableReduce):
    _op = staticmethod(operator.add)


class CSubTable(_TableReduce):
    _op = staticmethod(operator.sub)


class CMulTable(_TableReduce):
    _op = staticmethod(operator.mul)


class CDivTable(_TableReduce):
    _op = staticmethod(operator.truediv)


class CMaxTable(_TableReduce):
    _op = staticmethod(jnp.maximum)


class CMinTable(_TableReduce):
    _op = staticmethod(jnp.minimum)


class CAveTable(_TableReduce):
    def apply(self, params, state, x, *, training=False, rng=None):
        items = _items(x)
        return sum(items) / len(items), state


class MM(Module):
    """Batched matmul of a 2-tensor Table. reference: nn/MM.scala."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.trans_a, self.trans_b = trans_a, trans_b

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = _items(x)
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b, state

    def output_shape(self, input_shape):
        sa, sb = [list(s) for s in _items(input_shape)]
        if self.trans_a:
            sa[-1], sa[-2] = sa[-2], sa[-1]
        if self.trans_b:
            sb[-1], sb[-2] = sb[-2], sb[-1]
        # numpy batch broadcasting over the leading dims
        ba, bb = sa[:-2], sb[:-2]
        n = max(len(ba), len(bb))
        ba = [1] * (n - len(ba)) + ba
        bb = [1] * (n - len(bb)) + bb
        batch = [max(x, y) for x, y in zip(ba, bb)]
        return tuple(batch) + (sa[-2], sb[-1])


class Mul(Module):
    """Single learnable scalar gain. reference: nn/Mul.scala."""

    def build(self, rng, input_shape):
        return {"weight": jnp.ones((1,), jnp.float32)}, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        return x * params["weight"], state


class Add(Module):
    """Learnable bias vector. reference: nn/Add.scala."""

    def __init__(self, input_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size

    def build(self, rng, input_shape):
        return {"bias": jnp.zeros((self.input_size,), jnp.float32)}, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        return x + params["bias"], state


class CMul(Module):
    """Learnable componentwise gain of given shape. reference: nn/CMul.scala."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(self.size, jnp.float32)}, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        return x * params["weight"], state


class CAdd(Module):
    """Learnable componentwise bias of given shape. reference: nn/CAdd.scala."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def build(self, rng, input_shape):
        return {"bias": jnp.zeros(self.size, jnp.float32)}, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        return x + params["bias"], state


class Scale(Module):
    """CMul then CAdd. reference: nn/Scale.scala."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def build(self, rng, input_shape):
        return {"weight": jnp.ones(self.size, jnp.float32),
                "bias": jnp.zeros(self.size, jnp.float32)}, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        return x * params["weight"] + params["bias"], state


class MulConstant(Module):
    def __init__(self, scalar: float, ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.scalar = scalar

    def apply(self, params, state, x, *, training=False, rng=None):
        return x * self.scalar, state


class AddConstant(Module):
    def __init__(self, constant_scalar: float, ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.constant = constant_scalar

    def apply(self, params, state, x, *, training=False, rng=None):
        return x + self.constant, state


class Power(Module):
    """(shift + scale*x)^power. reference: nn/Power.scala."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.power, self.scale, self.shift = power, scale, shift

    def apply(self, params, state, x, *, training=False, rng=None):
        return (self.shift + self.scale * x) ** self.power, state


class Sqrt(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.sqrt(x), state


class Square(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.square(x), state


class Log(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.log(x), state


class Exp(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.exp(x), state


class Abs(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.abs(x), state


class Clamp(Module):
    def __init__(self, min_value: float, max_value: float, name: Optional[str] = None):
        super().__init__(name)
        self.min_value, self.max_value = min_value, max_value

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.clip(x, self.min_value, self.max_value), state


class _Reduce(Module):
    def __init__(self, dimension: int = 0, squeeze: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dimension = dimension
        self.squeeze = squeeze

    _fn = None

    def apply(self, params, state, x, *, training=False, rng=None):
        y = type(self)._fn(x, axis=self.dimension, keepdims=not self.squeeze)
        return y, state

    def output_shape(self, input_shape):
        s = list(input_shape)
        if self.squeeze:
            del s[self.dimension]
        else:
            s[self.dimension] = 1
        return tuple(s)


class Mean(_Reduce):
    _fn = staticmethod(jnp.mean)


class Sum(_Reduce):
    _fn = staticmethod(jnp.sum)


class Max(_Reduce):
    _fn = staticmethod(jnp.max)


class Min(_Reduce):
    _fn = staticmethod(jnp.min)


class Cosine(Module):
    """Cosine similarity of rows against learnable weights.
    reference: nn/Cosine.scala."""

    def __init__(self, input_size: int, output_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.output_size = input_size, output_size

    def build(self, rng, input_shape):
        w = init_mod.RandomUniform()(rng, (self.input_size, self.output_size),
                                     self.input_size, self.output_size)
        return {"weight": w}, {}, (input_shape[0], self.output_size)

    def apply(self, params, state, x, *, training=False, rng=None):
        w = params["weight"]
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
        wn = w / jnp.maximum(jnp.linalg.norm(w, axis=0, keepdims=True), 1e-12)
        return xn @ wn, state


class DotProduct(Module):
    """Rowwise dot of a 2-tensor Table. reference: nn/DotProduct.scala."""

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = _items(x)
        return jnp.sum(a * b, axis=-1), state


class MV(Module):
    """Matrix-vector product of a 2-tensor Table: {mat (b, n, m) or (n, m),
    vec (b, m) or (m,)} -> (b, n) or (n,).  reference: nn/MV.scala:33-84."""

    def __init__(self, trans: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.trans = trans

    def apply(self, params, state, x, *, training=False, rng=None):
        m, v = _items(x)
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...nm,...m->...n", m, v), state

    def output_shape(self, input_shape):
        ms = list(_items(input_shape)[0])
        if self.trans:
            ms[-1], ms[-2] = ms[-2], ms[-1]
        return tuple(ms[:-1])
