"""Weight initialization methods.

Reference: nn/InitializationMethod.scala + nn/abstractnn/Initializable.scala
(Zeros, Ones, ConstInit, RandomUniform, RandomNormal, Xavier, MsraFiller,
BilinearFiller).  Each method is a callable `(rng, shape, fan_in, fan_out,
dtype) -> array`; layers expose `set_init_method(weight_init, bias_init)`
like the reference's `setInitMethod`.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


class InitializationMethod:
    def __call__(self, rng, shape, fan_in: int, fan_out: int, dtype=jnp.float32):
        raise NotImplementedError


class Zeros(InitializationMethod):
    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)


class Ones(InitializationMethod):
    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.ones(shape, dtype)


class ConstInit(InitializationMethod):
    def __init__(self, value: float):
        self.value = value

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return jnp.full(shape, self.value, dtype)


class RandomUniform(InitializationMethod):
    """U(lower, upper); with no bounds, BigDL uses +-1/sqrt(fan_in)
    (reference: nn/InitializationMethod.scala RandomUniform)."""

    def __init__(self, lower: Optional[float] = None, upper: Optional[float] = None):
        if (lower is None) != (upper is None):
            raise ValueError("RandomUniform needs both bounds or neither")
        self.lower, self.upper = lower, upper

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        if self.lower is None:
            bound = 1.0 / math.sqrt(max(1, fan_in))
            lo, hi = -bound, bound
        else:
            lo, hi = self.lower, self.upper
        return jax.random.uniform(rng, shape, dtype, minval=lo, maxval=hi)


class RandomNormal(InitializationMethod):
    def __init__(self, mean: float = 0.0, stdv: float = 1.0):
        self.mean, self.stdv = mean, stdv

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        return self.mean + self.stdv * jax.random.normal(rng, shape, dtype)


class Xavier(InitializationMethod):
    """Glorot uniform: U(+-sqrt(6/(fan_in+fan_out)))."""

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        bound = math.sqrt(6.0 / max(1, fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, minval=-bound, maxval=bound)


class MsraFiller(InitializationMethod):
    """He init; varianceNormAverage=True averages fan_in/fan_out
    (reference MsraFiller)."""

    def __init__(self, variance_norm_average: bool = True):
        self.avg = variance_norm_average

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        n = (fan_in + fan_out) / 2.0 if self.avg else float(fan_in)
        std = math.sqrt(2.0 / max(1.0, n))
        return std * jax.random.normal(rng, shape, dtype)


class BilinearFiller(InitializationMethod):
    """Bilinear upsampling kernel for deconvolution weights (HWIO)."""

    def __call__(self, rng, shape, fan_in, fan_out, dtype=jnp.float32):
        kh, kw, cin, cout = shape
        f_h, f_w = math.ceil(kh / 2.0), math.ceil(kw / 2.0)
        c_h, c_w = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h), (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        ii, jj = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
        filt = (1 - jnp.abs(ii / f_h - c_h)) * (1 - jnp.abs(jj / f_w - c_w))
        # only the (in == out) channel-pair diagonal carries the filter, so
        # each channel is upsampled independently (no channel mixing)
        diag = jnp.eye(cin, cout, dtype=dtype)
        return (filt[..., None, None] * diag).astype(dtype)
