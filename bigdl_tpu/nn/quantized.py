"""Int8 quantized inference.

Reference: nn/quantized/ — `Quantizer` walks a trained module tree
replacing Linear / SpatialConvolution / SpatialDilatedConvolution with
quantized versions (nn/quantized/Quantizer.scala:27-32); weights live in
int8 `QuantizedTensor`s with per-output-channel scales; the native
BigQuant `MixPrecisionGEMM` multiplies int8 weights against per-minibatch
quantized activations (survey §2.9 BigQuant row).

TPU-native redesign: symmetric per-output-channel int8 weights + dynamic
per-tensor activation quantization; the int8 x int8 -> int32 matmul/conv
is a single `lax.dot_general` / `conv_general_dilated` with
`preferred_element_type=int32`, which XLA lowers onto the MXU's native
int8 path; dequantization fuses into the epilogue.  The functional pass
`quantize(module, params) -> (q_module, q_params)` replaces the in-place
tree mutation.

Performance note (measured, v5e, ResNet-50 batch 256 inference): int8 runs
at ~0.9x of bf16 — the model is HBM-bandwidth-bound, so halved weight
traffic doesn't pay for the extra per-layer dynamic-activation
quantization passes; int8's 2x MXU peak only wins on compute-bound
(large-matmul) workloads.  The reference's premise differs on CPU, where
BigQuant's int8 GEMM is the fast path.  This port is therefore capability
parity (memory-footprint halving for weights) first, speedup second.


Measured on v5e (ResNet-50, batch 64, jit): int8 inference 20.4 ms vs
fp32 18.8 ms — int8 weights DO hit the int8->int32 MXU path, but the
per-tensor dynamic activation quantization (abs-max reduce + round each
layer) costs more than the matmul saves at these HBM-bound shapes.  The
capability matches the reference (whose BigQuant int8 targets memory
footprint and AVX-512 VNNI throughput on CPUs); on TPU the win is the 4x
weight-memory reduction, not latency.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.conv import SpatialConvolution, _DIMSPEC_2D, _pad2d
from bigdl_tpu.nn.graph import Graph
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Container, Module, Node


def quantize_weight(w: jnp.ndarray, channel_axis: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-channel int8: returns (int8 weights, fp32 scale) with
    w ~= w_q * scale (scale broadcast over channel_axis)."""
    reduce_axes = tuple(i for i in range(w.ndim) if i != channel_axis)
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    w_q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return w_q, scale.astype(jnp.float32)


def quantize_activation(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dynamic symmetric per-tensor int8 activations (the analogue of
    BigQuant's per-minibatch activation quantization)."""
    absmax = jnp.max(jnp.abs(x))
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    x_q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return x_q, scale.astype(jnp.float32)


class QuantizedLinear(Module):
    """Int8 Linear. reference: nn/quantized/Linear.scala."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias

    @staticmethod
    def from_float(layer: Linear, params: Any) -> Tuple["QuantizedLinear", Any]:
        q = QuantizedLinear(layer.input_size, layer.output_size, layer.with_bias)
        w_q, scale = quantize_weight(jnp.asarray(params["weight"]), channel_axis=1)
        q_params = {"weight_q": w_q, "scale": scale[0]}  # (out,) after squeeze
        if layer.with_bias:
            q_params["bias"] = jnp.asarray(params["bias"])
        return q, q_params

    def build(self, rng, input_shape):
        float_layer = Linear(self.input_size, self.output_size, self.with_bias)
        params, _, out = float_layer.build(rng, input_shape)
        _, q_params = QuantizedLinear.from_float(float_layer, params)
        return q_params, {}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        x_q, x_scale = quantize_activation(x)
        acc = lax.dot_general(x_q, params["weight_q"],
                              (((x.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (x_scale * params["scale"])
        if self.with_bias:
            y = y + params["bias"]
        return y.astype(x.dtype), state

    def output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_size,)


class QuantizedSpatialConvolution(Module):
    """Int8 conv. reference: nn/quantized/SpatialConvolution.scala."""

    def __init__(self, conv_cfg: dict, name: Optional[str] = None):
        super().__init__(name)
        self.cfg = dict(conv_cfg)

    @staticmethod
    def from_float(layer: SpatialConvolution, params: Any
                   ) -> Tuple["QuantizedSpatialConvolution", Any]:
        cfg = dict(n_input=layer.n_input, n_output=layer.n_output,
                   kernel=layer.kernel, stride=layer.stride, pad=layer.pad,
                   n_group=layer.n_group, with_bias=layer.with_bias,
                   dilation=layer.dilation)
        q = QuantizedSpatialConvolution(cfg)
        # kernel layout HWIO: output channel axis = 3
        w_q, scale = quantize_weight(jnp.asarray(params["weight"]), channel_axis=3)
        q_params = {"weight_q": w_q, "scale": scale.reshape(-1)}
        if layer.with_bias:
            q_params["bias"] = jnp.asarray(params["bias"])
        return q, q_params

    def _float_layer(self) -> SpatialConvolution:
        c = self.cfg
        ref = SpatialConvolution(
            c["n_input"], c["n_output"], c["kernel"][1], c["kernel"][0],
            c["stride"][1], c["stride"][0], c["pad"][1], c["pad"][0],
            c["n_group"], c["with_bias"])
        ref.dilation = tuple(c["dilation"])
        return ref

    def build(self, rng, input_shape):
        float_layer = self._float_layer()
        params, _, out = float_layer.build(rng, input_shape)
        _, q_params = QuantizedSpatialConvolution.from_float(float_layer, params)
        return q_params, {}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        c = self.cfg
        x_q, x_scale = quantize_activation(x)
        acc = lax.conv_general_dilated(
            x_q, params["weight_q"], window_strides=tuple(c["stride"]),
            padding=_pad2d(*c["pad"], in_hw=x.shape[1:3], kernel=tuple(c["kernel"]),
                           stride=tuple(c["stride"]), dilation=tuple(c["dilation"])),
            rhs_dilation=tuple(c["dilation"]), dimension_numbers=_DIMSPEC_2D,
            feature_group_count=c["n_group"],
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (x_scale * params["scale"])
        if c["with_bias"]:
            y = y + params["bias"]
        return y.astype(x.dtype), state

    def output_shape(self, input_shape):
        return self._float_layer().output_shape(input_shape)


def quantize(module: Module, params: Any) -> Tuple[Module, Any]:
    """Walk the module tree, swapping Linear/SpatialConvolution (incl.
    dilated) for int8 versions with converted params.  The functional
    analogue of `module.quantize()` (nn/abstractnn/AbstractModule.scala:918
    -> nn/quantized/Quantizer.scala)."""
    from bigdl_tpu.nn.linear import SparseLinear

    if isinstance(module, Linear) and not isinstance(module, SparseLinear):
        return QuantizedLinear.from_float(module, params)
    if isinstance(module, SpatialConvolution):  # incl. SpatialDilatedConvolution
        return QuantizedSpatialConvolution.from_float(module, params)
    if isinstance(module, Graph):
        return _quantize_graph(module, params)
    if isinstance(module, Container) and not getattr(
            module, "_constructor_children", False):
        new = type(module).__new__(type(module))
        new.__dict__.update(module.__dict__)
        from collections import OrderedDict

        new.children = OrderedDict()
        q_params = dict(params) if isinstance(params, dict) else params
        for key, child in module.children.items():
            qc, qp = quantize(child, params[key])
            new.children[key] = qc
            q_params[key] = qp
        return new, q_params
    return module, params


def _quantize_graph(g: Graph, params: Any) -> Tuple[Graph, Any]:
    # rebuild nodes with quantized modules, preserving topology
    mapping: dict = {}
    q_params = dict(params)

    def conv_node(node: Node) -> Node:
        if id(node) in mapping:
            return mapping[id(node)]
        prevs = [conv_node(p) for p in node.prevs]
        if node.module is None:
            new = Node(None, prevs)
            new.name = node.name
        else:
            qm, qp = quantize(node.module, params.get(node.name, {}))
            q_params[node.name] = qp
            new = Node(qm, prevs)
            new.name = node.name
            qm.name = node.module.name
        mapping[id(node)] = new
        return new

    new_inputs = [conv_node(n) for n in g.input_nodes]
    new_outputs = [conv_node(n) for n in g.output_nodes]
    ng = Graph(new_inputs, new_outputs)
    ng.name = g.name
    return ng, q_params
