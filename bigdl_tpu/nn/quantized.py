"""Int8 quantized inference.

Reference: nn/quantized/ — `Quantizer` walks a trained module tree
replacing Linear / SpatialConvolution / SpatialDilatedConvolution with
quantized versions (nn/quantized/Quantizer.scala:27-32); weights live in
int8 `QuantizedTensor`s with per-output-channel scales; the native
BigQuant `MixPrecisionGEMM` multiplies int8 weights against per-minibatch
quantized activations (survey §2.9 BigQuant row).

TPU-native redesign: symmetric per-output-channel int8 weights + dynamic
per-tensor activation quantization; the int8 x int8 -> int32 matmul/conv
is a single `lax.dot_general` / `conv_general_dilated` with
`preferred_element_type=int32`, which XLA lowers onto the MXU's native
int8 path; dequantization fuses into the epilogue.  The functional pass
`quantize(module, params) -> (q_module, q_params)` replaces the in-place
tree mutation.

Performance (measured on v5e, benchmarks/bench_int8.py):

  * ResNet-50 batch-256 inference: bf16 24.9 ms; int8 DYNAMIC 30.8 ms
    (0.81x — the per-layer activation abs-max reduce costs more than the
    int8 matmul saves); int8 STATIC (calibrated scales, no runtime
    reduce) **19.8 ms = 1.26x faster than bf16** — the int8 MXU path
    finally pays, matching the reference's premise that quantization is
    the fast path (nn/quantized/Quantizer.scala:27-32); weight-only
    33.3 ms (0.75x — conv is MXU-bound, dequant-at-operand doesn't help).
  * TransformerLM single-token decode (batch 8, 1024x12): bf16 3.47 ms;
    WEIGHT-ONLY int8 3.00 ms = 1.16x — bandwidth-bound, halved weight
    traffic wins; activations stay bf16.

Rule of thumb: static for conv/vision inference, weight_only for
bandwidth-bound decode, dynamic only when no calibration data exists.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.conv import SpatialConvolution, _DIMSPEC_2D, _pad2d
from bigdl_tpu.nn.graph import Graph
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Container, Module, Node


def quantize_weight(w: jnp.ndarray, channel_axis: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-channel int8: returns (int8 weights, fp32 scale) with
    w ~= w_q * scale (scale broadcast over channel_axis)."""
    reduce_axes = tuple(i for i in range(w.ndim) if i != channel_axis)
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    w_q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return w_q, scale.astype(jnp.float32)


def quantize_activation(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dynamic symmetric per-tensor int8 activations (the analogue of
    BigQuant's per-minibatch activation quantization)."""
    absmax = jnp.max(jnp.abs(x))
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    x_q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return x_q, scale.astype(jnp.float32)


class _QuantizedBase(Module):
    """Shared activation-handling for int8 layers.

    Three modes (reference premise: nn/quantized/Quantizer.scala int8 is
    the FAST path; on TPU each mode targets a different bottleneck):

      * ``dynamic``   — per-batch abs-max activation scale (BigQuant's
        per-minibatch quantization).  Extra reduce per layer; loses on
        HBM-bound models.
      * ``static``    — activation scale is a CALIBRATED constant
        (`calibrate()`), so quantization is a fused elementwise op and the
        int8 MXU path runs without any runtime reduce.
      * ``weight_only`` — activations stay bf16/fp32; int8 weights are
        dequantized at the matmul operand, halving weight HBM traffic vs
        bf16 — the win on bandwidth-bound inference (LM decode).
    """

    mode: str = "dynamic"

    def _record_calibration(self, x) -> None:
        if getattr(self, "_calibrating", False):
            m = float(jnp.max(jnp.abs(x)))
            self._calib_absmax = max(getattr(self, "_calib_absmax", 0.0), m)

    def _activation_scale(self, params, x):
        if self.mode == "static":
            return params["x_scale"]
        absmax = jnp.max(jnp.abs(x))
        return jnp.maximum(absmax, 1e-8) / 127.0


class QuantizedLinear(_QuantizedBase):
    """Int8 Linear. reference: nn/quantized/Linear.scala."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 mode: str = "dynamic", name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.mode = mode

    @staticmethod
    def from_float(layer: Linear, params: Any,
                   mode: str = "dynamic") -> Tuple["QuantizedLinear", Any]:
        q = QuantizedLinear(layer.input_size, layer.output_size, layer.with_bias,
                            mode=mode)
        w_q, scale = quantize_weight(jnp.asarray(params["weight"]), channel_axis=1)
        q_params = {"weight_q": w_q, "scale": scale[0]}  # (out,) after squeeze
        if layer.with_bias:
            q_params["bias"] = jnp.asarray(params["bias"])
        if mode == "static":
            q_params["x_scale"] = jnp.asarray(1.0, jnp.float32)
        return q, q_params

    def build(self, rng, input_shape):
        float_layer = Linear(self.input_size, self.output_size, self.with_bias)
        params, _, out = float_layer.build(rng, input_shape)
        _, q_params = QuantizedLinear.from_float(float_layer, params, self.mode)
        return q_params, {}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        self._record_calibration(x)
        if self.mode == "weight_only" or getattr(self, "_calibrating", False):
            w = params["weight_q"].astype(x.dtype) * params["scale"].astype(x.dtype)
            y = lax.dot_general(x, w, (((x.ndim - 1,), (0,)), ((), ())))
        else:
            x_scale = self._activation_scale(params, x)
            x_q = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
            acc = lax.dot_general(x_q, params["weight_q"],
                                  (((x.ndim - 1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * (x_scale * params["scale"])
        if self.with_bias:
            y = y + params["bias"]
        return y.astype(x.dtype), state

    def output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_size,)


class QuantizedSpatialConvolution(_QuantizedBase):
    """Int8 conv. reference: nn/quantized/SpatialConvolution.scala."""

    def __init__(self, conv_cfg: dict, mode: str = "dynamic",
                 name: Optional[str] = None):
        super().__init__(name)
        self.cfg = dict(conv_cfg)
        self.mode = mode

    @staticmethod
    def from_float(layer: SpatialConvolution, params: Any, mode: str = "dynamic"
                   ) -> Tuple["QuantizedSpatialConvolution", Any]:
        cfg = dict(n_input=layer.n_input, n_output=layer.n_output,
                   kernel=layer.kernel, stride=layer.stride, pad=layer.pad,
                   n_group=layer.n_group, with_bias=layer.with_bias,
                   dilation=layer.dilation)
        q = QuantizedSpatialConvolution(cfg, mode=mode)
        # kernel layout HWIO: output channel axis = 3
        w_q, scale = quantize_weight(jnp.asarray(params["weight"]), channel_axis=3)
        q_params = {"weight_q": w_q, "scale": scale.reshape(-1)}
        if layer.with_bias:
            q_params["bias"] = jnp.asarray(params["bias"])
        if mode == "static":
            q_params["x_scale"] = jnp.asarray(1.0, jnp.float32)
        return q, q_params

    def _float_layer(self) -> SpatialConvolution:
        c = self.cfg
        ref = SpatialConvolution(
            c["n_input"], c["n_output"], c["kernel"][1], c["kernel"][0],
            c["stride"][1], c["stride"][0], c["pad"][1], c["pad"][0],
            c["n_group"], c["with_bias"])
        ref.dilation = tuple(c["dilation"])
        return ref

    def build(self, rng, input_shape):
        float_layer = self._float_layer()
        params, _, out = float_layer.build(rng, input_shape)
        _, q_params = QuantizedSpatialConvolution.from_float(
            float_layer, params, self.mode)
        return q_params, {}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        c = self.cfg
        self._record_calibration(x)
        conv_kw = dict(
            window_strides=tuple(c["stride"]),
            padding=_pad2d(*c["pad"], in_hw=x.shape[1:3], kernel=tuple(c["kernel"]),
                           stride=tuple(c["stride"]), dilation=tuple(c["dilation"])),
            rhs_dilation=tuple(c["dilation"]), dimension_numbers=_DIMSPEC_2D,
            feature_group_count=c["n_group"])
        if self.mode == "weight_only" or getattr(self, "_calibrating", False):
            w = params["weight_q"].astype(x.dtype) * params["scale"].astype(x.dtype)
            y = lax.conv_general_dilated(x, w, **conv_kw)
        else:
            x_scale = self._activation_scale(params, x)
            x_q = jnp.clip(jnp.round(x / x_scale), -127, 127).astype(jnp.int8)
            acc = lax.conv_general_dilated(
                x_q, params["weight_q"], preferred_element_type=jnp.int32,
                **conv_kw)
            y = acc.astype(jnp.float32) * (x_scale * params["scale"])
        if c["with_bias"]:
            y = y + params["bias"]
        return y.astype(x.dtype), state

    def output_shape(self, input_shape):
        return self._float_layer().output_shape(input_shape)


def quantize(module: Module, params: Any,
             mode: str = "dynamic", *, sample_input=None, state=None,
             calib_batches=None, bench_iters: int = 10) -> Tuple[Module, Any]:
    """Walk the module tree, swapping Linear/SpatialConvolution (incl.
    dilated) for int8 versions with converted params.  The functional
    analogue of `module.quantize()` (nn/abstractnn/AbstractModule.scala:918
    -> nn/quantized/Quantizer.scala).  `mode`: dynamic | static |
    weight_only (see _QuantizedBase) | auto; static needs a `calibrate()`
    pass before inference.

    `mode="auto"` microbenches float + all three int8 modes on the LIVE
    backend with `sample_input` and returns the fastest — the winning
    mode flips with the toolchain (round-2 static was 1.26x vs bf16;
    round-3 re-measure 0.96x, BENCH_APPENDIX.md), so no fixed choice is
    safe, and returning the FLOAT model when every int8 mode is a
    slowdown prevents quantize() shipping a regression silently.  NOTE:
    when `bf16` wins, the returned params are a bf16 CAST of the model
    (a dtype change, warned loudly), not int8.  The decision table lands
    on the returned module (a copy, never the caller's object) as
    `_quant_auto_report`."""
    if mode == "auto":
        return _quantize_auto(module, params, sample_input, state,
                              calib_batches, bench_iters)
    if mode not in ("dynamic", "static", "weight_only"):
        raise ValueError(f"unknown quantization mode {mode!r}")
    from bigdl_tpu.nn.linear import SparseLinear

    if isinstance(module, Linear) and not isinstance(module, SparseLinear):
        return QuantizedLinear.from_float(module, params, mode)
    if isinstance(module, SpatialConvolution):  # incl. SpatialDilatedConvolution
        return QuantizedSpatialConvolution.from_float(module, params, mode)
    if isinstance(module, Graph):
        return _quantize_graph(module, params, mode)
    if isinstance(module, Container) and not getattr(
            module, "_constructor_children", False):
        new = type(module).__new__(type(module))
        new.__dict__.update(module.__dict__)
        from collections import OrderedDict

        new.children = OrderedDict()
        q_params = dict(params) if isinstance(params, dict) else params
        for key, child in module.children.items():
            qc, qp = quantize(child, params[key], mode)
            new.children[key] = qc
            q_params[key] = qp
        return new, q_params
    return module, params


def _quantize_graph(g: Graph, params: Any, mode: str) -> Tuple[Graph, Any]:
    # rebuild nodes with quantized modules, preserving topology
    mapping: dict = {}
    q_params = dict(params)

    def conv_node(node: Node) -> Node:
        if id(node) in mapping:
            return mapping[id(node)]
        prevs = [conv_node(p) for p in node.prevs]
        if node.module is None:
            new = Node(None, prevs)
            new.name = node.name
        else:
            qm, qp = quantize(node.module, params.get(node.name, {}), mode)
            q_params[node.name] = qp
            new = Node(qm, prevs)
            new.name = node.name
            qm.name = node.module.name
        mapping[id(node)] = new
        return new

    new_inputs = [conv_node(n) for n in g.input_nodes]
    new_outputs = [conv_node(n) for n in g.output_nodes]
    ng = Graph(new_inputs, new_outputs)
    ng.name = g.name
    return ng, q_params


def _quantize_auto(module: Module, params: Any, sample_input, state,
                   calib_batches, iters: int) -> Tuple[Module, Any]:
    """Pick the fastest of {float, dynamic, static, weight_only} by
    measurement (reference premise: nn/quantized/Quantizer.scala treats
    int8 as THE fast path — on TPU which mode is fastest depends on the
    compiler/libtpu version, so measure, don't assume)."""
    import logging
    import time

    import jax

    if sample_input is None:
        raise ValueError(
            "quantize(mode='auto') needs sample_input= (a representative "
            "batch) to microbench the modes on the live toolchain")
    log = logging.getLogger("bigdl_tpu.quantized")
    state = {} if state is None else state
    x = jnp.asarray(sample_input)
    x16 = x.astype(jnp.bfloat16) if jnp.issubdtype(x.dtype, jnp.floating) \
        else x
    batches = calib_batches if calib_batches is not None else [x]

    # the float baseline runs TWICE: native dtype AND bf16 (the usual TPU
    # serving dtype) — comparing int8 only against f32 would let an int8
    # mode "win" while still being a regression vs bf16 serving
    p16 = jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
        else a, params)
    def _has_quantized(mod) -> bool:
        """True when the walker actually swapped some layer for an int8
        one — object identity is NOT enough (Containers/Graphs rebuild
        a fresh wrapper even when no child quantized)."""
        if isinstance(mod, _QuantizedBase):
            return True
        for child in getattr(mod, "children", {}).values():
            if _has_quantized(child):
                return True
        if isinstance(mod, Graph):
            seen, stack = set(), list(mod.output_nodes)
            while stack:
                nd = stack.pop()
                if id(nd) in seen:
                    continue
                seen.add(id(nd))
                if nd.module is not None and _has_quantized(nd.module):
                    return True
                stack.extend(nd.prevs)
        return False

    candidates = [("float", module, params, x), ("bf16", module, p16, x16)]
    walkable = False
    for m in ("dynamic", "static", "weight_only"):
        qm, qp = quantize(module, params, m)
        if not _has_quantized(qm):
            continue  # walker found nothing quantizable: identity, skip
        walkable = True
        if m == "static":
            qp = calibrate(qm, qp, state, batches)
        # int8 layers return y.astype(x.dtype): benching them on the raw
        # fp32 sample runs the whole net's ACTIVATIONS fp32 and
        # systematically penalizes int8 vs the bf16 serving reality
        # (r5 capture: auto's static read 28.4 ms where the bf16-input
        # table row measured 20.0 ms — a mispick, not noise)
        candidates.append((m, qm, qp, x16))
    if not walkable:
        # custom Modules the tree walker cannot descend (TransformerLM,
        # scan-stacked blocks): the leaf-wise weight-only wrapper is the
        # int8 path — decode-class workloads are weight-bandwidth-bound,
        # exactly where it can pay
        qm, qp = WeightOnlyInt8.from_float(module, params,
                                           compute_dtype=jnp.bfloat16)
        candidates.append(("weight_only_wrap", qm, qp, x16))

    def time_mode(mod, p, xi):
        fwd = jax.jit(lambda p_, x_: mod.apply(p_, state, x_,
                                               training=False)[0])
        out = fwd(p, xi)
        # sync through a dependent readback (block_until_ready does not
        # truly block through the axon tunnel)
        float(jnp.sum(out.astype(jnp.float32)))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fwd(p, xi)
        float(jnp.sum(out.astype(jnp.float32)))
        return (time.perf_counter() - t0) / iters

    report = []
    best = None
    for name, mod, p, xi in candidates:
        dt = time_mode(mod, p, xi)
        report.append((name, dt * 1e3))
        if best is None or dt < best[0]:
            best = (dt, name, mod, p)
    _, name, mod, p = best
    log.info("quantize(auto): %s -> picked %r",
             ", ".join(f"{n}={ms:.2f}ms" for n, ms in report), name)
    if name == "bf16":
        # loud, not silent: a function named quantize() is returning a
        # dtype-cast rather than an int8 model because that measured faster
        log.warning("quantize(auto): every int8 mode measured slower than "
                    "bf16; returning BF16-CAST params (not int8)")
    if mod is module:
        # float/bf16 winner is the caller's original module object —
        # annotate a shallow copy so the report never mutates their model
        import copy

        mod = copy.copy(mod)
    mod._quant_auto_report = {"picked": name,
                              "ms_per_batch": dict(report)}
    return mod, p


def calibrate(q_module: Module, q_params: Any, state: Any, batches,
              percentile_headroom: float = 1.0) -> Any:
    """Fill static activation scales by observing real data.

    Reference analogue: BigQuant loads activation thresholds computed from
    calibration data into the native kernel descriptors
    (nn/quantized/Desc.scala); here the scales are plain fp32 leaves in the
    quantized params.

    Runs the quantized model EAGERLY (no jit) over `batches` (iterable of
    input arrays or MiniBatches) with every quantized layer in a recording
    mode that (a) computes this layer's input abs-max and (b) forwards in
    float so downstream layers see accurate activations.  Returns q_params
    with each static layer's `x_scale` = absmax * headroom / 127.
    """
    qmods = [m for m in _walk(q_module) if isinstance(m, _QuantizedBase)]
    for m in qmods:
        m._calibrating = True
        m._calib_absmax = 0.0
    try:
        for batch in batches:
            x = batch.get_input() if hasattr(batch, "get_input") else batch
            q_module.apply(q_params, state, jnp.asarray(x), training=False)
    finally:
        for m in qmods:
            m._calibrating = False

    # write scales back by walking module tree and params together
    # (Graph is a Container whose children are keyed by node name, so one
    # Container branch covers both)
    def fill(module, params):
        if isinstance(module, _QuantizedBase):
            if module.mode == "static":
                absmax = max(getattr(module, "_calib_absmax", 0.0), 1e-8)
                return dict(params, x_scale=jnp.asarray(
                    absmax * percentile_headroom / 127.0, jnp.float32))
            return params
        if isinstance(module, Container) and isinstance(params, dict):
            out = dict(params)
            for key, child in module.children.items():
                if key in out:
                    out[key] = fill(child, out[key])
            return out
        return params

    return fill(q_module, q_params)


def _walk(module: Module):
    # one canonical tree walker (Module.flattened_modules)
    yield from module.flattened_modules()


class WeightOnlyInt8(Module):
    """Weight-only int8 wrapper for ANY module (TransformerLM, Graph, ...).

    Every float parameter leaf with ndim >= 2 is stored int8 with a
    per-output-channel scale (reduced over axis -2, so scan-stacked block
    params keep per-layer scales); `apply` dequantizes leaf-wise to the
    activation dtype and delegates to the wrapped module.  XLA fuses the
    convert+scale into each consumer's operand read, so weights stream
    from HBM at half bf16 width — the win on bandwidth-bound inference
    (LM decode), where the reference's BigQuant premise (int8 as the fast
    path, nn/quantized/Quantizer.scala:27-32) holds on TPU too.
    """

    def __init__(self, inner: Module, name: Optional[str] = None,
                 min_size: int = 1 << 12, compute_dtype=None):
        super().__init__(name)
        self.inner = inner
        self.min_size = min_size  # skip tiny leaves (norm gains etc.)
        self.compute_dtype = compute_dtype  # None: follow the input's dtype

    @staticmethod
    def from_float(inner: Module, params: Any, min_size: int = 1 << 12,
                   compute_dtype=None) -> Tuple["WeightOnlyInt8", Any]:
        wrapper = WeightOnlyInt8(inner, min_size=min_size,
                                 compute_dtype=compute_dtype)

        def conv(leaf):
            leaf = jnp.asarray(leaf)
            if (leaf.ndim < 2 or leaf.size < min_size
                    or not jnp.issubdtype(leaf.dtype, jnp.floating)):
                return leaf
            absmax = jnp.max(jnp.abs(leaf), axis=-2, keepdims=True)
            scale = jnp.maximum(absmax, 1e-8) / 127.0
            q = jnp.clip(jnp.round(leaf / scale), -127, 127).astype(jnp.int8)
            return {"__wq__": q, "__ws__": scale.astype(jnp.float32)}

        is_leaf = lambda v: not isinstance(v, dict)
        q_params = jax.tree_util.tree_map(conv, params, is_leaf=is_leaf)
        return wrapper, q_params

    def _dequantize(self, params, dtype):
        def deq(v):
            if isinstance(v, dict) and "__wq__" in v:
                return v["__wq__"].astype(dtype) * v["__ws__"].astype(dtype)
            return v

        return jax.tree_util.tree_map(
            deq, params,
            is_leaf=lambda v: isinstance(v, dict) and "__wq__" in v)

    def build(self, rng, input_shape):
        params, state, out = self.inner.build(rng, input_shape)
        _, q_params = WeightOnlyInt8.from_float(self.inner, params,
                                                self.min_size)
        return q_params, state, out

    def apply(self, params, state, x, *, training=False, rng=None):
        if self.compute_dtype is not None:
            dtype = self.compute_dtype
        elif jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            dtype = x.dtype
        else:
            dtype = jnp.float32
        return self.inner.apply(self._dequantize(params, dtype), state, x,
                                training=training, rng=rng)

    # -- KV-cache generation protocol (bigdl_tpu.generation) --------------
    # int8 weight-only IS the decode-class quantization (bandwidth-bound,
    # halved weight traffic), so the wrapper forwards the cache-aware
    # protocol and quantize(mode='auto') models drop into GenerationEngine
    # unchanged.  The SAME delegation seam carries int8 KV-cache
    # quantization: `dtype=jnp.int8` (or BIGDL_TPU_KV_DTYPE=int8 through
    # GenerationConfig) flows to the inner model's init_cache, which
    # allocates the quantized ring/pool with fp32 scale planes — weights
    # and KV quantize independently and compose.

    def init_cache(self, slots: int, capacity: int, dtype=None):
        return self.inner.init_cache(
            slots, capacity, dtype if dtype is not None
            else (self.compute_dtype or jnp.float32))

    def apply_cached(self, params, tokens, cache, *, wrapped_append=False):
        dtype = self.compute_dtype if self.compute_dtype is not None \
            else jnp.float32
        return self.inner.apply_cached(self._dequantize(params, dtype),
                                       tokens, cache,
                                       wrapped_append=wrapped_append)

    def output_shape(self, input_shape):
        return self.inner.output_shape(input_shape)
