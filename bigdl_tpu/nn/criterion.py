"""Loss functions (criterions).

Reference: nn/abstractnn/AbstractCriterion.scala plus the ~40-criterion zoo
(nn/ClassNLLCriterion.scala, nn/CrossEntropyCriterion.scala,
nn/MSECriterion.scala, nn/BCECriterion.scala, nn/SmoothL1Criterion.scala,
nn/MarginCriterion.scala, nn/KLDCriterion.scala,
nn/DiceCoefficientCriterion.scala, nn/TimeDistributedCriterion.scala,
nn/MultiCriterion.scala, nn/ParallelCriterion.scala, ...).

Redesign: a Criterion is a pure function `forward(input, target) -> scalar`;
there is no `backward`/`updateGradInput` because jax.grad differentiates the
loss.  Class targets are 0-based int arrays (the reference is 1-based).
All criterions honour `size_average` like the reference.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import capture_init


class Criterion:
    """Base. reference: nn/abstractnn/AbstractCriterion.scala."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        capture_init(cls)

    def forward(self, input, target):
        raise NotImplementedError

    def __call__(self, input, target):
        return self.forward(input, target)


def _reduce(x, size_average: bool):
    return jnp.mean(x) if size_average else jnp.sum(x)


class ClassNLLCriterion(Criterion):
    """NLL over log-probabilities (pair with LogSoftMax).
    reference: nn/ClassNLLCriterion.scala (weights + sizeAverage supported;
    logProbAsInput=True matches the reference default)."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True,
                 log_prob_as_input: bool = True):
        self.weights = weights
        self.size_average = size_average
        self.log_prob = log_prob_as_input

    def forward(self, input, target):
        logp = input if self.log_prob else jnp.log(jnp.maximum(input, 1e-8))
        t = target.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, t[:, None], axis=-1)[:, 0]
        if self.weights is not None:
            w = jnp.take(self.weights, t)
            total = -jnp.sum(w * picked)
            return total / jnp.sum(w) if self.size_average else total
        return _reduce(-picked, self.size_average)


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused. reference: nn/CrossEntropyCriterion.scala."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.inner = ClassNLLCriterion(weights, size_average)

    def forward(self, input, target):
        return self.inner.forward(jax.nn.log_softmax(input, axis=-1), target)


class MSECriterion(Criterion):
    """reference: nn/MSECriterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce(jnp.square(input - target), self.size_average)


class AbsCriterion(Criterion):
    """L1. reference: nn/AbsCriterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce(jnp.abs(input - target), self.size_average)


class BCECriterion(Criterion):
    """Binary cross-entropy over probabilities. reference: nn/BCECriterion.scala."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average

    def forward(self, input, target):
        eps = 1e-12
        p = jnp.clip(input, eps, 1.0 - eps)
        loss = -(target * jnp.log(p) + (1.0 - target) * jnp.log(1.0 - p))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss, self.size_average)


class BCEWithLogitsCriterion(Criterion):
    """Numerically-stable sigmoid+BCE (log-sum-exp trick)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        loss = jnp.maximum(input, 0) - input * target + jnp.log1p(jnp.exp(-jnp.abs(input)))
        return _reduce(loss, self.size_average)


class SmoothL1Criterion(Criterion):
    """Huber with delta=1. reference: nn/SmoothL1Criterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        d = jnp.abs(input - target)
        loss = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(loss, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    """reference: nn/MultiLabelSoftMarginCriterion.scala."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average

    def forward(self, input, target):
        loss = -(target * jax.nn.log_sigmoid(input) + (1 - target) * jax.nn.log_sigmoid(-input))
        if self.weights is not None:
            loss = loss * self.weights
        per_sample = jnp.mean(loss, axis=-1)
        return _reduce(per_sample, self.size_average)


class MarginCriterion(Criterion):
    """Hinge; target in {-1, 1}. reference: nn/MarginCriterion.scala."""

    def __init__(self, margin: float = 1.0, size_average: bool = True,
                 squared: bool = False):
        self.margin = margin
        self.size_average = size_average
        self.squared = squared

    def forward(self, input, target):
        loss = jnp.maximum(0.0, self.margin - input * target)
        if self.squared:
            loss = jnp.square(loss)
        return _reduce(loss, self.size_average)


class HingeEmbeddingCriterion(Criterion):
    """reference: nn/HingeEmbeddingCriterion.scala."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def forward(self, input, target):
        loss = jnp.where(target > 0, input, jnp.maximum(0.0, self.margin - input))
        return _reduce(loss, self.size_average)


class CosineEmbeddingCriterion(Criterion):
    """Input is Table(x1, x2); target +-1.
    reference: nn/CosineEmbeddingCriterion.scala."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def forward(self, input, target):
        x1, x2 = input[1], input[2]
        cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        loss = jnp.where(target > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _reduce(loss, self.size_average)


class KLDCriterion(Criterion):
    """VAE KL(N(mu, sigma) || N(0, 1)); input is Table(mean, log_var).
    reference: nn/KLDCriterion.scala (GaussianSampler counterpart)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target=None):
        mean, log_var = input[1], input[2]
        kld = -0.5 * jnp.sum(1 + log_var - jnp.square(mean) - jnp.exp(log_var), axis=-1)
        return _reduce(kld, self.size_average)


class DistKLDivCriterion(Criterion):
    """KL divergence given log-probs input. reference: nn/DistKLDivCriterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        loss = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-12)) - input), 0.0)
        if self.size_average:
            return jnp.sum(loss) / input.shape[0]
        return jnp.sum(loss)


class DiceCoefficientCriterion(Criterion):
    """1 - dice overlap. reference: nn/DiceCoefficientCriterion.scala."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        self.size_average = size_average
        self.epsilon = epsilon

    def forward(self, input, target):
        axes = tuple(range(1, input.ndim))
        inter = jnp.sum(input * target, axis=axes)
        denom = jnp.sum(input, axis=axes) + jnp.sum(target, axis=axes)
        dice = 1.0 - 2.0 * (inter + self.epsilon) / (denom + 2 * self.epsilon)
        return _reduce(dice, self.size_average)


class L1Cost(Criterion):
    """Sum of |input|. reference: nn/L1Cost.scala."""

    def forward(self, input, target=None):
        return jnp.sum(jnp.abs(input))


class ClassSimplexCriterion(Criterion):
    """MSE against simplex-embedded class targets.
    reference: nn/ClassSimplexCriterion.scala."""

    def __init__(self, n_classes: int):
        self.n_classes = n_classes
        self.mse = MSECriterion()

    def forward(self, input, target):
        onehot = jax.nn.one_hot(target.astype(jnp.int32), self.n_classes, dtype=input.dtype)
        return self.mse.forward(input, onehot)


class SoftmaxWithCriterion(Criterion):
    """Caffe-style fused softmax loss. reference: nn/SoftmaxWithCriterion.scala."""

    def __init__(self, ignore_label: Optional[int] = None):
        self.ignore_label = ignore_label

    def forward(self, input, target):
        logp = jax.nn.log_softmax(input, axis=-1)
        t = target.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]
        if self.ignore_label is not None:
            mask = (t != self.ignore_label).astype(input.dtype)
            return -jnp.sum(picked * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return -jnp.mean(picked)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target).
    reference: nn/MultiCriterion.scala."""

    def __init__(self):
        self.criteria = []
        self.weights = []

    def add(self, criterion: Criterion, weight: float = 1.0) -> "MultiCriterion":
        self.criteria.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, input, target):
        return sum(w * c.forward(input, target)
                   for c, w in zip(self.criteria, self.weights))


class ParallelCriterion(Criterion):
    """i-th criterion on i-th (input, target) table entries.
    reference: nn/ParallelCriterion.scala."""

    def __init__(self, repeat_target: bool = False):
        self.criteria = []
        self.weights = []
        self.repeat_target = repeat_target

    def add(self, criterion: Criterion, weight: float = 1.0) -> "ParallelCriterion":
        self.criteria.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, input, target):
        ins = list(input)
        tgts = [target] * len(ins) if self.repeat_target else list(target)
        return sum(w * c.forward(i, t)
                   for c, w, i, t in zip(self.criteria, self.weights, ins, tgts))


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at each timestep of (B, T, ...) input and average.
    reference: nn/TimeDistributedCriterion.scala."""

    def __init__(self, criterion: Criterion, size_average: bool = False):
        self.criterion = criterion
        self.size_average = size_average

    def forward(self, input, target):
        n, t = input.shape[0], input.shape[1]
        flat_in = jnp.reshape(input, (n * t,) + input.shape[2:])
        flat_t = jnp.reshape(target, (n * t,) + target.shape[2:])
        total = self.criterion.forward(flat_in, flat_t)
        # reference semantics: sum over timesteps of the per-timestep loss,
        # divided by T iff sizeAverage is set at THIS level.  Whether the
        # flat total needs rescaling depends on the inner reduction:
        # mean-reducing inner -> flat mean * T == sum_t(mean_n); sum-reducing
        # inner -> flat sum already == sum_t(sum_n).
        inner_avg = getattr(self.criterion, "size_average", True)
        sum_over_t = total * t if inner_avg else total
        return sum_over_t / t if self.size_average else sum_over_t
