"""Loss functions (criterions).

Reference: nn/abstractnn/AbstractCriterion.scala plus the ~40-criterion zoo
(nn/ClassNLLCriterion.scala, nn/CrossEntropyCriterion.scala,
nn/MSECriterion.scala, nn/BCECriterion.scala, nn/SmoothL1Criterion.scala,
nn/MarginCriterion.scala, nn/KLDCriterion.scala,
nn/DiceCoefficientCriterion.scala, nn/TimeDistributedCriterion.scala,
nn/MultiCriterion.scala, nn/ParallelCriterion.scala, ...).

Redesign: a Criterion is a pure function `forward(input, target) -> scalar`;
there is no `backward`/`updateGradInput` because jax.grad differentiates the
loss.  Class targets are 0-based int arrays (the reference is 1-based).
All criterions honour `size_average` like the reference.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import capture_init


class Criterion:
    """Base. reference: nn/abstractnn/AbstractCriterion.scala."""

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        capture_init(cls)

    def forward(self, input, target):
        raise NotImplementedError

    def __call__(self, input, target):
        return self.forward(input, target)


def _reduce(x, size_average: bool):
    return jnp.mean(x) if size_average else jnp.sum(x)


class ClassNLLCriterion(Criterion):
    """NLL over log-probabilities (pair with LogSoftMax).
    reference: nn/ClassNLLCriterion.scala (weights + sizeAverage supported;
    logProbAsInput=True matches the reference default)."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True,
                 log_prob_as_input: bool = True):
        self.weights = weights
        self.size_average = size_average
        self.log_prob = log_prob_as_input

    def forward(self, input, target):
        logp = input if self.log_prob else jnp.log(jnp.maximum(input, 1e-8))
        t = target.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, t[:, None], axis=-1)[:, 0]
        if self.weights is not None:
            w = jnp.take(self.weights, t)
            total = -jnp.sum(w * picked)
            return total / jnp.sum(w) if self.size_average else total
        return _reduce(-picked, self.size_average)


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused. reference: nn/CrossEntropyCriterion.scala."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.inner = ClassNLLCriterion(weights, size_average)

    def forward(self, input, target):
        return self.inner.forward(jax.nn.log_softmax(input, axis=-1), target)


class MSECriterion(Criterion):
    """reference: nn/MSECriterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce(jnp.square(input - target), self.size_average)


class AbsCriterion(Criterion):
    """L1. reference: nn/AbsCriterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        return _reduce(jnp.abs(input - target), self.size_average)


class BCECriterion(Criterion):
    """Binary cross-entropy over probabilities. reference: nn/BCECriterion.scala."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average

    def forward(self, input, target):
        eps = 1e-12
        p = jnp.clip(input, eps, 1.0 - eps)
        loss = -(target * jnp.log(p) + (1.0 - target) * jnp.log(1.0 - p))
        if self.weights is not None:
            loss = loss * self.weights
        return _reduce(loss, self.size_average)


class BCEWithLogitsCriterion(Criterion):
    """Numerically-stable sigmoid+BCE (log-sum-exp trick)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        loss = jnp.maximum(input, 0) - input * target + jnp.log1p(jnp.exp(-jnp.abs(input)))
        return _reduce(loss, self.size_average)


class SmoothL1Criterion(Criterion):
    """Huber with delta=1. reference: nn/SmoothL1Criterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        d = jnp.abs(input - target)
        loss = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
        return _reduce(loss, self.size_average)


class MultiLabelSoftMarginCriterion(Criterion):
    """reference: nn/MultiLabelSoftMarginCriterion.scala."""

    def __init__(self, weights: Optional[jnp.ndarray] = None, size_average: bool = True):
        self.weights = weights
        self.size_average = size_average

    def forward(self, input, target):
        loss = -(target * jax.nn.log_sigmoid(input) + (1 - target) * jax.nn.log_sigmoid(-input))
        if self.weights is not None:
            loss = loss * self.weights
        per_sample = jnp.mean(loss, axis=-1)
        return _reduce(per_sample, self.size_average)


class MarginCriterion(Criterion):
    """Hinge; target in {-1, 1}. reference: nn/MarginCriterion.scala."""

    def __init__(self, margin: float = 1.0, size_average: bool = True,
                 squared: bool = False):
        self.margin = margin
        self.size_average = size_average
        self.squared = squared

    def forward(self, input, target):
        loss = jnp.maximum(0.0, self.margin - input * target)
        if self.squared:
            loss = jnp.square(loss)
        return _reduce(loss, self.size_average)


class HingeEmbeddingCriterion(Criterion):
    """reference: nn/HingeEmbeddingCriterion.scala."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def forward(self, input, target):
        loss = jnp.where(target > 0, input, jnp.maximum(0.0, self.margin - input))
        return _reduce(loss, self.size_average)


class CosineEmbeddingCriterion(Criterion):
    """Input is Table(x1, x2); target +-1.
    reference: nn/CosineEmbeddingCriterion.scala."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def forward(self, input, target):
        x1, x2 = input[1], input[2]
        cos = jnp.sum(x1 * x2, -1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        loss = jnp.where(target > 0, 1.0 - cos, jnp.maximum(0.0, cos - self.margin))
        return _reduce(loss, self.size_average)


class KLDCriterion(Criterion):
    """VAE KL(N(mu, sigma) || N(0, 1)); input is Table(mean, log_var).
    reference: nn/KLDCriterion.scala (GaussianSampler counterpart)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target=None):
        mean, log_var = input[1], input[2]
        kld = -0.5 * jnp.sum(1 + log_var - jnp.square(mean) - jnp.exp(log_var), axis=-1)
        return _reduce(kld, self.size_average)


class DistKLDivCriterion(Criterion):
    """KL divergence given log-probs input. reference: nn/DistKLDivCriterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        loss = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-12)) - input), 0.0)
        if self.size_average:
            return jnp.sum(loss) / input.shape[0]
        return jnp.sum(loss)


class DiceCoefficientCriterion(Criterion):
    """1 - dice overlap. reference: nn/DiceCoefficientCriterion.scala."""

    def __init__(self, size_average: bool = True, epsilon: float = 1.0):
        self.size_average = size_average
        self.epsilon = epsilon

    def forward(self, input, target):
        axes = tuple(range(1, input.ndim))
        inter = jnp.sum(input * target, axis=axes)
        denom = jnp.sum(input, axis=axes) + jnp.sum(target, axis=axes)
        dice = 1.0 - 2.0 * (inter + self.epsilon) / (denom + 2 * self.epsilon)
        return _reduce(dice, self.size_average)


class L1Cost(Criterion):
    """Sum of |input|. reference: nn/L1Cost.scala."""

    def forward(self, input, target=None):
        return jnp.sum(jnp.abs(input))


class ClassSimplexCriterion(Criterion):
    """MSE against simplex-embedded class targets.
    reference: nn/ClassSimplexCriterion.scala."""

    def __init__(self, n_classes: int):
        self.n_classes = n_classes
        self.mse = MSECriterion()

    def forward(self, input, target):
        onehot = jax.nn.one_hot(target.astype(jnp.int32), self.n_classes, dtype=input.dtype)
        return self.mse.forward(input, onehot)


class SoftmaxWithCriterion(Criterion):
    """Caffe-style fused softmax loss. reference: nn/SoftmaxWithCriterion.scala."""

    def __init__(self, ignore_label: Optional[int] = None):
        self.ignore_label = ignore_label

    def forward(self, input, target):
        logp = jax.nn.log_softmax(input, axis=-1)
        t = target.astype(jnp.int32)
        picked = jnp.take_along_axis(logp, t[..., None], axis=-1)[..., 0]
        if self.ignore_label is not None:
            mask = (t != self.ignore_label).astype(input.dtype)
            return -jnp.sum(picked * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return -jnp.mean(picked)


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target).
    reference: nn/MultiCriterion.scala."""

    def __init__(self):
        self.criteria = []
        self.weights = []

    def add(self, criterion: Criterion, weight: float = 1.0) -> "MultiCriterion":
        self.criteria.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, input, target):
        return sum(w * c.forward(input, target)
                   for c, w in zip(self.criteria, self.weights))


class ParallelCriterion(Criterion):
    """i-th criterion on i-th (input, target) table entries.
    reference: nn/ParallelCriterion.scala."""

    def __init__(self, repeat_target: bool = False):
        self.criteria = []
        self.weights = []
        self.repeat_target = repeat_target

    def add(self, criterion: Criterion, weight: float = 1.0) -> "ParallelCriterion":
        self.criteria.append(criterion)
        self.weights.append(weight)
        return self

    def forward(self, input, target):
        ins = list(input)
        tgts = [target] * len(ins) if self.repeat_target else list(target)
        return sum(w * c.forward(i, t)
                   for c, w, i, t in zip(self.criteria, self.weights, ins, tgts))


class TimeDistributedCriterion(Criterion):
    """Apply a criterion at each timestep of (B, T, ...) input and average.
    reference: nn/TimeDistributedCriterion.scala."""

    def __init__(self, criterion: Criterion, size_average: bool = False):
        self.criterion = criterion
        self.size_average = size_average

    def forward(self, input, target):
        n, t = input.shape[0], input.shape[1]
        flat_in = jnp.reshape(input, (n * t,) + input.shape[2:])
        flat_t = jnp.reshape(target, (n * t,) + target.shape[2:])
        total = self.criterion.forward(flat_in, flat_t)
        # reference semantics: sum over timesteps of the per-timestep loss,
        # divided by T iff sizeAverage is set at THIS level.  Whether the
        # flat total needs rescaling depends on the inner reduction:
        # mean-reducing inner -> flat mean * T == sum_t(mean_n); sum-reducing
        # inner -> flat sum already == sum_t(sum_n).
        inner_avg = getattr(self.criterion, "size_average", True)
        sum_over_t = total * t if inner_avg else total
        return sum_over_t / t if self.size_average else sum_over_t


class MarginRankingCriterion(Criterion):
    """Table(x1, x2), y in {1,-1}: max(0, -y*(x1-x2) + margin).
    reference: nn/MarginRankingCriterion.scala."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        self.margin = margin
        self.size_average = size_average

    def forward(self, input, target):
        x1, x2 = input[1], input[2]
        y = target[1] if isinstance(target, Table) else target
        return _reduce(jnp.maximum(0.0, -y * (x1 - x2) + self.margin),
                       self.size_average)


class MultiMarginCriterion(Criterion):
    """Multiclass hinge: mean_j max(0, margin - x[t] + x[j])^p / dim.
    reference: nn/MultiMarginCriterion.scala (0-based classes here)."""

    def __init__(self, p: int = 1, weights=None, margin: float = 1.0,
                 size_average: bool = True):
        self.p, self.weights, self.margin = p, weights, margin
        self.size_average = size_average

    def forward(self, input, target):
        t = target.astype(jnp.int32)
        n, dim = input.shape
        xt = jnp.take_along_axis(input, t[:, None], axis=-1)
        h = jnp.maximum(0.0, self.margin - xt + input)
        if self.p == 2:
            h = h * h
        if self.weights is not None:
            h = h * jnp.take(self.weights, t)[:, None]
        # the j == t term contributes margin^p; mask it out
        mask = jax.nn.one_hot(t, dim, dtype=input.dtype)
        per_sample = jnp.sum(h * (1.0 - mask), axis=-1) / dim
        return _reduce(per_sample, self.size_average)


class MultiLabelMarginCriterion(Criterion):
    """Multilabel hinge; target rows hold 0-based class ids padded with -1.
    reference: nn/MultiLabelMarginCriterion.scala (1-based, 0-padded there)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        t = target.astype(jnp.int32)
        n, dim = input.shape
        valid = (t >= 0)
        safe_t = jnp.maximum(t, 0)
        one_hot = jax.nn.one_hot(safe_t, dim, dtype=jnp.bool_)  # (n, k, dim)
        is_target = jnp.any(one_hot & valid[:, :, None], axis=1)
        xt = jnp.take_along_axis(input, safe_t, axis=-1)        # (n, k)
        # hinge of every non-target j against every valid target slot
        h = jnp.maximum(0.0, 1.0 - xt[:, :, None] + input[:, None, :])
        keep = valid[:, :, None] & ~is_target[:, None, :]
        per_sample = jnp.sum(jnp.where(keep, h, 0.0), axis=(1, 2)) / dim
        return _reduce(per_sample, self.size_average)


class SoftMarginCriterion(Criterion):
    """log(1 + exp(-y*x)) with y in {1,-1}. reference: nn/SoftMarginCriterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        # softplus(-y*x) == log(1+exp(-y*x)) without overflow for large |x|
        return _reduce(jax.nn.softplus(-input * target), self.size_average)


class L1HingeEmbeddingCriterion(Criterion):
    """Table(x1, x2), y=1: ||x1-x2||_1; y=-1: max(0, margin - ||x1-x2||_1).
    reference: nn/L1HingeEmbeddingCriterion.scala."""

    def __init__(self, margin: float = 1.0):
        self.margin = margin

    def forward(self, input, target):
        d = jnp.sum(jnp.abs(input[1] - input[2]), axis=-1)
        y = target[1] if isinstance(target, Table) else target
        y = jnp.reshape(y, d.shape)
        loss = jnp.where(y > 0, d, jnp.maximum(0.0, self.margin - d))
        return jnp.sum(loss)


class CosineDistanceCriterion(Criterion):
    """1 - cos(input, target) per row. reference: nn/CosineDistanceCriterion.scala."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input, target):
        num = jnp.sum(input * target, axis=-1)
        den = jnp.linalg.norm(input, axis=-1) * jnp.linalg.norm(target, axis=-1)
        return _reduce(1.0 - num / jnp.maximum(den, 1e-12), self.size_average)


class CosineProximityCriterion(Criterion):
    """-sum(l2norm(input) . l2norm(target)) (Keras cosine_proximity).
    reference: nn/CosineProximityCriterion.scala."""

    def forward(self, input, target):
        a = input / jnp.maximum(jnp.linalg.norm(input, axis=-1, keepdims=True), 1e-12)
        b = target / jnp.maximum(jnp.linalg.norm(target, axis=-1, keepdims=True), 1e-12)
        return -jnp.mean(jnp.sum(a * b, axis=-1))


class DotProductCriterion(Criterion):
    """loss = dot(input, target) (positive; gradInput = target).
    reference: nn/DotProductCriterion.scala."""

    def __init__(self, size_average: bool = False):
        self.size_average = size_average

    def forward(self, input, target):
        dot = jnp.sum(input * target)
        if self.size_average and input.ndim == 2:
            dot = dot / input.shape[0]
        return dot


class PGCriterion(Criterion):
    """Negative policy gradient: -1/n sum(R . log P) over a batch of
    multinomial distributions; target holds reward at the sampled action
    index. reference: nn/PGCriterion.scala (built there as
    TransformerCriterion(DotProductCriterion, Log->MulConstant(-1)))."""

    def __init__(self, size_average: bool = False):
        self.size_average = size_average

    def forward(self, input, target):
        logp = -jnp.log(jnp.maximum(input, 1e-12))
        dot = jnp.sum(logp * target)
        if self.size_average and input.ndim == 2:
            dot = dot / input.shape[0]
        return dot


class GaussianCriterion(Criterion):
    """Negative log-likelihood of a diagonal Gaussian; input Table(mean,
    log_variance). reference: nn/GaussianCriterion.scala (VAE decoder loss)."""

    def forward(self, input, target):
        mean, log_var = input[1], input[2]
        return jnp.sum(0.5 * jnp.log(2.0 * jnp.pi) + 0.5 * log_var
                       + jnp.square(target - mean) / (2.0 * jnp.exp(log_var)))


class KullbackLeiblerDivergenceCriterion(Criterion):
    """KL(y_true || y_pred) over probability rows (Keras kld).
    reference: nn/KullbackLeiblerDivergenceCriterion.scala."""

    def forward(self, input, target):
        p = jnp.clip(target, 1e-7, 1.0)
        q = jnp.clip(input, 1e-7, 1.0)
        return jnp.mean(jnp.sum(p * jnp.log(p / q), axis=-1))


class MeanAbsolutePercentageCriterion(Criterion):
    """100 * mean(|y_t - y_p| / clip(|y_t|)). 
    reference: nn/MeanAbsolutePercentageCriterion.scala."""

    def forward(self, input, target):
        diff = jnp.abs(target - input) / jnp.clip(jnp.abs(target), 1e-7, None)
        return 100.0 * jnp.mean(diff)


class MeanSquaredLogarithmicCriterion(Criterion):
    """mean((log(y_t+1) - log(y_p+1))^2).
    reference: nn/MeanSquaredLogarithmicCriterion.scala."""

    def forward(self, input, target):
        a = jnp.log(jnp.clip(target, 1e-7, None) + 1.0)
        b = jnp.log(jnp.clip(input, 1e-7, None) + 1.0)
        return jnp.mean(jnp.square(a - b))


class PoissonCriterion(Criterion):
    """mean(y_p - y_t * log(y_p)). reference: nn/PoissonCriterion.scala."""

    def forward(self, input, target):
        return jnp.mean(input - target * jnp.log(jnp.clip(input, 1e-7, None)))


class SmoothL1CriterionWithWeights(Criterion):
    """Fast-RCNN bbox regression loss: smooth-L1 with sigma and
    inside/outside weights, normalized by `num`.
    reference: nn/SmoothL1CriterionWithWeights.scala.

    forward(input, Table(target, inside_w, outside_w)) or plain target."""

    def __init__(self, sigma: float = 1.0, num: int = 0):
        self.sigma2 = sigma * sigma
        self.num = num

    def forward(self, input, target):
        if isinstance(target, Table):
            t = target[1]
            in_w = target[2] if 2 in target else None
            out_w = target[3] if 3 in target else None
        else:
            t, in_w, out_w = target, None, None
        d = input - t
        if in_w is not None:
            d = d * in_w
        ad = jnp.abs(d)
        loss = jnp.where(ad < 1.0 / self.sigma2,
                         0.5 * self.sigma2 * d * d,
                         ad - 0.5 / self.sigma2)
        if out_w is not None:
            loss = loss * out_w
        total = jnp.sum(loss)
        return total / self.num if self.num > 0 else total


class TimeDistributedMaskCriterion(Criterion):
    """Apply a criterion per timestep, skipping padded positions
    (target == padding_value). reference: nn/TimeDistributedMaskCriterion.scala."""

    def __init__(self, criterion: Criterion, padding_value: int = 0):
        self.criterion = criterion
        self.padding_value = padding_value

    def forward(self, input, target):
        b, t = target.shape[0], target.shape[1]
        flat_in = input.reshape((b * t,) + input.shape[2:])
        flat_tg = target.reshape((b * t,) + target.shape[2:])
        not_pad = flat_tg != self.padding_value
        if not_pad.ndim > 1:
            # a timestep is padded only when ALL its features equal the pad value
            not_pad = jnp.any(not_pad.reshape(b * t, -1), axis=-1)
        mask = not_pad.astype(flat_in.dtype)
        # per-element loss via vmap of the inner criterion on singletons
        per = jax.vmap(
            lambda i, tg: self.criterion.forward(i[None], tg[None]))(
                flat_in, flat_tg)
        return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)


class TransformerCriterion(Criterion):
    """Apply transformation modules to input/target, then an inner criterion
    (perceptual-loss style). reference: nn/TransformerCriterion.scala."""

    def __init__(self, criterion: Criterion, input_transformer=None,
                 target_transformer=None):
        self.criterion = criterion
        self.input_transformer = input_transformer
        self.target_transformer = target_transformer

    def _run(self, module, x):
        if module is None:
            return x
        if module.params is None:
            from bigdl_tpu.nn.module import shape_of
            module.init(shape_of(x))
        y, _ = module.apply(module.params, module.state, x, training=False)
        return y

    def forward(self, input, target):
        return self.criterion.forward(self._run(self.input_transformer, input),
                                      self._run(self.target_transformer, target))
