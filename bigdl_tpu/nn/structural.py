"""Structural / utility layers and gradient-shaping identities.

Reference files (all under nn/): Negative.scala, Echo.scala,
GradientReversal.scala, ActivityRegularization.scala, L1Penalty.scala,
NegativeEntropyPenalty.scala, Index.scala, Masking.scala, MaskedSelect.scala,
Pack.scala, Replicate.scala, Reverse.scala, Tile.scala, InferReshape.scala,
NarrowTable.scala, BifurcateSplitTable.scala, CrossProduct.scala,
DenseToSparse.scala, SparseJoinTable.scala.

The penalty layers (ActivityRegularization/L1Penalty/NegativeEntropyPenalty)
are identity maps whose *backward* adds the penalty's gradient to gradInput
(the reference accumulates `loss` forward and patches gradInput backward).
Under jax autograd the same contract is a `custom_vjp` identity whose
cotangent is `g + d(penalty)/dx` — the penalty then influences training
exactly as in the reference without the trainer summing side losses.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Container, Module


class Negative(Module):
    """y = -x. reference: nn/Negative.scala."""

    def __init__(self, inplace: bool = False, name: Optional[str] = None):
        super().__init__(name)

    def apply(self, params, state, x, *, training=False, rng=None):
        return -x, state


class Echo(Module):
    """Identity that prints the activity shape on host — debugging aid.
    reference: nn/Echo.scala.  Uses jax.debug.callback so it works under jit
    without forcing a host sync of the values."""

    def apply(self, params, state, x, *, training=False, rng=None):
        shapes = jax.tree_util.tree_map(lambda a: jnp.shape(a), x)
        jax.debug.print("{name}: shape={shape}", name=self.name,
                        shape=str(shapes))
        return x, state


def _grad_scale_identity(scale):
    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (g * scale,)

    f.defvjp(fwd, bwd)
    return f


class GradientReversal(Module):
    """Identity forward, gradient scaled by -lambda backward (adversarial
    domain adaptation). reference: nn/GradientReversal.scala."""

    def __init__(self, the_lambda: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.the_lambda = the_lambda

    def set_lambda(self, l: float) -> "GradientReversal":
        self.the_lambda = l
        return self

    def apply(self, params, state, x, *, training=False, rng=None):
        return _grad_scale_identity(-self.the_lambda)(x), state


def _penalty_identity(penalty_grad):
    """Identity whose backward adds d(penalty)/dx to the cotangent."""

    @jax.custom_vjp
    def f(x):
        return x

    def fwd(x):
        return x, x

    def bwd(x, g):
        return (g + penalty_grad(x),)

    f.defvjp(fwd, bwd)
    return f


class ActivityRegularization(Module):
    """L1+L2 activity penalty: loss += l1*sum|x| + l2*sum(x^2).
    reference: nn/ActivityRegularization.scala."""

    def __init__(self, l1: float = 0.0, l2: float = 0.0, name: Optional[str] = None):
        super().__init__(name)
        self.l1, self.l2 = l1, l2

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or (self.l1 == 0.0 and self.l2 == 0.0):
            return x, state
        l1, l2 = self.l1, self.l2
        y = _penalty_identity(lambda t: l1 * jnp.sign(t) + 2.0 * l2 * t)(x)
        return y, state


class L1Penalty(Module):
    """Sparsity penalty l1weight * sum|x| on the activity.
    reference: nn/L1Penalty.scala."""

    def __init__(self, l1weight: float, size_average: bool = False,
                 provide_output: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.l1weight = float(l1weight)
        self.size_average = size_average

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training:
            return x, state
        w = self.l1weight
        if self.size_average:
            w = w / np.prod(x.shape)
        y = _penalty_identity(lambda t, w=w: w * jnp.sign(t))(x)
        return y, state


class NegativeEntropyPenalty(Module):
    """Penalty beta * sum(p log p) pushing a probability activity towards
    high entropy (exploration bonus). reference: nn/NegativeEntropyPenalty.scala."""

    def __init__(self, beta: float = 0.01, name: Optional[str] = None):
        super().__init__(name)
        self.beta = beta

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training:
            return x, state
        beta = self.beta

        def grad(p):
            return beta * (jnp.log(jnp.maximum(p, 1e-12)) + 1.0)

        return _penalty_identity(grad)(x), state


class Index(Module):
    """Table(tensor, indices) -> gather along `dim`. Indices are 1-based in
    the reference (nn/Index.scala); here 0-based like the rest of the API."""

    def __init__(self, dim: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        t, idx = x[1], x[2]
        return jnp.take(t, idx.astype(jnp.int32), axis=self.dim), state


class Masking(Module):
    """Zero out timesteps whose features ALL equal mask_value (the mask
    propagation contract of Keras Masking). reference: nn/Masking.scala."""

    def __init__(self, mask_value: float = 0.0, name: Optional[str] = None):
        super().__init__(name)
        self.mask_value = mask_value

    def apply(self, params, state, x, *, training=False, rng=None):
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, x, 0.0), state


class MaskedSelect(Module):
    """Table(tensor, byte mask) -> 1-D tensor of selected elements.

    reference: nn/MaskedSelect.scala.  The output length is data-dependent,
    which XLA cannot compile (dynamic shapes break MXU tiling), so this op is
    host-eager: under `jit` tracing it raises, directing the model author to
    the static-shape alternative (multiply by the mask / jnp.where), which is
    what a TPU-native graph should contain.
    """

    def apply(self, params, state, x, *, training=False, rng=None):
        t, mask = x[1], x[2]
        if isinstance(jnp.asarray(t), jax.core.Tracer):
            raise TypeError(
                "MaskedSelect has a data-dependent output shape and cannot be "
                "jitted; use masking (x * mask) for on-device graphs")
        tn = np.asarray(t)
        mn = np.asarray(mask).astype(bool)
        return jnp.asarray(tn[mn]), state


class Pack(Module):
    """Stack a Table of equal-shape tensors along a new axis.
    reference: nn/Pack.scala."""

    def __init__(self, dim: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        parts = list(x) if isinstance(x, Table) else [x]
        return jnp.stack(parts, axis=self.dim), state


class Replicate(Module):
    """Insert a new axis of size n_features at `dim` by broadcasting.
    reference: nn/Replicate.scala."""

    def __init__(self, n_features: int, dim: int = 0, n_dim: int = 0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_features, self.dim = n_features, dim

    def apply(self, params, state, x, *, training=False, rng=None):
        y = jnp.expand_dims(x, self.dim)
        reps = [1] * y.ndim
        reps[self.dim] = self.n_features
        return jnp.tile(y, reps), state

    def output_shape(self, input_shape):
        s = list(input_shape)
        s.insert(self.dim, self.n_features)
        return tuple(s)


class Reverse(Module):
    """Flip along one axis. reference: nn/Reverse.scala."""

    def __init__(self, dimension: int = 0, is_inplace: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.flip(x, axis=self.dimension), state


class Tile(Module):
    """Repeat `copies` times along an axis. reference: nn/Tile.scala."""

    def __init__(self, dim: int = 0, copies: int = 2, name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.copies = dim, copies

    def apply(self, params, state, x, *, training=False, rng=None):
        reps = [1] * x.ndim
        reps[self.dim] = self.copies
        return jnp.tile(x, reps), state

    def output_shape(self, input_shape):
        s = list(input_shape)
        s[self.dim] *= self.copies
        return tuple(s)


class InferReshape(Module):
    """Reshape with -1 (inferred) and 0 (copy input dim) entries.
    reference: nn/InferReshape.scala."""

    def __init__(self, size: Sequence[int], batch_mode: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def _target(self, in_shape):
        lead = (in_shape[0],) if self.batch_mode else ()
        offset = 1 if self.batch_mode else 0
        out = []
        for i, s in enumerate(self.size):
            if s == 0:
                out.append(in_shape[i + offset])
            else:
                out.append(s)
        known = int(np.prod([s for s in out if s != -1])) * int(np.prod(lead, dtype=np.int64) if lead else 1)
        total = int(np.prod(in_shape))
        out = [total // known if s == -1 else s for s in out]
        return tuple(lead) + tuple(out)

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.reshape(x, self._target(x.shape)), state

    def output_shape(self, input_shape):
        return self._target(input_shape)


class NarrowTable(Module):
    """Slice a Table: elements [offset, offset+length).
    reference: nn/NarrowTable.scala (1-based offset there; 0-based here)."""

    def __init__(self, offset: int, length: int = 1, name: Optional[str] = None):
        super().__init__(name)
        self.offset, self.length = offset, length

    def apply(self, params, state, x, *, training=False, rng=None):
        vals = list(x)[self.offset:self.offset + self.length]
        return Table(*vals), state


class BifurcateSplitTable(Module):
    """Split a tensor into two halves along `dimension` -> Table(left, right).
    reference: nn/BifurcateSplitTable.scala."""

    def __init__(self, dimension: int, name: Optional[str] = None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, x, *, training=False, rng=None):
        n = x.shape[self.dimension]
        left = jax.lax.slice_in_dim(x, 0, n // 2, axis=self.dimension)
        right = jax.lax.slice_in_dim(x, n // 2, n, axis=self.dimension)
        return Table(left, right), state


class CrossProduct(Module):
    """Pairwise dot products of a Table of vectors -> (batch, numPairs).
    reference: nn/CrossProduct.scala (wide-and-deep feature crossing)."""

    def __init__(self, num_tensor: int = 0, embedding_size: int = 0,
                 name: Optional[str] = None):
        super().__init__(name)

    def apply(self, params, state, x, *, training=False, rng=None):
        vals = list(x)
        outs = []
        for i in range(len(vals)):
            for j in range(i + 1, len(vals)):
                outs.append(jnp.sum(vals[i] * vals[j], axis=-1, keepdims=True))
        return jnp.concatenate(outs, axis=-1), state


class DenseToSparse(Module):
    """Identity on TPU: the reference converts DenseTensor -> SparseTensor
    (nn/DenseToSparse.scala) to feed SparseLinear/SparseJoinTable; the
    TPU-native pipeline keeps sparse-ish features dense (multi-hot) because
    scatter/gather sparse gemm loses to the MXU's dense matmul at BigDL's
    feature widths (see SparseLinear docstring)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return x, state


class SparseJoinTable(Module):
    """Concatenate (dense-encoded) sparse features along `dimension`.
    reference: nn/SparseJoinTable.scala."""

    def __init__(self, dimension: int, name: Optional[str] = None):
        super().__init__(name)
        self.dimension = dimension

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.concatenate(list(x), axis=self.dimension), state


class ResizeBilinear(Module):
    """Bilinear resize of NHWC maps to (out_height, out_width).
    reference: nn/ResizeBilinear.scala (and the TF ResizeBilinear op it
    backs).  align_corners matches TF semantics: corner pixels map to
    corners exactly (scale = (in-1)/(out-1))."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.out_hw = (output_height, output_width)
        self.align_corners = align_corners

    def _interp_1d(self, x, axis, out_size):
        in_size = x.shape[axis]
        if in_size == out_size:
            return x
        if self.align_corners and out_size > 1:
            pos = jnp.arange(out_size, dtype=jnp.float32) * (
                (in_size - 1) / (out_size - 1))
        else:
            pos = jnp.arange(out_size, dtype=jnp.float32) * (in_size / out_size)
        lo = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, in_size - 1)
        hi = jnp.minimum(lo + 1, in_size - 1)
        frac = (pos - lo).astype(x.dtype)
        shape = [1] * x.ndim
        shape[axis] = out_size
        frac = frac.reshape(shape)
        return (jnp.take(x, lo, axis=axis) * (1 - frac)
                + jnp.take(x, hi, axis=axis) * frac)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = self._interp_1d(x, 1, self.out_hw[0])
        y = self._interp_1d(y, 2, self.out_hw[1])
        return y, state

    def output_shape(self, input_shape):
        n, _, _, c = input_shape
        return (n, self.out_hw[0], self.out_hw[1], c)


class Remat(Container):
    """Gradient checkpointing wrapper (`jax.checkpoint` around the child):
    the child's internal activations are RECOMPUTED during backward instead
    of stored to HBM.

    No reference counterpart — the closest is shareGradInput's memory
    aliasing (models/resnet/ResNet.scala), which XLA buffer reuse already
    subsumes.  On an HBM-bandwidth-bound train step (ResNet-50 at batch
    256 has ~3x more bandwidth demand than FLOP demand, see
    BENCH_APPENDIX.md) rematerialization converts spare MXU FLOPs into
    reduced activation traffic.
    """

    _constructor_children = True

    def __init__(self, inner: Module, name: Optional[str] = None):
        super().__init__(name)
        self.children["inner"] = inner
        self.inner = inner

    def build(self, rng, input_shape):
        p, s, out = self.inner.build(rng, input_shape)
        return {"inner": p}, {"inner": s}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        import jax as _jax

        fn = _jax.checkpoint(
            lambda p, xx: self.inner.apply(p, state["inner"], xx,
                                           training=training, rng=rng))
        out, new_s = fn(params["inner"], x)
        return out, {"inner": new_s}

    def output_shape(self, input_shape):
        return self.inner.output_shape(input_shape)
