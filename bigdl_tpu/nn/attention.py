"""Multi-head attention + transformer blocks.

The reference has no attention layers at all (survey §5.7); long-context is
a designed-fresh, first-class TPU capability here.  The layer wraps the
attention cores in `bigdl_tpu.ops.attention`:

  * default (`use_flash=True`): the pallas blockwise flash kernel
    (ops/flash_attention.py) — per the last VALID measurement (round 3:
    flash wins from S~8k, dense fails to compile at S=32768).  The
    round-5 re-measure that flipped the default to dense fed the cores
    axis-swapped (B, H, S, D) inputs and is struck as invalid
    (ADVICE.md r5 high; BENCH_APPENDIX "Attention kernel" section is
    marked accordingly); the default stays a measured, revisitable
    choice — re-flip only on a valid re-run,
  * `use_flash=False` — XLA's dense softmax-attention fusion,
  * `seq_parallel="ring"` — ring attention over the mesh `sequence` axis
    (K/V blocks rotate one ICI hop per step; O(S_local) memory/chip),
  * `seq_parallel="ulysses"` — all-to-all head-scatter/sequence-gather.

Sequence parallelism engages only when the active mesh actually has a
sequence axis of size > 1, so the same model code runs single-chip and on a
dp x sp x tp mesh unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bigdl_tpu.core.engine import AXIS_DATA, AXIS_SEQUENCE, Engine
from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.activation import GELU
from bigdl_tpu.nn.dropout import Dropout
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Container, Module, child_rng
from bigdl_tpu.nn.norm import LayerNormalization
from bigdl_tpu.ops.attention import dense_attention, ring_attention, ulysses_attention
from bigdl_tpu.ops.decode_attention import (decode_attention_pallas,
                                            decode_attention_ref, decode_impl)
from bigdl_tpu.ops.flash_attention import flash_attention


def apply_rope(x: jax.Array, *, base: float = 10000.0,
               positions: Optional[jax.Array] = None) -> jax.Array:
    """Rotary position embedding over (B, S, H, D) (D even).

    `positions` may be (S,) — shared across the batch, the training case —
    or (B, S) for per-row offsets (the decode path, where every KV-cache
    slot sits at its own absolute position).
    """
    b, s, h, d = x.shape
    if positions is None:
        positions = jnp.arange(s)
    positions = jnp.asarray(positions)
    freqs = base ** (-jnp.arange(0, d, 2) / d)
    angles = positions[..., :, None] * freqs  # (S, D/2) or (B, S, D/2)
    if angles.ndim == 2:
        angles = angles[None]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., ::2], x[..., 1::2]
    rot = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rot.reshape(b, s, h, d).astype(x.dtype)


def causal_mask(q_len: int, kv_len: int, *,
                q_offset: "int | jax.Array" = 0) -> jax.Array:
    """Boolean (q_len, kv_len) causal mask with a query position offset.

    Query row i sits at absolute position `q_offset + i`; key column j at
    position j.  True = attend.  With `q_offset=0, kv_len=q_len` this is
    the standard lower-triangular training mask; a length-1 decode query
    against a cached prefix uses `causal_mask(1, capacity, q_offset=t)`,
    which both enforces causality AND excludes the not-yet-written tail of
    the ring buffer (cache index j only holds a valid entry once position
    j has been written, i.e. j <= t).  `q_offset` may be a traced scalar.
    """
    qpos = q_offset + jnp.arange(q_len)
    return qpos[:, None] >= jnp.arange(kv_len)[None, :]


def quantize_kv(t: jax.Array) -> "tuple[jax.Array, jax.Array]":
    """Symmetric per-token per-head int8 quantization of a K or V tensor
    (..., head_dim) -> (int8 values, fp32 scales over the leading dims).
    Scales are absmax/127 floored at 1e-8 so all-zero rows stay exactly
    zero after dequant (the trash-block / unwritten-tail invariant)."""
    scale = jnp.maximum(jnp.max(jnp.abs(t), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(t / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _active_mesh(explicit: Optional[Mesh]) -> Optional[Mesh]:
    if explicit is not None:
        return explicit
    if Engine._mesh is not None:  # initialized Engine wins
        return Engine._mesh
    return None


class MultiHeadAttention(Module):
    """Self-attention over (B, S, D) inputs.

    No reference counterpart (the reference tops out at LSTM/GRU recurrence,
    nn/Recurrent.scala); API follows the framework's functional Module
    protocol.  `causal=True` gives decoder (LM) masking.
    """

    def __init__(self, hidden_size: int, n_head: int, *, causal: bool = False,
                 dropout: float = 0.0, with_bias: bool = True, rope: bool = False,
                 seq_parallel: Optional[str] = None, use_flash: bool = True,
                 seq_axis: str = AXIS_SEQUENCE, data_axis: str = AXIS_DATA,
                 name: Optional[str] = None):
        super().__init__(name)
        if hidden_size % n_head != 0:
            raise ValueError(f"hidden_size {hidden_size} % n_head {n_head} != 0")
        if seq_parallel not in (None, "ring", "ulysses"):
            raise ValueError(f"unknown seq_parallel {seq_parallel!r}")
        self.hidden_size = hidden_size
        self.n_head = n_head
        self.head_dim = hidden_size // n_head
        self.causal = causal
        self.dropout_p = dropout
        self.with_bias = with_bias
        self.rope = rope
        self.seq_parallel = seq_parallel
        self.use_flash = use_flash
        self.seq_axis = seq_axis
        self.data_axis = data_axis
        self.mesh: Optional[Mesh] = None  # explicit override for tests

    def build(self, rng, input_shape):
        d = self.hidden_size
        ks = jax.random.split(rng, 4)
        xavier = init_mod.Xavier()
        params = {}
        for key, k in zip(("wq", "wk", "wv", "wo"), ks):
            params[key] = xavier(k, (d, d), d, d)
            if self.with_bias:
                params[key.replace("w", "b")] = jnp.zeros((d,), jnp.float32)
        return params, {}, input_shape

    def _core(self, q, k, v):
        mesh = _active_mesh(self.mesh)
        sp = self.seq_parallel
        if sp is not None and mesh is not None and \
                mesh.shape.get(self.seq_axis, 1) > 1:
            axis_size = mesh.shape[self.seq_axis]
            if sp == "ulysses" and self.n_head % axis_size != 0:
                raise ValueError(
                    f"ulysses sequence parallelism needs n_head ({self.n_head}) "
                    f"divisible by the '{self.seq_axis}' mesh axis ({axis_size})")
            core = ring_attention if sp == "ring" else ulysses_attention
            fn = partial(core, axis_name=self.seq_axis, causal=self.causal)
            data = self.data_axis if self.data_axis in mesh.axis_names else None
            spec = P(data, self.seq_axis, None, None)
            return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                                 out_specs=spec)(q, k, v)
        if self.use_flash:
            # pallas blockwise kernel; falls back to dense when shapes
            # don't tile (bigdl_tpu/ops/flash_attention.py)
            return flash_attention(q, k, v, causal=self.causal)
        return dense_attention(q, k, v, causal=self.causal)

    def apply(self, params, state, x, *, training=False, rng=None):
        b, s, d = x.shape
        h, hd = self.n_head, self.head_dim

        def proj(name, t):
            y = t @ params["w" + name]
            if self.with_bias:
                y = y + params["b" + name]
            return y.reshape(b, s, h, hd)

        q, k, v = proj("q", x), proj("k", x), proj("v", x)
        if self.rope:
            q, k = apply_rope(q), apply_rope(k)
        ctx = self._core(q, k, v).reshape(b, s, d)
        out = ctx @ params["wo"]
        if self.with_bias:
            out = out + params["bo"]
        if self.dropout_p > 0.0:
            out, _ = Dropout(self.dropout_p).apply({}, {}, out,
                                                   training=training, rng=rng)
        return out, state

    def apply_cached(self, params, x, kv, *, lengths, wrapped_append=False):
        """Cache-aware inference forward (the generation hot path).

        `x` is (B, S, D) NEW tokens only; `lengths` (B,) int32 counts
        tokens already written per row, so row b's new tokens sit at
        absolute positions lengths[b]..lengths[b]+S-1 and land at ring
        indices `position % C`.  `kv` is a dict describing ONE layer's
        cache in one of two layouts:

          * ring (kvcache.py): {"k","v"} of (B, C, H, Dh);
          * paged (pagedkv.py): {"k","v"} are the POOL (n_blocks,
            block_size, H, Dh) shared across slots, plus "table"
            (B, max_blocks) int32 block ids (0 = trash block); the
            logical ring index maps through the table.

        Either layout optionally carries {"k_scale","v_scale"} (int8 KV):
        K/V are quantized per token per head at write and dequantized at
        read.  Returns (out, new_kv) with new_kv in the same layout.

        Two shapes matter: prefill (B=1, S<=C, lengths=0) and decode
        (S=1, per-row lengths, ring wrap-around = sliding-window
        attention).  S=1 dispatches to the decode-specialized lane when
        measured to win (ops/decode_attention.py `decode_impl`); the
        paged read otherwise gathers pool blocks back into ring layout
        and runs the IDENTICAL dense path, which is what keeps paged-on
        vs paged-off bitwise-equal at fp32 (masked trash/stale columns
        get exactly-zero softmax weight).  The default mask indexes keys
        by ring slot, which equals position only while writes are
        monotone within the window — a multi-token append AFTER a wrap
        needs `wrapped_append=True`: the mask then recovers each
        column's LATEST written position (`e - ((e - j) % C)` for last
        write position e) so chunked prefill of a prompt longer than
        the ring and the spec-decode verify pass stay causally correct.
        In the no-wrap case the recovered position equals the column
        index, so the two masks are boolean-identical and the outputs
        bitwise-equal — which is what lets the chunked executables use
        it unconditionally without breaking chunk-vs-unchunked parity.
        """
        b, s, d = x.shape
        h, hd = self.n_head, self.head_dim

        def proj(name, t):
            y = t @ params["w" + name]
            if self.with_bias:
                y = y + params["b" + name]
            return y.reshape(b, s, h, hd)

        q, k, v = proj("q", x), proj("k", x), proj("v", x)
        positions = lengths[:, None] + jnp.arange(s)[None, :]  # (B, S)
        if self.rope:
            # keys are stored rope'd at their absolute write position;
            # the decode query ropes at its own offset, so Q.K stays the
            # relative-position product regardless of cache state
            q = apply_rope(q, positions=positions)
            k = apply_rope(k, positions=positions)
        paged = "table" in kv
        quant = kv.get("k_scale") is not None
        if paged:
            table = kv["table"]
            blk = kv["k"].shape[1]
            cap = table.shape[1] * blk
            idx = positions % cap
            # the write index IS the table lookup: unclaimed entries are 0,
            # so pad/inactive writes scatter harmlessly into the trash block
            wix = (jnp.take_along_axis(table, idx // blk, axis=1), idx % blk)
        else:
            cap = kv["k"].shape[1]
            idx = positions % cap
            wix = (jnp.arange(b)[:, None], idx)
        if quant:
            k_q, k_sc = quantize_kv(k)
            v_q, v_sc = quantize_kv(v)
            new_kv = {"k": kv["k"].at[wix].set(k_q),
                      "v": kv["v"].at[wix].set(v_q),
                      "k_scale": kv["k_scale"].at[wix].set(k_sc),
                      "v_scale": kv["v_scale"].at[wix].set(v_sc)}
        else:
            new_kv = {"k": kv["k"].at[wix].set(k.astype(kv["k"].dtype)),
                      "v": kv["v"].at[wix].set(v.astype(kv["v"].dtype))}
        if paged:
            new_kv["table"] = table

        impl = decode_impl(cap) if s == 1 else "dense"
        if impl == "pallas" and paged:
            # fused gather: the kernel DMAs pool blocks straight off the
            # scalar-prefetched table — no materialized (B, C, H, Dh)
            ctx = decode_attention_pallas(
                q[:, 0], new_kv["k"], new_kv["v"], table, lengths,
                k_scale=new_kv.get("k_scale"),
                v_scale=new_kv.get("v_scale"))[:, None]
        else:
            if paged:
                keys = new_kv["k"][table].reshape(b, cap, h, hd)
                vals = new_kv["v"][table].reshape(b, cap, h, hd)
                if quant:
                    k_sc = new_kv["k_scale"][table].reshape(b, cap, h)
                    v_sc = new_kv["v_scale"][table].reshape(b, cap, h)
            else:
                keys, vals = new_kv["k"], new_kv["v"]
                if quant:
                    k_sc, v_sc = new_kv["k_scale"], new_kv["v_scale"]
            if quant:
                keys = keys.astype(q.dtype) * k_sc[..., None]
                vals = vals.astype(q.dtype) * v_sc[..., None]
            else:
                keys = keys.astype(q.dtype)
                vals = vals.astype(q.dtype)
            if impl in ("ref", "pallas"):
                ctx = decode_attention_ref(q[:, 0], keys, vals,
                                           lengths=lengths)[:, None]
            elif wrapped_append and s > 1:
                # wrap-safe multi-token append: column j holds the
                # LATEST position p ≡ j (mod C) with p <= e, where e is
                # the last position written this pass; attend iff that
                # position is causally visible and was ever written.
                # Without a wrap pos_j == j, reducing to the mask below.
                e = positions[:, -1][:, None]               # (B, 1)
                pos_j = e - ((e - jnp.arange(cap)[None, :]) % cap)
                mask = (pos_j[:, None, :] <= positions[:, :, None]) \
                    & (pos_j[:, None, :] >= 0)              # (B, S, C)
                ctx = dense_attention(q, keys, vals, mask=mask[:, None])
            else:
                # per-row causal mask over the full ring: (B,S,C)->(B,1,S,C)
                mask = jax.vmap(
                    lambda off: causal_mask(s, cap, q_offset=off))(lengths)
                ctx = dense_attention(q, keys, vals, mask=mask[:, None])
        out = ctx.reshape(b, s, d) @ params["wo"]
        if self.with_bias:
            out = out + params["bo"]
        return out, new_kv


class TransformerBlock(Container):
    """Pre-LN transformer decoder/encoder block:
    x + MHA(LN(x)); then x + MLP(LN(x)) with a GELU 4x-wide MLP."""

    _constructor_children = True  # children derive from config; don't serialize

    def __init__(self, hidden_size: int, n_head: int, *, causal: bool = True,
                 mlp_ratio: int = 4, dropout: float = 0.0, rope: bool = False,
                 seq_parallel: Optional[str] = None, use_flash: bool = True,
                 moe_experts: int = 0, moe_k: int = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        self.hidden_size = hidden_size
        self.children["ln1"] = LayerNormalization(hidden_size)
        self.children["attn"] = MultiHeadAttention(
            hidden_size, n_head, causal=causal, dropout=dropout, rope=rope,
            seq_parallel=seq_parallel, use_flash=use_flash)
        self.children["ln2"] = LayerNormalization(hidden_size)
        if moe_experts > 0:
            # expert-parallel MLP (shard its stacked params over 'expert')
            from bigdl_tpu.nn.moe import MoE

            self.children["mlp"] = MoE(hidden_size, moe_experts, k=moe_k,
                                       mlp_ratio=mlp_ratio, dropout=dropout)
        else:
            self.children["mlp"] = _Mlp(hidden_size, mlp_ratio * hidden_size,
                                        dropout)

    def build(self, rng, input_shape):
        params, state = {}, {}
        shape = input_shape
        for i, (key, m) in enumerate(self.children.items()):
            params[key], state[key], _ = m.build(jax.random.fold_in(rng, i), shape)
        return params, state, shape

    def apply(self, params, state, x, *, training=False, rng=None):
        c = self.children
        st = state if isinstance(state, dict) else {}
        h, _ = c["ln1"].apply(params["ln1"], st.get("ln1", {}), x)
        h, _ = c["attn"].apply(params["attn"], st.get("attn", {}), h,
                               training=training, rng=child_rng(rng, 0))
        x = x + h
        h, _ = c["ln2"].apply(params["ln2"], st.get("ln2", {}), x)
        h, _ = c["mlp"].apply(params["mlp"], st.get("mlp", {}), h,
                              training=training, rng=child_rng(rng, 1))
        return x + h, state

    def apply_cached(self, params, x, kv, *, lengths, wrapped_append=False):
        """Inference-only block forward against a per-layer KV ring
        buffer (see MultiHeadAttention.apply_cached); returns
        (out, new_kv)."""
        c = self.children
        h, _ = c["ln1"].apply(params["ln1"], {}, x)
        h, new_kv = c["attn"].apply_cached(params["attn"], h, kv,
                                           lengths=lengths,
                                           wrapped_append=wrapped_append)
        x = x + h
        h, _ = c["ln2"].apply(params["ln2"], {}, x)
        h, _ = c["mlp"].apply(params["mlp"], {}, h, training=False)
        return x + h, new_kv


class _Mlp(Container):
    _constructor_children = True

    def __init__(self, d: int, hidden: int, dropout: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.children["fc1"] = Linear(d, hidden)
        self.children["act"] = GELU()
        self.children["fc2"] = Linear(hidden, d)
        self.dropout = Dropout(dropout) if dropout > 0.0 else None

    def build(self, rng, input_shape):
        params, state = {}, {}
        shape = input_shape
        for i, (key, m) in enumerate(self.children.items()):
            params[key], state[key], shape = m.build(jax.random.fold_in(rng, i), shape)
        return params, state, shape

    def apply(self, params, state, x, *, training=False, rng=None):
        st = state if isinstance(state, dict) else {}
        for i, (key, m) in enumerate(self.children.items()):
            x, _ = m.apply(params[key], st.get(key, {}), x, training=training,
                           rng=child_rng(rng, i))
        if self.dropout is not None:
            x, _ = self.dropout.apply({}, {}, x, training=training, rng=rng)
        return x, state
