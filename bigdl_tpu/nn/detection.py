"""Detection heads: anchors, NMS, proposals, ROI pooling, SSD/Frcnn outputs.

Reference: nn/Anchor.scala, nn/Nms.scala, nn/PriorBox.scala,
nn/Proposal.scala, nn/RoiPooling.scala, nn/DetectionOutputSSD.scala,
nn/DetectionOutputFrcnn.scala.

TPU-first redesign: the reference's NMS is a scalar greedy loop with early
exit; data-dependent shapes don't compile under XLA, so every op here is
FIXED-SHAPE — NMS returns a (max_out,) index vector plus a validity mask,
proposals/detections are padded to their top-k, and suppression runs as a
`lax.fori_loop` over a precomputed IoU matrix.  Boxes are (x1, y1, x2, y2).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Module

# ---------------------------------------------------------------------------
# box math


def bbox_area(boxes: jnp.ndarray) -> jnp.ndarray:
    return jnp.clip(boxes[..., 2] - boxes[..., 0], 0) * \
        jnp.clip(boxes[..., 3] - boxes[..., 1], 0)


def bbox_iou(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Pairwise IoU: a (N, 4), b (M, 4) -> (N, M)."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = bbox_area(a)[:, None] + bbox_area(b)[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def bbox_transform_inv(boxes: jnp.ndarray, deltas: jnp.ndarray) -> jnp.ndarray:
    """Decode (dx, dy, dw, dh) deltas onto anchor/prior boxes.
    reference: the BboxUtil.bboxTransformInv used by Proposal.scala."""
    widths = boxes[:, 2] - boxes[:, 0] + 1.0
    heights = boxes[:, 3] - boxes[:, 1] + 1.0
    ctr_x = boxes[:, 0] + 0.5 * (widths - 1.0)
    ctr_y = boxes[:, 1] + 0.5 * (heights - 1.0)
    dx, dy, dw, dh = deltas[:, 0], deltas[:, 1], deltas[:, 2], deltas[:, 3]
    pred_ctr_x = dx * widths + ctr_x
    pred_ctr_y = dy * heights + ctr_y
    pred_w = jnp.exp(dw) * widths
    pred_h = jnp.exp(dh) * heights
    # exact inverse of the encode: zero deltas reproduce the input box
    return jnp.stack([pred_ctr_x - 0.5 * (pred_w - 1.0),
                      pred_ctr_y - 0.5 * (pred_h - 1.0),
                      pred_ctr_x + 0.5 * (pred_w - 1.0),
                      pred_ctr_y + 0.5 * (pred_h - 1.0)], axis=1)


def clip_boxes(boxes: jnp.ndarray, height: float, width: float) -> jnp.ndarray:
    x1 = jnp.clip(boxes[:, 0], 0, width - 1.0)
    y1 = jnp.clip(boxes[:, 1], 0, height - 1.0)
    x2 = jnp.clip(boxes[:, 2], 0, width - 1.0)
    y2 = jnp.clip(boxes[:, 3], 0, height - 1.0)
    return jnp.stack([x1, y1, x2, y2], axis=1)


# ---------------------------------------------------------------------------
# NMS


def nms(boxes: jnp.ndarray, scores: jnp.ndarray, iou_threshold: float,
        max_out: int, score_threshold: float = -jnp.inf
        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Greedy NMS, fixed output size.

    Returns (indices (max_out,) int32, valid (max_out,) bool).  Padded slots
    hold index 0 with valid=False.  reference: nn/Nms.scala (scalar greedy
    loop -> fori_loop over a precomputed IoU matrix here).
    """
    n = boxes.shape[0]
    order = jnp.argsort(-scores)
    sboxes = boxes[order]
    sscores = scores[order]
    iou_mat = bbox_iou(sboxes, sboxes)

    def body(i, suppressed):
        alive = jnp.logical_not(suppressed[i]) & (sscores[i] > score_threshold)
        kill = alive & (iou_mat[i] > iou_threshold) & \
            (jnp.arange(n) > i)
        return jnp.where(kill, True, suppressed)

    suppressed = lax.fori_loop(0, n, body, jnp.zeros((n,), bool))
    keep = jnp.logical_not(suppressed) & (sscores > score_threshold)
    # stable-select the kept entries into the first `max_out` slots
    rank = jnp.cumsum(keep) - 1
    # suppressed/overflow entries get slot >= max_out -> mode="drop" discards
    slot = jnp.where(keep, rank, max_out)
    idx_out = jnp.zeros((max_out,), jnp.int32).at[slot].set(
        order.astype(jnp.int32), mode="drop")
    valid = jnp.zeros((max_out,), bool).at[slot].set(keep, mode="drop")
    return idx_out, valid


class Nms(Module):
    """Module wrapper: input Table(boxes, scores) -> Table(indices, valid).
    reference: nn/Nms.scala."""

    def __init__(self, iou_threshold: float = 0.3, max_out: int = 100,
                 score_threshold: float = -float("inf"),
                 name: Optional[str] = None):
        super().__init__(name)
        self.iou_threshold = iou_threshold
        self.max_out = max_out
        self.score_threshold = score_threshold

    def apply(self, params, state, x, *, training=False, rng=None):
        boxes, scores = x[1], x[2]
        idx, valid = nms(boxes, scores, self.iou_threshold, self.max_out,
                         self.score_threshold)
        return Table(idx, valid), state


# ---------------------------------------------------------------------------
# anchor / prior generation


class Anchor:
    """RPN anchor generator.  reference: nn/Anchor.scala (generateAnchors:
    base box 0..base_size-1, ratio enumeration then scale enumeration)."""

    def __init__(self, ratios: Sequence[float], scales: Sequence[float],
                 base_size: int = 16):
        self.ratios = list(ratios)
        self.scales = list(scales)
        self.base_size = base_size
        self._base = self._generate_base()

    def _generate_base(self) -> np.ndarray:
        base = np.array([0, 0, self.base_size - 1, self.base_size - 1], np.float32)
        w = base[2] - base[0] + 1
        h = base[3] - base[1] + 1
        x_ctr = base[0] + 0.5 * (w - 1)
        y_ctr = base[1] + 0.5 * (h - 1)
        size = w * h
        anchors = []
        for r in self.ratios:
            ws = round(math.sqrt(size / r))
            hs = round(ws * r)
            for s in self.scales:
                wss, hss = ws * s, hs * s
                anchors.append([x_ctr - 0.5 * (wss - 1), y_ctr - 0.5 * (hss - 1),
                                x_ctr + 0.5 * (wss - 1), y_ctr + 0.5 * (hss - 1)])
        return np.asarray(anchors, np.float32)

    @property
    def anchor_num(self) -> int:
        return len(self.ratios) * len(self.scales)

    def generate(self, height: int, width: int, stride: float) -> jnp.ndarray:
        """All anchors for an HxW feature grid -> (H*W*A, 4)."""
        shift_x = jnp.arange(width, dtype=jnp.float32) * stride
        shift_y = jnp.arange(height, dtype=jnp.float32) * stride
        sx, sy = jnp.meshgrid(shift_x, shift_y)
        shifts = jnp.stack([sx.ravel(), sy.ravel(), sx.ravel(), sy.ravel()], axis=1)
        return (shifts[:, None, :] + jnp.asarray(self._base)[None, :, :]
                ).reshape(-1, 4)


class PriorBox(Module):
    """SSD prior boxes for one feature map.  reference: nn/PriorBox.scala.

    Output Table(priors (K, 4) normalized cxcy-minmax boxes, variances
    (K, 4)).  Input is the feature map (N, H, W, C); `image_size` fixes the
    normalization.
    """

    def __init__(self, min_sizes: Sequence[float],
                 max_sizes: Optional[Sequence[float]] = None,
                 aspect_ratios: Sequence[float] = (2.0,),
                 flip: bool = True, clip: bool = False,
                 variances: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
                 offset: float = 0.5,
                 img_h: int = 300, img_w: int = 300,
                 step_h: Optional[float] = None, step_w: Optional[float] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.min_sizes = list(min_sizes)
        self.max_sizes = list(max_sizes or [])
        if self.max_sizes:
            assert len(self.max_sizes) == len(self.min_sizes)
        ars = [1.0]
        for ar in aspect_ratios:
            if any(abs(ar - a) < 1e-6 for a in ars):
                continue
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
        self.ars = ars
        self.clip = clip
        self.variances = list(variances)
        self.offset = offset
        self.img_h, self.img_w = img_h, img_w
        self.step_h, self.step_w = step_h, step_w

    def num_priors(self) -> int:
        n = len(self.ars) * len(self.min_sizes)
        return n + len(self.max_sizes)

    def apply(self, params, state, x, *, training=False, rng=None):
        _, h, w, _ = x.shape
        step_h = self.step_h or self.img_h / h
        step_w = self.step_w or self.img_w / w
        widths, heights = [], []
        for i, ms in enumerate(self.min_sizes):
            for ar in self.ars:
                if abs(ar - 1.0) < 1e-6:
                    widths.append(ms)
                    heights.append(ms)
                    if self.max_sizes:
                        rs = math.sqrt(ms * self.max_sizes[i])
                        widths.append(rs)
                        heights.append(rs)
                else:
                    widths.append(ms * math.sqrt(ar))
                    heights.append(ms / math.sqrt(ar))
        ws = jnp.asarray(widths, jnp.float32) / 2.0
        hs = jnp.asarray(heights, jnp.float32) / 2.0
        cx = (jnp.arange(w, dtype=jnp.float32) + self.offset) * step_w
        cy = (jnp.arange(h, dtype=jnp.float32) + self.offset) * step_h
        gx, gy = jnp.meshgrid(cx, cy)  # (h, w)
        cxs = gx[..., None]  # (h, w, 1)
        cys = gy[..., None]
        boxes = jnp.stack([
            (cxs - ws) / self.img_w, (cys - hs) / self.img_h,
            (cxs + ws) / self.img_w, (cys + hs) / self.img_h], axis=-1)
        boxes = boxes.reshape(-1, 4)
        if self.clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        variances = jnp.tile(jnp.asarray(self.variances, jnp.float32),
                             (boxes.shape[0], 1))
        return Table(boxes, variances), state


# ---------------------------------------------------------------------------
# proposal


class Proposal(Module):
    """RPN proposal layer: decode anchor deltas, clip, NMS, top-k.
    reference: nn/Proposal.scala.

    Input Table(scores (N, H, W, 2A) — second half is fg, bbox_deltas
    (N, H, W, 4A), im_info (2,) = (height, width)); batch 1, like the
    reference.  Output: (post_nms_top_n, 5) rois as (0, x1, y1, x2, y2),
    plus a validity mask, as Table(rois, valid).
    """

    def __init__(self, pre_nms_top_n: int = 6000, post_nms_top_n: int = 300,
                 ratios: Sequence[float] = (0.5, 1.0, 2.0),
                 scales: Sequence[float] = (8.0, 16.0, 32.0),
                 feat_stride: int = 16, min_size: int = 16,
                 nms_threshold: float = 0.7, name: Optional[str] = None):
        super().__init__(name)
        self.pre_nms_top_n = pre_nms_top_n
        self.post_nms_top_n = post_nms_top_n
        self.anchor = Anchor(ratios, scales, base_size=feat_stride)
        self.feat_stride = feat_stride
        self.min_size = min_size
        self.nms_threshold = nms_threshold

    def apply(self, params, state, x, *, training=False, rng=None):
        scores, deltas, im_info = x[1], x[2], x[3]
        a = self.anchor.anchor_num
        _, h, w, _ = scores.shape
        fg = scores[0, :, :, a:].reshape(-1)  # (H*W*A,)
        deltas = deltas[0].reshape(-1, 4)
        anchors = self.anchor.generate(h, w, self.feat_stride)
        proposals = bbox_transform_inv(anchors, deltas)
        proposals = clip_boxes(proposals, im_info[0], im_info[1])
        ws = proposals[:, 2] - proposals[:, 0] + 1
        hs = proposals[:, 3] - proposals[:, 1] + 1
        keep = (ws >= self.min_size) & (hs >= self.min_size)
        fg = jnp.where(keep, fg, -jnp.inf)
        k = min(self.pre_nms_top_n, proposals.shape[0])
        top_scores, top_idx = lax.top_k(fg, k)
        top_boxes = proposals[top_idx]
        idx, valid = nms(top_boxes, top_scores, self.nms_threshold,
                         self.post_nms_top_n, score_threshold=-jnp.inf)
        rois = top_boxes[idx]
        valid = valid & jnp.isfinite(top_scores[idx])
        rois = jnp.concatenate([jnp.zeros((rois.shape[0], 1), rois.dtype), rois],
                               axis=1)
        return Table(rois, valid), state


# ---------------------------------------------------------------------------
# ROI pooling


class RoiPooling(Module):
    """Quantized max ROI pooling (Fast R-CNN semantics, exact Caffe bin
    rounding).  reference: nn/RoiPooling.scala.

    Input Table(features (1, H, W, C), rois (R, 5) as (batch_idx, x1, y1,
    x2, y2) in image coords).  Output (R, PH, PW, C).
    """

    def __init__(self, pooled_h: int, pooled_w: int, spatial_scale: float,
                 name: Optional[str] = None):
        super().__init__(name)
        self.ph = pooled_h
        self.pw = pooled_w
        self.spatial_scale = spatial_scale

    def apply(self, params, state, x, *, training=False, rng=None):
        feats, rois = x[1], x[2]
        fmap = feats[0]  # (H, W, C); batch 1 like the reference
        h, w, _ = fmap.shape
        ph, pw = self.ph, self.pw

        def pool_one(roi):
            x1 = jnp.round(roi[1] * self.spatial_scale)
            y1 = jnp.round(roi[2] * self.spatial_scale)
            x2 = jnp.round(roi[3] * self.spatial_scale)
            y2 = jnp.round(roi[4] * self.spatial_scale)
            roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
            roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
            bin_w = roi_w / pw
            bin_h = roi_h / ph
            # membership matrices: Mh (PH, H), Mw (PW, W)
            hh = jnp.arange(h, dtype=jnp.float32)
            wwv = jnp.arange(w, dtype=jnp.float32)
            pa = jnp.arange(ph, dtype=jnp.float32)
            pb = jnp.arange(pw, dtype=jnp.float32)
            hstart = jnp.clip(jnp.floor(pa * bin_h) + y1, 0, h)
            hend = jnp.clip(jnp.ceil((pa + 1) * bin_h) + y1, 0, h)
            wstart = jnp.clip(jnp.floor(pb * bin_w) + x1, 0, w)
            wend = jnp.clip(jnp.ceil((pb + 1) * bin_w) + x1, 0, w)
            mh = (hh[None, :] >= hstart[:, None]) & (hh[None, :] < hend[:, None])
            mw = (wwv[None, :] >= wstart[:, None]) & (wwv[None, :] < wend[:, None])
            neg = jnp.asarray(-jnp.inf, fmap.dtype)
            # max over w per output col: (H, PW, C)
            a_ = jnp.max(jnp.where(mw[None, :, :, None], fmap[:, None, :, :], neg),
                         axis=2)
            # then max over h per output row: (PH, PW, C)
            out = jnp.max(jnp.where(mh[:, :, None, None], a_[None, :, :, :], neg),
                          axis=1)
            return jnp.where(jnp.isfinite(out), out, 0.0)  # empty bin -> 0

        return jax.vmap(pool_one)(rois), state

    def output_shape(self, input_shape):
        feats, rois = input_shape
        return (rois[0], self.ph, self.pw, feats[-1])


class RoiAlign(Module):
    """Bilinear ROI align (avg), the TPU-friendly successor the framework
    prefers for new models; sampling_ratio fixed grid per bin."""

    def __init__(self, pooled_h: int, pooled_w: int, spatial_scale: float,
                 sampling_ratio: int = 2, name: Optional[str] = None):
        super().__init__(name)
        self.ph, self.pw = pooled_h, pooled_w
        self.spatial_scale = spatial_scale
        self.sampling_ratio = sampling_ratio

    def apply(self, params, state, x, *, training=False, rng=None):
        feats, rois = x[1], x[2]
        fmap = feats[0]
        h, w, c = fmap.shape
        ph, pw, sr = self.ph, self.pw, self.sampling_ratio

        def bilinear(yy, xx):
            y0 = jnp.clip(jnp.floor(yy), 0, h - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, w - 1)
            y1 = jnp.clip(y0 + 1, 0, h - 1)
            x1 = jnp.clip(x0 + 1, 0, w - 1)
            ly = jnp.clip(yy - y0, 0, 1)[..., None]
            lx = jnp.clip(xx - x0, 0, 1)[..., None]
            i = lambda a, b: fmap[a.astype(jnp.int32), b.astype(jnp.int32)]
            return (i(y0, x0) * (1 - ly) * (1 - lx) + i(y0, x1) * (1 - ly) * lx
                    + i(y1, x0) * ly * (1 - lx) + i(y1, x1) * ly * lx)

        def pool_one(roi):
            x1 = roi[1] * self.spatial_scale
            y1 = roi[2] * self.spatial_scale
            x2 = roi[3] * self.spatial_scale
            y2 = roi[4] * self.spatial_scale
            roi_w = jnp.maximum(x2 - x1, 1.0)
            roi_h = jnp.maximum(y2 - y1, 1.0)
            bin_w = roi_w / pw
            bin_h = roi_h / ph
            # sample grid: (PH*SR) x (PW*SR) points
            gy = y1 + (jnp.arange(ph * sr, dtype=jnp.float32) + 0.5) * bin_h / sr
            gx = x1 + (jnp.arange(pw * sr, dtype=jnp.float32) + 0.5) * bin_w / sr
            yy, xx = jnp.meshgrid(gy, gx, indexing="ij")
            vals = bilinear(yy, xx)  # (PH*SR, PW*SR, C)
            vals = vals.reshape(ph, sr, pw, sr, c)
            return vals.mean(axis=(1, 3))

        return jax.vmap(pool_one)(rois), state


# ---------------------------------------------------------------------------
# detection outputs


def _decode_ssd(priors: jnp.ndarray, variances: jnp.ndarray,
                loc: jnp.ndarray) -> jnp.ndarray:
    """Decode SSD loc predictions with prior variances (CENTER_SIZE code)."""
    pw = priors[:, 2] - priors[:, 0]
    ph_ = priors[:, 3] - priors[:, 1]
    pcx = (priors[:, 0] + priors[:, 2]) / 2
    pcy = (priors[:, 1] + priors[:, 3]) / 2
    cx = variances[:, 0] * loc[:, 0] * pw + pcx
    cy = variances[:, 1] * loc[:, 1] * ph_ + pcy
    bw = jnp.exp(variances[:, 2] * loc[:, 2]) * pw
    bh = jnp.exp(variances[:, 3] * loc[:, 3]) * ph_
    return jnp.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2], axis=1)


class DetectionOutputSSD(Module):
    """SSD post-processing: decode + per-class NMS + global top-k.
    reference: nn/DetectionOutputSSD.scala.

    Input Table(loc (1, K*4), conf (1, K*n_classes), priors Table from
    PriorBox).  Output Table(dets (keep_top_k, 6) = (class, score, x1, y1,
    x2, y2), valid mask).
    """

    def __init__(self, n_classes: int, background_label: int = 0,
                 nms_threshold: float = 0.45, nms_top_k: int = 400,
                 keep_top_k: int = 200, conf_threshold: float = 0.01,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_classes = n_classes
        self.background_label = background_label
        self.nms_threshold = nms_threshold
        self.nms_top_k = nms_top_k
        self.keep_top_k = keep_top_k
        self.conf_threshold = conf_threshold

    def apply(self, params, state, x, *, training=False, rng=None):
        loc, conf, prior_table = x[1], x[2], x[3]
        priors, variances = prior_table[1], prior_table[2]
        k = priors.shape[0]
        loc = loc.reshape(k, 4)
        conf = conf.reshape(k, self.n_classes)
        boxes = _decode_ssd(priors, variances, loc)

        all_scores, all_cls, all_box = [], [], []
        pre_k = min(self.nms_top_k, k)
        for c in range(self.n_classes):
            if c == self.background_label:
                continue
            # pre-filter by score so the IoU matrix is (nms_top_k, nms_top_k),
            # not (K, K) — K=8732 for SSD300 would be quadratic in priors
            top_s, top_i = lax.top_k(conf[:, c], pre_k)
            cand = boxes[top_i]
            idx, valid = nms(cand, top_s, self.nms_threshold, pre_k,
                             self.conf_threshold)
            all_scores.append(jnp.where(valid, top_s[idx], -jnp.inf))
            all_cls.append(jnp.full((pre_k,), c, jnp.float32))
            all_box.append(cand[idx])
        scores = jnp.concatenate(all_scores)
        classes = jnp.concatenate(all_cls)
        bxs = jnp.concatenate(all_box, axis=0)
        topk = min(self.keep_top_k, scores.shape[0])
        top_s, top_i = lax.top_k(scores, topk)
        dets = jnp.concatenate([
            classes[top_i][:, None], top_s[:, None], bxs[top_i]], axis=1)
        return Table(dets, jnp.isfinite(top_s)), state


class DetectionOutputFrcnn(Module):
    """Fast R-CNN post-processing: per-class bbox regression decode,
    per-class NMS.  reference: nn/DetectionOutputFrcnn.scala.

    Input Table(rois (R, 5), cls_prob (R, n_classes), bbox_pred
    (R, n_classes*4), im_info (2,)).  Output Table(dets (max_per_image, 6),
    valid).
    """

    def __init__(self, n_classes: int, nms_threshold: float = 0.3,
                 max_per_image: int = 100, conf_threshold: float = 0.05,
                 bbox_normalize_means: Sequence[float] = (0.0, 0.0, 0.0, 0.0),
                 bbox_normalize_stds: Sequence[float] = (0.1, 0.1, 0.2, 0.2),
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_classes = n_classes
        self.nms_threshold = nms_threshold
        self.max_per_image = max_per_image
        self.conf_threshold = conf_threshold
        self.means = jnp.asarray(bbox_normalize_means, jnp.float32)
        self.stds = jnp.asarray(bbox_normalize_stds, jnp.float32)

    def apply(self, params, state, x, *, training=False, rng=None):
        rois, cls_prob, bbox_pred, im_info = x[1], x[2], x[3], x[4]
        r = rois.shape[0]
        boxes = rois[:, 1:5]
        deltas = bbox_pred.reshape(r, self.n_classes, 4) * self.stds + self.means

        all_scores, all_cls, all_box = [], [], []
        for c in range(1, self.n_classes):  # 0 = background
            dec = bbox_transform_inv(boxes, deltas[:, c, :])
            dec = clip_boxes(dec, im_info[0], im_info[1])
            s = cls_prob[:, c]
            idx, valid = nms(dec, s, self.nms_threshold, r, self.conf_threshold)
            all_scores.append(jnp.where(valid, s[idx], -jnp.inf))
            all_cls.append(jnp.full((r,), c, jnp.float32))
            all_box.append(dec[idx])
        scores = jnp.concatenate(all_scores)
        classes = jnp.concatenate(all_cls)
        bxs = jnp.concatenate(all_box, axis=0)
        topk = min(self.max_per_image, scores.shape[0])
        top_s, top_i = lax.top_k(scores, topk)
        dets = jnp.concatenate([
            classes[top_i][:, None], top_s[:, None], bxs[top_i]], axis=1)
        return Table(dets, jnp.isfinite(top_s)), state


# ---------------------------------------------------------------------------
# SSD training loss (the trainable glue for the detection heads: the
# reference trains SSD in its model-zoo projects on top of these same
# primitives; here the matcher/criterion ships in-core so ROI-augmented
# detection training is testable end-to-end — consumes RoiImageToBatch's
# padded (B, n_max, 5) targets, vision/roi.py)


class MultiBoxCriterion:
    """SSD MultiBox loss: prior<->gt matching (bipartite force-match +
    IoU>=`overlap` soft match), (cx, cy, w, h) offset encoding with SSD
    variances, smooth-L1 localization on positives, cross-entropy
    confidence with 3:1 hard negative mining.

    `priors`: (M, 4) normalized corner boxes (e.g. concatenated PriorBox
    outputs).  Input: Table(loc (B, M, 4), conf (B, M, C)) with class 0 =
    background; target: (B, n_max, 5) rows [class, x1, y1, x2, y2],
    class −1 = padding (vision/roi.py RoiImageToBatch)."""

    def __init__(self, priors, overlap: float = 0.5,
                 neg_pos_ratio: float = 3.0,
                 variances: Tuple[float, float] = (0.1, 0.2)):
        self.priors = jnp.asarray(priors, jnp.float32).reshape(-1, 4)
        self.overlap = overlap
        self.neg_pos_ratio = neg_pos_ratio
        self.variances = variances

    def _encode(self, gt):
        p = self.priors
        pw = p[:, 2] - p[:, 0]
        ph = p[:, 3] - p[:, 1]
        pcx = p[:, 0] + 0.5 * pw
        pcy = p[:, 1] + 0.5 * ph
        gw = jnp.clip(gt[:, 2] - gt[:, 0], 1e-6)
        gh = jnp.clip(gt[:, 3] - gt[:, 1], 1e-6)
        gcx = gt[:, 0] + 0.5 * gw
        gcy = gt[:, 1] + 0.5 * gh
        v0, v1 = self.variances
        return jnp.stack([(gcx - pcx) / pw / v0, (gcy - pcy) / ph / v0,
                          jnp.log(gw / pw) / v1, jnp.log(gh / ph) / v1], 1)

    def _match(self, gt_boxes, gt_cls):
        """(n_max, 4), (n_max,) -> (labels (M,), loc_targets (M, 4),
        pos mask (M,)).  Matching follows the standard SSD assigner."""
        valid = gt_cls >= 0
        iou = bbox_iou(self.priors, gt_boxes) * valid[None, :]
        best_gt = jnp.argmax(iou, axis=1)
        best_gt_iou = jnp.max(iou, axis=1)
        # force-match: each valid gt claims its best prior.  Invalid
        # (padding) gts scatter out-of-bounds and are dropped — their
        # argmax is also index 0 and a duplicate-index write could
        # otherwise clobber a real force-match.
        best_prior = jnp.argmax(iou, axis=0)  # (n_max,)
        forced_gt = jnp.arange(gt_boxes.shape[0])
        idx = jnp.where(valid, best_prior, self.priors.shape[0])
        best_gt = best_gt.at[idx].set(forced_gt, mode="drop")
        best_gt_iou = best_gt_iou.at[idx].set(2.0, mode="drop")
        pos = best_gt_iou >= self.overlap
        labels = jnp.where(pos, gt_cls[best_gt] + 1.0, 0.0)
        loc_t = self._encode(gt_boxes[best_gt])
        return labels.astype(jnp.int32), loc_t, pos

    def forward(self, output, target):
        loc, conf = output[1], output[2]
        target = jnp.asarray(target)
        gt_boxes, gt_cls = target[..., 1:5], target[..., 0]
        labels, loc_t, pos = jax.vmap(self._match)(gt_boxes, gt_cls)
        n_pos = jnp.sum(pos, axis=1)  # (B,)

        diff = jnp.abs(loc - loc_t)
        sl1 = jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5)
        loss_loc = jnp.sum(sl1.sum(-1) * pos, axis=1)

        logp = jax.nn.log_softmax(conf, axis=-1)
        ce = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        # hard negative mining: rank background losses per image, keep
        # the top neg_pos_ratio * n_pos
        neg_ce = jnp.where(pos, -jnp.inf, ce)
        order = jnp.argsort(-neg_ce, axis=1)
        rank = jnp.argsort(order, axis=1)
        n_neg = jnp.clip(self.neg_pos_ratio * n_pos, 1,
                         pos.shape[1] - 1)[:, None]
        neg = (~pos) & (rank < n_neg)
        loss_conf = jnp.sum(ce * (pos | neg), axis=1)

        denom = jnp.clip(n_pos.astype(jnp.float32), 1.0).sum()
        return (loss_loc.sum() + loss_conf.sum()) / denom
