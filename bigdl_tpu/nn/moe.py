"""Mixture-of-Experts MLP with expert parallelism.

No reference counterpart — survey §2.10 records expert parallelism as
absent from BigDL; this is beyond-reference TPU capability (the `expert`
mesh axis declared in core/engine.py).

Design (Switch/top-k routing, fixed capacity — every shape is static so
the whole layer jits):
  * experts are STACKED on a leading E dimension (fc1 (E, D, H), ...);
    sharding them with `P('expert', ...)` over the mesh's expert axis
    makes XLA insert the dispatch/return all-to-alls — no hand-written
    collectives (vs the NCCL alltoall an MoE framework hand-codes);
  * routing is dense one-hot einsum dispatch (Switch-Transformer style):
    tokens over capacity are DROPPED (residual passes them through),
    keeping shapes static for jit;
  * the load-balance auxiliary loss enters training through the same
    custom_vjp identity the penalty layers use (nn/structural.py) — the
    trainer needs no side-loss plumbing.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module


@jax.custom_vjp
def _aux_identity(probs, penalty_grad):
    """Identity on probs whose backward adds `penalty_grad` to the
    cotangent.  The penalty gradient is an explicit ARGUMENT (not a python
    closure) so the custom_vjp stays valid inside scan/jit traces."""
    return probs


def _aux_fwd(probs, penalty_grad):
    return probs, penalty_grad


def _aux_bwd(penalty_grad, g):
    return (g + penalty_grad, None)


_aux_identity.defvjp(_aux_fwd, _aux_bwd)


class MoE(Module):
    """Top-k routed expert MLP over (..., D) activations.

    Args: hidden_size D, n_expert E, k (experts per token, 1=Switch),
    mlp_ratio (expert hidden width H = ratio*D), capacity_factor (slots per
    expert = ceil(k*T/E * factor)), aux_weight (load-balance loss scale).
    """

    def __init__(self, hidden_size: int, n_expert: int, k: int = 1,
                 mlp_ratio: int = 4, capacity_factor: float = 1.25,
                 aux_weight: float = 1e-2, dropout: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        assert 1 <= k <= n_expert
        self.hidden_size = hidden_size
        self.n_expert = n_expert
        self.k = k
        self.mlp_hidden = mlp_ratio * hidden_size
        self.capacity_factor = capacity_factor
        self.aux_weight = aux_weight
        self.dropout = dropout

    def build(self, rng, input_shape):
        d, h, e = self.hidden_size, self.mlp_hidden, self.n_expert
        ks = jax.random.split(rng, 3)
        xavier = init_mod.Xavier()
        params = {
            "router": {"weight": xavier(ks[0], (d, e), d, e)},
            "experts": {
                "fc1_w": xavier(ks[1], (e, d, h), d, h),
                "fc1_b": jnp.zeros((e, h), jnp.float32),
                "fc2_w": xavier(ks[2], (e, h, d), h, d),
                "fc2_b": jnp.zeros((e, d), jnp.float32),
            },
        }
        return params, {}, input_shape

    def capacity(self, n_tokens: int) -> int:
        return max(1, int(math.ceil(
            self.k * n_tokens / self.n_expert * self.capacity_factor)))

    def apply(self, params, state, x, *, training=False, rng=None):
        d, e, k = self.hidden_size, self.n_expert, self.k
        lead = x.shape[:-1]
        t = 1
        for s in lead:
            t *= int(s)
        xt = x.reshape(t, d)
        cap = self.capacity(t)

        logits = (xt @ params["router"]["weight"].astype(xt.dtype))
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)  # (T,E)

        # top-k choice.  k=1 gates by the RAW router probability (Switch
        # semantics: y = p_i(x) * E_i(x)) — renormalizing would make the
        # gate identically 1.0 and starve the router of task-loss gradient;
        # k>=2 renormalizes over the chosen k (top-2 semantics), where the
        # relative weights still carry gradient.
        top_vals, top_idx = jax.lax.top_k(probs, k)           # (T,k)
        if k > 1:
            top_vals = top_vals / jnp.maximum(
                jnp.sum(top_vals, -1, keepdims=True), 1e-9)

        # slot-priority position assignment: slot 0 of every token wins
        # capacity before slot 1 (standard Switch/top-2 semantics)
        onehots = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)  # (T,k,E)
        flat = jnp.swapaxes(onehots, 0, 1).reshape(k * t, e)     # slot-major
        pos_flat = jnp.cumsum(flat, axis=0) * flat - 1.0         # (k*T,E)
        pos = jnp.swapaxes(pos_flat.reshape(k, t, e), 0, 1)      # (T,k,E)
        keep = (pos >= 0) & (pos < cap)
        slot = jax.nn.one_hot(
            jnp.sum(pos * onehots, -1).astype(jnp.int32), cap,
            dtype=jnp.float32)                                   # (T,k,C)
        kept = jnp.any(keep & (onehots > 0), axis=-1)            # (T,k)

        # dispatch (T,E,C); combine weights are derived after the optional
        # aux-loss hook so the penalized probs feed the one combine einsum
        dispatch = jnp.einsum("tke,tkc->tec", onehots,
                              slot * kept[..., None])

        if training and self.aux_weight > 0.0:
            # Switch load-balance loss: E * sum_e(frac_e * P_e) where frac_e
            # is the PRE-capacity-drop top-1 routing fraction (Switch paper
            # semantics — computing it post-drop would cap the penalty at
            # capacity/T exactly when an expert is most overloaded).  frac
            # is stop-grad (argmax path); gradient flows via probs.
            frac = jax.lax.stop_gradient(jnp.mean(onehots[:, 0, :], axis=0))
            w = self.aux_weight * e / t
            # d(aux)/d(probs) with aux = w*T*sum_e(frac_e * mean_t probs)
            probs = _aux_identity(probs,
                                  jnp.broadcast_to(w * frac, probs.shape))
            top_vals = jnp.take_along_axis(probs, top_idx, axis=-1)
            if k > 1:
                top_vals = top_vals / jnp.maximum(
                    jnp.sum(top_vals, -1, keepdims=True), 1e-9)

        combine = jnp.einsum("tke,tkc->tec", onehots,
                             slot * (kept * top_vals)[..., None])

        w1 = params["experts"]["fc1_w"].astype(x.dtype)
        b1 = params["experts"]["fc1_b"].astype(x.dtype)
        w2 = params["experts"]["fc2_w"].astype(x.dtype)
        b2 = params["experts"]["fc2_b"].astype(x.dtype)
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), xt)
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", expert_in, w1)
                        + b1[:, None, :])
        if training and self.dropout > 0.0 and rng is not None:
            mask = jax.random.bernoulli(rng, 1.0 - self.dropout, h.shape)
            h = h * mask.astype(h.dtype) / (1.0 - self.dropout)
        expert_out = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
        y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
        return y.reshape(x.shape), state

    def output_shape(self, input_shape):
        return input_shape
