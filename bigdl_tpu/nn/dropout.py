"""Stochastic regularization layers.

Reference: nn/Dropout.scala (scale-at-train-time, i.e. inverted dropout),
nn/GaussianDropout.scala, nn/GaussianNoise.scala.  Randomness comes from the
`rng` threaded through `apply` (threefry keys — deterministic per step), not
from mutable generator state like the reference's per-thread mersenne
twister (utils/RandomGenerator.scala).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class Dropout(Module):
    """Inverted dropout. reference: nn/Dropout.scala."""

    def __init__(self, init_p: float = 0.5, ip: bool = False, scale: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.p = init_p
        self.scale = scale

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or self.p <= 0.0:
            return x, state
        if rng is None:
            raise ValueError("Dropout in training mode requires an rng")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, x.shape)
        y = jnp.where(mask, x, 0.0)
        if self.scale:
            y = y / keep
        return y.astype(x.dtype), state


class GaussianDropout(Module):
    """Multiplicative N(1, p/(1-p)) noise. reference: nn/GaussianDropout.scala."""

    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(name)
        self.rate = rate

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or self.rate <= 0.0:
            return x, state
        if rng is None:
            raise ValueError("GaussianDropout in training mode requires an rng")
        stddev = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + stddev * jax.random.normal(rng, x.shape, x.dtype)
        return x * noise, state


class GaussianNoise(Module):
    """Additive N(0, stddev) noise. reference: nn/GaussianNoise.scala."""

    def __init__(self, stddev: float, name: Optional[str] = None):
        super().__init__(name)
        self.stddev = stddev

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training:
            return x, state
        if rng is None:
            raise ValueError("GaussianNoise in training mode requires an rng")
        return x + self.stddev * jax.random.normal(rng, x.shape, x.dtype), state


class SpatialDropout1D(Module):
    """Drop whole channels of (N, T, C). reference: nn/SpatialDropout1D.scala."""

    def __init__(self, init_p: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.p = init_p

    _mask_axes = (1,)

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or self.p <= 0.0:
            return x, state
        if rng is None:
            raise ValueError(f"{type(self).__name__} in training needs an rng")
        shape = list(x.shape)
        for ax in self._mask_axes:
            shape[ax] = 1
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, tuple(shape))
        return (jnp.where(mask, x, 0.0) / keep).astype(x.dtype), state


class SpatialDropout2D(SpatialDropout1D):
    """Drop whole feature maps of NHWC. reference: nn/SpatialDropout2D.scala."""

    _mask_axes = (1, 2)


class SpatialDropout3D(SpatialDropout1D):
    """Drop whole volumes of NDHWC. reference: nn/SpatialDropout3D.scala."""

    _mask_axes = (1, 2, 3)


class GaussianSampler(Module):
    """Reparameterised gaussian sampling for VAEs: input Table{mean,
    log_variance} -> mean + eps * exp(0.5 * log_var), eps ~ N(0, 1).
    reference: nn/GaussianSampler.scala:29-41 (samples in both train and
    eval mode; gradients flow to both inputs via the reparameterisation)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        mean, log_var = list(x)[:2]
        if rng is None:
            raise ValueError("GaussianSampler requires an rng")
        eps = jax.random.normal(rng, mean.shape, mean.dtype)
        return mean + eps * jnp.exp(0.5 * log_var), state

    def output_shape(self, input_shape):
        return list(input_shape)[0]
