"""Embedding layers.

Reference: nn/LookupTable.scala (with maxNorm renorm + paddingValue),
nn/LookupTableSparse.scala.  A gather on TPU; XLA lowers `take` to an
efficient dynamic-gather.  Indices are 0-based (the reference is 1-based —
framework-wide convention delta, documented in module.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module


class LookupTable(Module):
    """Index -> embedding row. reference: nn/LookupTable.scala."""

    def __init__(self, n_index: int, n_output: int, padding_value: Optional[int] = None,
                 max_norm: Optional[float] = None, norm_type: float = 2.0,
                 weight_init=None, w_regularizer=None, name: Optional[str] = None):
        super().__init__(name)
        self.n_index = n_index
        self.n_output = n_output
        self.padding_value = padding_value
        self.max_norm = max_norm
        self.norm_type = norm_type
        self.weight_init = weight_init or init_mod.RandomNormal(0.0, 1.0)
        self.w_regularizer = w_regularizer  # reference: nn/LookupTable.scala

    def build(self, rng, input_shape):
        w = self.weight_init(rng, (self.n_index, self.n_output),
                             self.n_index, self.n_output)
        if self.padding_value is not None:
            w = w.at[self.padding_value].set(0.0)
        return {"weight": w}, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        # gather first, renorm only the gathered rows — O(batch*d), not O(V*d)
        y = jnp.take(params["weight"], x.astype(jnp.int32), axis=0)
        if self.max_norm is not None:
            norms = jnp.linalg.norm(y, ord=self.norm_type, axis=-1, keepdims=True)
            y = y * jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-7))
        return y, state

    def output_shape(self, input_shape):
        return tuple(input_shape) + (self.n_output,)
