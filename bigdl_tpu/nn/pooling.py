"""Pooling layers (NHWC).

Reference: nn/SpatialMaxPooling.scala, nn/SpatialAveragePooling.scala,
nn/TemporalMaxPooling.scala.  All lower to `lax.reduce_window`, which XLA
vectorizes on the VPU; no explicit index bookkeeping for the backward pass
(the reference tracks argmax indices by hand — jax.grad derives it).

BigDL pooling supports `ceilMode` (nn/SpatialMaxPooling.scala); we keep it.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Module


def _pool_out(size: int, k: int, stride: int, pad: int, ceil_mode: bool) -> int:
    if pad == -1:  # TF-style SAME: out = ceil(size / stride)
        return -(-size // stride)
    if ceil_mode:
        out = -(-(size + 2 * pad - k) // stride) + 1
        # Torch/BigDL rule: the last window may not start entirely inside the
        # right padding (otherwise it would read only pad values -> -inf/NaN)
        if (out - 1) * stride >= size + pad:
            out -= 1
        return out
    return (size + 2 * pad - k) // stride + 1


def _window_pad(size, k, stride, pad, ceil_mode):
    """Explicit (lo, hi) padding that realizes ceil/floor/SAME semantics."""
    out = _pool_out(size, k, stride, pad, ceil_mode)
    if pad == -1:  # SAME: split the deficit, extra on the high side
        needed = max(0, (out - 1) * stride + k - size)
        return (needed // 2, needed - needed // 2)
    needed = max(0, (out - 1) * stride + k - size - pad)
    return (pad, needed)


class SpatialMaxPooling(Module):
    """reference: nn/SpatialMaxPooling.scala."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None, dh: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0, ceil_mode: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.kernel = (kh, kw)
        self.stride = (dh or kh, dw or kw)
        self.pad = (pad_h, pad_w)
        self.ceil_mode = ceil_mode

    def set_ceil_mode(self) -> "SpatialMaxPooling":
        self.ceil_mode = True
        return self

    def apply(self, params, state, x, *, training=False, rng=None):
        kh, kw = self.kernel
        sh, sw = self.stride
        _, h, w, _ = x.shape
        pad_h = _window_pad(h, kh, sh, self.pad[0], self.ceil_mode)
        pad_w = _window_pad(w, kw, sw, self.pad[1], self.ceil_mode)
        # -inf (not finfo.min) so JAX recognizes the differentiable
        # reduce_window_max special case
        y = lax.reduce_window(
            x, -jnp.inf, lax.max, (1, kh, kw, 1), (1, sh, sw, 1),
            [(0, 0), pad_h, pad_w, (0, 0)])
        return y, state

    def output_shape(self, input_shape):
        n, h, w, c = input_shape
        kh, kw = self.kernel
        sh, sw = self.stride
        return (n, _pool_out(h, kh, sh, self.pad[0], self.ceil_mode),
                _pool_out(w, kw, sw, self.pad[1], self.ceil_mode), c)


class SpatialAveragePooling(Module):
    """reference: nn/SpatialAveragePooling.scala.  `count_include_pad`
    matches the reference's countIncludePad."""

    def __init__(self, kw: int, kh: int, dw: Optional[int] = None, dh: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0, ceil_mode: bool = False,
                 count_include_pad: bool = True, divide: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.kernel = (kh, kw)
        self.stride = (dh or kh, dw or kw)
        self.pad = (pad_h, pad_w)
        self.ceil_mode = ceil_mode
        self.count_include_pad = count_include_pad
        self.divide = divide

    def apply(self, params, state, x, *, training=False, rng=None):
        kh, kw = self.kernel
        sh, sw = self.stride
        _, h, w, _ = x.shape
        pad_h = _window_pad(h, kh, sh, self.pad[0], self.ceil_mode)
        pad_w = _window_pad(w, kw, sw, self.pad[1], self.ceil_mode)
        window_pad = [(0, 0), pad_h, pad_w, (0, 0)]
        summed = lax.reduce_window(x, 0.0, lax.add, (1, kh, kw, 1), (1, sh, sw, 1), window_pad)
        if not self.divide:
            return summed, state
        if self.count_include_pad:
            y = summed / (kh * kw)
        else:
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, (1, kh, kw, 1), (1, sh, sw, 1), window_pad)
            y = summed / counts
        return y, state

    def output_shape(self, input_shape):
        n, h, w, c = input_shape
        kh, kw = self.kernel
        sh, sw = self.stride
        return (n, _pool_out(h, kh, sh, self.pad[0], self.ceil_mode),
                _pool_out(w, kw, sw, self.pad[1], self.ceil_mode), c)


class TemporalMaxPooling(Module):
    """1-D max pool over (N, T, C). reference: nn/TemporalMaxPooling.scala."""

    def __init__(self, k_w: int, d_w: Optional[int] = None, name: Optional[str] = None):
        super().__init__(name)
        self.k_w = k_w
        self.d_w = d_w or k_w

    def apply(self, params, state, x, *, training=False, rng=None):
        y = lax.reduce_window(x, -jnp.inf, lax.max, (1, self.k_w, 1), (1, self.d_w, 1), "VALID")
        return y, state

    def output_shape(self, input_shape):
        n, t, c = input_shape
        return (n, (t - self.k_w) // self.d_w + 1, c)


class GlobalAveragePooling2D(Module):
    """Mean over H, W (Keras-style; reference keras/GlobalAveragePooling2D)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.mean(x, axis=(1, 2)), state

    def output_shape(self, input_shape):
        n, h, w, c = input_shape
        return (n, c)


class GlobalMaxPooling2D(Module):
    """Max over H, W (reference: keras/GlobalMaxPooling2D.scala)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.max(x, axis=(1, 2)), state

    def output_shape(self, input_shape):
        n, h, w, c = input_shape
        return (n, c)
