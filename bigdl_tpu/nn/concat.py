"""Branch-and-concat containers.

Reference: nn/Concat.scala (apply branches to one input, concatenate outputs
along a dim — the Inception building block), nn/Bottle.scala.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Container, Module, child_rng


class Concat(Container):
    """reference: nn/Concat.scala.  `dimension` is 0-based here; for NHWC
    feature-map concat use dimension=3 (the reference's NCHW dim 2)."""

    def __init__(self, dimension: int, *modules: Module, name: Optional[str] = None):
        super().__init__(name)
        self.dimension = dimension
        for m in modules:
            self.add(m)

    def build(self, rng, input_shape):
        params, state = {}, {}
        shapes = []
        for i, (key, m) in enumerate(self.children.items()):
            p, s, out = m.build(jax.random.fold_in(rng, i), input_shape)
            params[key], state[key] = p, s
            shapes.append(out)
        return params, state, self._concat_shape(shapes)

    def _concat_shape(self, shapes):
        out = list(shapes[0])
        out[self.dimension] = sum(s[self.dimension] for s in shapes)
        return tuple(out)

    def apply(self, params, state, x, *, training=False, rng=None):
        outs = []
        new_state = {}
        for i, (key, m) in enumerate(self.children.items()):
            y, new_state[key] = m.apply(params[key], state[key], x,
                                        training=training, rng=child_rng(rng, i))
            outs.append(y)
        return jnp.concatenate(outs, axis=self.dimension), new_state

    def output_shape(self, input_shape):
        return self._concat_shape([m.output_shape(input_shape) for m in self.children.values()])


class Bottle(Container):
    """Collapse leading dims, apply inner module, restore.
    reference: nn/Bottle.scala."""

    def __init__(self, module: Module, n_input_dim: int = 2, n_output_dim: int = 2,
                 name: Optional[str] = None):
        super().__init__(name)
        self.add(module)
        self.n_input_dim = n_input_dim

    def build(self, rng, input_shape):
        lead = input_shape[: len(input_shape) - self.n_input_dim + 1]
        inner_shape = (int(jnp.prod(jnp.array(lead))),) + tuple(input_shape[len(lead):])
        p, s, out = self[0].build(rng, inner_shape)
        return {"0": p}, {"0": s}, tuple(lead) + tuple(out[1:])

    def apply(self, params, state, x, *, training=False, rng=None):
        lead = x.shape[: x.ndim - self.n_input_dim + 1]
        flat = jnp.reshape(x, (-1,) + x.shape[len(lead):])
        y, s = self[0].apply(params["0"], state["0"], flat, training=training, rng=rng)
        return jnp.reshape(y, lead + y.shape[1:]), {"0": s}
