"""Forward-only TF-style operations + control flow.

Reference: nn/ops/ (71 files — `Operation` base forbids backward,
nn/ops/Operation.scala:32: compare/gather/oneHot/pad/rank/select/slice,
feature-column ops CategoricalColHashBucket/CrossCol/IndicatorCol/
Kv2Tensor/MkString) and nn/tf/ (ControlOps Switch/Merge/Enter/Exit/
NextIteration, StridedSlice).

TPU-native redesign: numeric ops are thin jnp wrappers whose outputs pass
through `lax.stop_gradient` (the functional meaning of "backward
forbidden"); TF's frame-based control flow (Scheduler/FrameManager,
nn/Scheduler.scala:36) collapses into structured `lax.cond`/
`lax.while_loop` modules, which is how XLA wants control flow expressed.
String/feature-column ops run host-side on numpy object arrays (strings
never enter XLA) with a deterministic FNV-1a hash replacing the JVM's
`##` hashing.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Module


class Operation(Module):
    """Forward-only op (reference: nn/ops/Operation.scala:32 — backward
    throws).  Outputs are wrapped in stop_gradient so `jax.grad` through a
    graph containing Operations treats them as constants, the functional
    equivalent of 'no backward'."""

    def compute(self, x: Any) -> Any:
        raise NotImplementedError(type(self).__name__)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = self.compute(x)
        if isinstance(y, Table):
            y = Table(*[lax.stop_gradient(v) for v in y])
        elif isinstance(y, (jnp.ndarray, jax.Array)):
            y = lax.stop_gradient(y)
        return y, state


def _pair(x: Any) -> Tuple[Any, Any]:
    a, b = list(x) if isinstance(x, Table) else x
    return a, b


# ---------------------------------------------------------------------------
# comparison / logical (reference: nn/ops/{Equal,Greater,...}.scala)
# ---------------------------------------------------------------------------


class Equal(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.equal(a, b)


class NotEqual(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.not_equal(a, b)


class Greater(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.greater(a, b)


class GreaterEqual(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.greater_equal(a, b)


class Less(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.less(a, b)


class LessEqual(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.less_equal(a, b)


class LogicalAnd(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.logical_and(a, b)


class LogicalOr(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.logical_or(a, b)


class LogicalNot(Operation):
    def compute(self, x):
        return jnp.logical_not(x)


class All(Operation):
    def __init__(self, axis: Optional[int] = None, keep_dims: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.axis, self.keep_dims = axis, keep_dims

    def compute(self, x):
        return jnp.all(x, axis=self.axis, keepdims=self.keep_dims)


class Any(Operation):
    def __init__(self, axis: Optional[int] = None, keep_dims: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.axis, self.keep_dims = axis, keep_dims

    def compute(self, x):
        return jnp.any(x, axis=self.axis, keepdims=self.keep_dims)


# ---------------------------------------------------------------------------
# structural (reference: nn/ops/{Gather,OneHot,Pad,Rank,Select,Slice,...})
# ---------------------------------------------------------------------------


class Gather(Operation):
    """Gather rows along `axis` by integer indices; input Table(params, ids)."""

    def __init__(self, axis: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def compute(self, x):
        table, idx = _pair(x)
        return jnp.take(table, idx.astype(jnp.int32), axis=self.axis)


class OneHot(Operation):
    def __init__(self, depth: int, on_value: float = 1.0, off_value: float = 0.0,
                 axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.depth, self.on, self.off, self.axis = depth, on_value, off_value, axis

    def compute(self, x):
        oh = jax.nn.one_hot(x.astype(jnp.int32), self.depth, axis=self.axis)
        return oh * (self.on - self.off) + self.off


class Pad(Operation):
    def __init__(self, paddings: Sequence[Tuple[int, int]], value: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.paddings = [tuple(p) for p in paddings]
        self.value = value

    def compute(self, x):
        return jnp.pad(x, self.paddings, constant_values=self.value)


class Rank(Operation):
    def compute(self, x):
        return jnp.asarray(x.ndim, jnp.int32)


class ShapeOp(Operation):
    def compute(self, x):
        return jnp.asarray(x.shape, jnp.int32)


class SelectOp(Operation):
    """Elementwise where(cond, then, else); input Table(cond, t, e)
    (reference: nn/ops/Select.scala)."""

    def compute(self, x):
        cond, t, e = list(x)
        return jnp.where(cond, t, e)


class Slice(Operation):
    def __init__(self, begin: Sequence[int], size: Sequence[int],
                 name: Optional[str] = None):
        super().__init__(name)
        self.begin, self.size = list(begin), list(size)

    def compute(self, x):
        sizes = [dim - b if s == -1 else s
                 for b, s, dim in zip(self.begin, self.size, x.shape)]
        return lax.slice(x, self.begin, [b + s for b, s in zip(self.begin, sizes)])


class StridedSlice(Operation):
    """reference: nn/tf/StridedSlice.scala — python slice semantics."""

    def __init__(self, slices: Sequence[Tuple[Optional[int], Optional[int], int]],
                 name: Optional[str] = None):
        super().__init__(name)
        self.slices = [tuple(s) for s in slices]

    def compute(self, x):
        return x[tuple(slice(*s) for s in self.slices)]


class Tile(Operation):
    def __init__(self, multiples: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.multiples = list(multiples)

    def compute(self, x):
        return jnp.tile(x, self.multiples)


class ArgMax(Operation):
    def __init__(self, axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def compute(self, x):
        return jnp.argmax(x, axis=self.axis)


class Cast(Operation):
    def __init__(self, dtype: str, name: Optional[str] = None):
        super().__init__(name)
        self.dtype = dtype

    def compute(self, x):
        return x.astype(jnp.dtype(self.dtype))


class TopK(Operation):
    def __init__(self, k: int, name: Optional[str] = None):
        super().__init__(name)
        self.k = k

    def compute(self, x):
        values, indices = lax.top_k(x, self.k)
        return Table(values, indices)


class InTopK(Operation):
    def __init__(self, k: int, name: Optional[str] = None):
        super().__init__(name)
        self.k = k

    def compute(self, x):
        predictions, targets = _pair(x)
        _, top = lax.top_k(predictions, self.k)
        return jnp.any(top == targets[:, None].astype(top.dtype), axis=-1)


class Sign(Operation):
    def compute(self, x):
        return jnp.sign(x)


class Mod(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.mod(a, b)


class FloorDiv(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.floor_divide(a, b)


class Maximum(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.maximum(a, b)


class Minimum(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.minimum(a, b)


class SquaredDifference(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.square(a - b)


class RandomUniformOp(Operation):
    """reference: nn/ops/RandomUniform.scala."""

    def __init__(self, minval: float = 0.0, maxval: float = 1.0, seed: int = 0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.minval, self.maxval, self.seed = minval, maxval, seed
        self._count = 0

    def apply(self, params, state, x, *, training=False, rng=None):
        if rng is None:
            rng = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._count)
            self._count += 1
        shape = tuple(np.asarray(x).tolist()) if not hasattr(x, "shape") or x.ndim == 1 \
            else tuple(x.shape)
        y = jax.random.uniform(rng, shape, jnp.float32, self.minval, self.maxval)
        return lax.stop_gradient(y), state


# ---------------------------------------------------------------------------
# control flow (reference: nn/tf/ControlOps.scala Switch/Merge/Enter/Exit ->
# structured lax control flow)
# ---------------------------------------------------------------------------


class Cond(Module):
    """Run `then_module` or `else_module` on the data input depending on a
    scalar boolean — Switch+Merge collapsed into `lax.cond`.  Input:
    Table(pred, data)."""

    _constructor_children = True

    def __init__(self, then_module: Module, else_module: Module,
                 name: Optional[str] = None):
        super().__init__(name)
        self.then_module = then_module
        self.else_module = else_module

    def build(self, rng, input_shape):
        pred_shape, data_shape = list(input_shape)
        k1, k2 = jax.random.split(rng)
        p_then, s_then, out = self.then_module.build(k1, data_shape)
        p_else, s_else, _ = self.else_module.build(k2, data_shape)
        return ({"then": p_then, "else": p_else},
                {"then": s_then, "else": s_else}, out)

    def apply(self, params, state, x, *, training=False, rng=None):
        pred, data = _pair(x)
        out = lax.cond(
            jnp.asarray(pred).reshape(()),
            lambda d: self.then_module.apply(params["then"], state["then"], d,
                                             training=training, rng=rng)[0],
            lambda d: self.else_module.apply(params["else"], state["else"], d,
                                             training=training, rng=rng)[0],
            data)
        return out, state


class WhileLoop(Module):
    """Repeat `body` while `cond_fn(x)` holds — Enter/Exit/NextIteration
    frames collapsed into `lax.while_loop`.  `body` must be shape-
    preserving (the TF loop-invariant requirement, enforced by XLA)."""

    _constructor_children = True

    def __init__(self, body: Module, cond_fn: Callable[[Any], Any],
                 max_iterations: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.body = body
        self.cond_fn = cond_fn
        self.max_iterations = max_iterations

    def build(self, rng, input_shape):
        p, s, out = self.body.build(rng, input_shape)
        return {"body": p}, {"body": s}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        limit = self.max_iterations

        def cond(carry):
            i, v = carry
            ok = jnp.asarray(self.cond_fn(v)).reshape(())
            if limit is not None:
                ok = jnp.logical_and(ok, i < limit)
            return ok

        def body(carry):
            i, v = carry
            out, _ = self.body.apply(params["body"], state["body"], v,
                                     training=training, rng=rng)
            return i + 1, out

        _, out = lax.while_loop(cond, body, (jnp.asarray(0), x))
        return out, state


# ---------------------------------------------------------------------------
# feature-column ops (host-side, numpy object/string arrays)
# reference: nn/ops/{CategoricalColHashBucket,CrossCol,IndicatorCol,
# Kv2Tensor,MkString}.scala
# ---------------------------------------------------------------------------


def fnv1a(s: str) -> int:
    h = 0xCBF29CE484222325
    for b in s.encode():
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class CategoricalColHashBucket(Operation):
    """String column -> stable hash bucket ids."""

    def __init__(self, hash_bucket_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.hash_bucket_size = hash_bucket_size

    def compute(self, x):
        flat = np.asarray(x, dtype=object).reshape(-1)
        ids = np.asarray([fnv1a(str(v)) % self.hash_bucket_size for v in flat],
                         np.int32)
        return jnp.asarray(ids.reshape(np.asarray(x, dtype=object).shape))


class CrossCol(Operation):
    """Cross N string columns -> hashed bucket of the joined key."""

    def __init__(self, hash_bucket_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.hash_bucket_size = hash_bucket_size

    def compute(self, x):
        cols = [np.asarray(c, dtype=object).reshape(-1) for c in x]
        n = len(cols[0])
        ids = np.asarray(
            [fnv1a("_X_".join(str(c[i]) for c in cols)) % self.hash_bucket_size
             for i in range(n)], np.int32)
        return jnp.asarray(ids)


class IndicatorCol(Operation):
    """Categorical indices -> multi-hot indicator vector."""

    def __init__(self, feature_num: int, name: Optional[str] = None):
        super().__init__(name)
        self.feature_num = feature_num

    def compute(self, x):
        idx = jnp.asarray(x).astype(jnp.int32)
        if idx.ndim == 1:
            idx = idx[:, None]
        oh = jax.nn.one_hot(idx, self.feature_num)
        return jnp.clip(oh.sum(axis=-2), 0.0, 1.0)


class Kv2Tensor(Operation):
    """Parse "k:v,k:v" strings into dense rows (host-side)."""

    def __init__(self, kv_delimiter: str = ",", item_delimiter: str = ":",
                 feature_num: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.kv_delimiter = kv_delimiter
        self.item_delimiter = item_delimiter
        self.feature_num = feature_num

    def compute(self, x):
        rows = np.asarray(x, dtype=object).reshape(-1)
        out = np.zeros((len(rows), self.feature_num), np.float32)
        for i, row in enumerate(rows):
            for item in str(row).split(self.kv_delimiter):
                if not item:
                    continue
                k, v = item.split(self.item_delimiter)
                out[i, int(k)] = float(v)
        return jnp.asarray(out)


class MkString(Operation):
    """Join numeric rows into delimiter-separated strings (host-side)."""

    def __init__(self, str_delimiter: str = ",", name: Optional[str] = None):
        super().__init__(name)
        self.str_delimiter = str_delimiter

    def compute(self, x):
        arr = np.asarray(x)
        def fmt(v):
            f = float(v)
            return str(int(f)) if f.is_integer() else str(f)
        return np.asarray(
            [self.str_delimiter.join(fmt(v) for v in row) for row in arr],
            dtype=object)
