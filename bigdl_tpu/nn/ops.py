"""Forward-only TF-style operations + control flow.

Reference: nn/ops/ (71 files — `Operation` base forbids backward,
nn/ops/Operation.scala:32: compare/gather/oneHot/pad/rank/select/slice,
feature-column ops CategoricalColHashBucket/CrossCol/IndicatorCol/
Kv2Tensor/MkString) and nn/tf/ (ControlOps Switch/Merge/Enter/Exit/
NextIteration, StridedSlice).

TPU-native redesign: numeric ops are thin jnp wrappers whose outputs pass
through `lax.stop_gradient` (the functional meaning of "backward
forbidden"); TF's frame-based control flow (Scheduler/FrameManager,
nn/Scheduler.scala:36) collapses into structured `lax.cond`/
`lax.while_loop` modules, which is how XLA wants control flow expressed.
String/feature-column ops run host-side on numpy object arrays (strings
never enter XLA) with a deterministic FNV-1a hash replacing the JVM's
`##` hashing.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Module


class Operation(Module):
    """Forward-only op (reference: nn/ops/Operation.scala:32 — backward
    throws).  Outputs are wrapped in stop_gradient so `jax.grad` through a
    graph containing Operations treats them as constants, the functional
    equivalent of 'no backward'."""

    def compute(self, x: Any) -> Any:
        raise NotImplementedError(type(self).__name__)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = self.compute(x)
        if isinstance(y, Table):
            y = Table(*[lax.stop_gradient(v) for v in y])
        elif isinstance(y, (jnp.ndarray, jax.Array)):
            y = lax.stop_gradient(y)
        return y, state


def _pair(x: Any) -> Tuple[Any, Any]:
    a, b = list(x) if isinstance(x, Table) else x
    return a, b


# ---------------------------------------------------------------------------
# comparison / logical (reference: nn/ops/{Equal,Greater,...}.scala)
# ---------------------------------------------------------------------------


class Equal(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.equal(a, b)


class NotEqual(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.not_equal(a, b)


class Greater(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.greater(a, b)


class GreaterEqual(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.greater_equal(a, b)


class Less(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.less(a, b)


class LessEqual(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.less_equal(a, b)


class LogicalAnd(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.logical_and(a, b)


class LogicalOr(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.logical_or(a, b)


class LogicalNot(Operation):
    def compute(self, x):
        return jnp.logical_not(x)


class All(Operation):
    def __init__(self, axis: Optional[int] = None, keep_dims: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.axis, self.keep_dims = axis, keep_dims

    def compute(self, x):
        return jnp.all(x, axis=self.axis, keepdims=self.keep_dims)


class Any(Operation):
    def __init__(self, axis: Optional[int] = None, keep_dims: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.axis, self.keep_dims = axis, keep_dims

    def compute(self, x):
        return jnp.any(x, axis=self.axis, keepdims=self.keep_dims)


# ---------------------------------------------------------------------------
# structural (reference: nn/ops/{Gather,OneHot,Pad,Rank,Select,Slice,...})
# ---------------------------------------------------------------------------


class Gather(Operation):
    """Gather rows along `axis` by integer indices; input Table(params, ids)."""

    def __init__(self, axis: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def compute(self, x):
        table, idx = _pair(x)
        return jnp.take(table, idx.astype(jnp.int32), axis=self.axis)


class OneHot(Operation):
    def __init__(self, depth: int, on_value: float = 1.0, off_value: float = 0.0,
                 axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.depth, self.on, self.off, self.axis = depth, on_value, off_value, axis

    def compute(self, x):
        oh = jax.nn.one_hot(x.astype(jnp.int32), self.depth, axis=self.axis)
        return oh * (self.on - self.off) + self.off


class Pad(Operation):
    def __init__(self, paddings: Sequence[Tuple[int, int]], value: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.paddings = [tuple(p) for p in paddings]
        self.value = value

    def compute(self, x):
        return jnp.pad(x, self.paddings, constant_values=self.value)


class Rank(Operation):
    def compute(self, x):
        return jnp.asarray(x.ndim, jnp.int32)


class ShapeOp(Operation):
    def compute(self, x):
        return jnp.asarray(x.shape, jnp.int32)


class SelectOp(Operation):
    """Elementwise where(cond, then, else); input Table(cond, t, e)
    (reference: nn/ops/Select.scala)."""

    def compute(self, x):
        cond, t, e = list(x)
        return jnp.where(cond, t, e)


class Slice(Operation):
    def __init__(self, begin: Sequence[int], size: Sequence[int],
                 name: Optional[str] = None):
        super().__init__(name)
        self.begin, self.size = list(begin), list(size)

    def compute(self, x):
        sizes = [dim - b if s == -1 else s
                 for b, s, dim in zip(self.begin, self.size, x.shape)]
        return lax.slice(x, self.begin, [b + s for b, s in zip(self.begin, sizes)])

    def output_shape(self, input_shape):
        return tuple(dim - b if s == -1 else s
                     for b, s, dim in zip(self.begin, self.size, input_shape))


class StridedSlice(Operation):
    """reference: nn/tf/StridedSlice.scala — python slice semantics."""

    def __init__(self, slices: Sequence[Tuple[Optional[int], Optional[int], int]],
                 name: Optional[str] = None):
        super().__init__(name)
        self.slices = [tuple(s) for s in slices]

    def compute(self, x):
        return x[tuple(slice(*s) for s in self.slices)]

    def output_shape(self, input_shape):
        out = []
        for dim, s in zip(input_shape, self.slices):
            out.append(len(range(*slice(*s).indices(dim))))
        return tuple(out) + tuple(input_shape[len(self.slices):])


class Tile(Operation):
    def __init__(self, multiples: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.multiples = list(multiples)

    def compute(self, x):
        return jnp.tile(x, self.multiples)

    def output_shape(self, input_shape):
        n = max(len(input_shape), len(self.multiples))
        s = [1] * (n - len(input_shape)) + list(input_shape)
        m = [1] * (n - len(self.multiples)) + self.multiples
        return tuple(d * r for d, r in zip(s, m))


class ArgMax(Operation):
    def __init__(self, axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def compute(self, x):
        return jnp.argmax(x, axis=self.axis)


class Cast(Operation):
    def __init__(self, dtype: str, name: Optional[str] = None):
        super().__init__(name)
        self.dtype = dtype

    def compute(self, x):
        return x.astype(jnp.dtype(self.dtype))


class TopK(Operation):
    def __init__(self, k: int, name: Optional[str] = None):
        super().__init__(name)
        self.k = k

    def compute(self, x):
        values, indices = lax.top_k(x, self.k)
        return Table(values, indices)


class InTopK(Operation):
    def __init__(self, k: int, name: Optional[str] = None):
        super().__init__(name)
        self.k = k

    def compute(self, x):
        predictions, targets = _pair(x)
        _, top = lax.top_k(predictions, self.k)
        return jnp.any(top == targets[:, None].astype(top.dtype), axis=-1)


class Sign(Operation):
    def compute(self, x):
        return jnp.sign(x)


class Mod(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.mod(a, b)


class FloorDiv(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.floor_divide(a, b)


class Maximum(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.maximum(a, b)


class Minimum(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.minimum(a, b)


class SquaredDifference(Operation):
    def compute(self, x):
        a, b = _pair(x)
        return jnp.square(a - b)


class RandomUniformOp(Operation):
    """reference: nn/ops/RandomUniform.scala."""

    def __init__(self, minval: float = 0.0, maxval: float = 1.0, seed: int = 0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.minval, self.maxval, self.seed = minval, maxval, seed
        self._count = 0

    def apply(self, params, state, x, *, training=False, rng=None):
        if rng is None:
            rng = jax.random.fold_in(jax.random.PRNGKey(self.seed), self._count)
            self._count += 1
        shape = tuple(np.asarray(x).tolist()) if not hasattr(x, "shape") or x.ndim == 1 \
            else tuple(x.shape)
        y = jax.random.uniform(rng, shape, jnp.float32, self.minval, self.maxval)
        return lax.stop_gradient(y), state


# ---------------------------------------------------------------------------
# control flow (reference: nn/tf/ControlOps.scala Switch/Merge/Enter/Exit ->
# structured lax control flow)
# ---------------------------------------------------------------------------


class Cond(Module):
    """Run `then_module` or `else_module` on the data input depending on a
    scalar boolean — Switch+Merge collapsed into `lax.cond`.  Input:
    Table(pred, data)."""

    _constructor_children = True

    def __init__(self, then_module: Module, else_module: Module,
                 name: Optional[str] = None):
        super().__init__(name)
        self.then_module = then_module
        self.else_module = else_module

    def build(self, rng, input_shape):
        pred_shape, data_shape = list(input_shape)
        k1, k2 = jax.random.split(rng)
        p_then, s_then, out = self.then_module.build(k1, data_shape)
        p_else, s_else, _ = self.else_module.build(k2, data_shape)
        return ({"then": p_then, "else": p_else},
                {"then": s_then, "else": s_else}, out)

    def apply(self, params, state, x, *, training=False, rng=None):
        pred, data = _pair(x)
        out = lax.cond(
            jnp.asarray(pred).reshape(()),
            lambda d: self.then_module.apply(params["then"], state["then"], d,
                                             training=training, rng=rng)[0],
            lambda d: self.else_module.apply(params["else"], state["else"], d,
                                             training=training, rng=rng)[0],
            data)
        return out, state


class WhileLoop(Module):
    """Repeat `body` while `cond_fn(x)` holds — Enter/Exit/NextIteration
    frames collapsed into `lax.while_loop`.  `body` must be shape-
    preserving (the TF loop-invariant requirement, enforced by XLA)."""

    _constructor_children = True

    def __init__(self, body: Module, cond_fn: Callable[[Any], Any],
                 max_iterations: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.body = body
        self.cond_fn = cond_fn
        self.max_iterations = max_iterations

    def build(self, rng, input_shape):
        p, s, out = self.body.build(rng, input_shape)
        return {"body": p}, {"body": s}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        limit = self.max_iterations

        def cond(carry):
            i, v = carry
            ok = jnp.asarray(self.cond_fn(v)).reshape(())
            if limit is not None:
                ok = jnp.logical_and(ok, i < limit)
            return ok

        def body(carry):
            i, v = carry
            out, _ = self.body.apply(params["body"], state["body"], v,
                                     training=training, rng=rng)
            return i + 1, out

        _, out = lax.while_loop(cond, body, (jnp.asarray(0), x))
        return out, state


# ---------------------------------------------------------------------------
# feature-column ops (host-side, numpy object/string arrays)
# reference: nn/ops/{CategoricalColHashBucket,CrossCol,IndicatorCol,
# Kv2Tensor,MkString}.scala
# ---------------------------------------------------------------------------


def fnv1a(s: str) -> int:
    h = 0xCBF29CE484222325
    for b in s.encode():
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


class CategoricalColHashBucket(Operation):
    """String column -> stable hash bucket ids."""

    def __init__(self, hash_bucket_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.hash_bucket_size = hash_bucket_size

    def compute(self, x):
        flat = np.asarray(x, dtype=object).reshape(-1)
        ids = np.asarray([fnv1a(str(v)) % self.hash_bucket_size for v in flat],
                         np.int32)
        return jnp.asarray(ids.reshape(np.asarray(x, dtype=object).shape))


class CrossCol(Operation):
    """Cross N string columns -> hashed bucket of the joined key."""

    def __init__(self, hash_bucket_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.hash_bucket_size = hash_bucket_size

    def compute(self, x):
        cols = [np.asarray(c, dtype=object).reshape(-1) for c in x]
        n = len(cols[0])
        ids = np.asarray(
            [fnv1a("_X_".join(str(c[i]) for c in cols)) % self.hash_bucket_size
             for i in range(n)], np.int32)
        return jnp.asarray(ids)


class IndicatorCol(Operation):
    """Categorical indices -> multi-hot indicator vector."""

    def __init__(self, feature_num: int, name: Optional[str] = None):
        super().__init__(name)
        self.feature_num = feature_num

    def compute(self, x):
        idx = jnp.asarray(x).astype(jnp.int32)
        if idx.ndim == 1:
            idx = idx[:, None]
        oh = jax.nn.one_hot(idx, self.feature_num)
        return jnp.clip(oh.sum(axis=-2), 0.0, 1.0)


class Kv2Tensor(Operation):
    """Parse "k:v,k:v" strings into dense rows (host-side)."""

    def __init__(self, kv_delimiter: str = ",", item_delimiter: str = ":",
                 feature_num: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.kv_delimiter = kv_delimiter
        self.item_delimiter = item_delimiter
        self.feature_num = feature_num

    def compute(self, x):
        rows = np.asarray(x, dtype=object).reshape(-1)
        out = np.zeros((len(rows), self.feature_num), np.float32)
        for i, row in enumerate(rows):
            for item in str(row).split(self.kv_delimiter):
                if not item:
                    continue
                k, v = item.split(self.item_delimiter)
                out[i, int(k)] = float(v)
        return jnp.asarray(out)


class MkString(Operation):
    """Join numeric rows into delimiter-separated strings (host-side)."""

    def __init__(self, str_delimiter: str = ",", name: Optional[str] = None):
        super().__init__(name)
        self.str_delimiter = str_delimiter

    def compute(self, x):
        arr = np.asarray(x)
        def fmt(v):
            f = float(v)
            return str(int(f)) if f.is_integer() else str(f)
        return np.asarray(
            [self.str_delimiter.join(fmt(v) for v in row) for row in arr],
            dtype=object)


# ---------------------------------------------------------------------------
# elementwise math op zoo (reference: nn/ops/{Floor,Round,Erf,...}.scala —
# thin forward-only wrappers used by loaded TF graphs)
# ---------------------------------------------------------------------------


class _Elementwise(Operation):
    _fn: Callable = None

    def compute(self, x):
        return type(self)._fn(jnp.asarray(x))


class Floor(_Elementwise):
    """reference: nn/ops/Floor.scala."""
    _fn = staticmethod(jnp.floor)


class Rint(_Elementwise):
    """Round to nearest even integer. reference: nn/ops/Rint.scala."""
    _fn = staticmethod(jnp.rint)


class Round(_Elementwise):
    """reference: nn/ops/Round.scala (TF Round = half-to-even)."""
    _fn = staticmethod(jnp.rint)


class Erf(_Elementwise):
    """reference: nn/ops/Erf.scala."""
    _fn = staticmethod(jax.scipy.special.erf)


class Erfc(_Elementwise):
    """reference: nn/ops/Erfc.scala."""
    _fn = staticmethod(jax.scipy.special.erfc)


class Expm1(_Elementwise):
    """reference: nn/ops/Expm1.scala."""
    _fn = staticmethod(jnp.expm1)


class Digamma(_Elementwise):
    """reference: nn/ops/Digamma.scala."""
    _fn = staticmethod(jax.scipy.special.digamma)


class Lgamma(_Elementwise):
    """reference: nn/ops/Lgamma.scala."""
    _fn = staticmethod(jax.scipy.special.gammaln)


class IsFinite(_Elementwise):
    """reference: nn/ops/IsFinite.scala."""
    _fn = staticmethod(jnp.isfinite)


class IsInf(_Elementwise):
    """reference: nn/ops/IsInf.scala."""
    _fn = staticmethod(jnp.isinf)


class IsNan(_Elementwise):
    """reference: nn/ops/IsNan.scala."""
    _fn = staticmethod(jnp.isnan)


class Ceil(_Elementwise):
    """reference: utils/tf/loaders/Ceil.scala."""
    _fn = staticmethod(jnp.ceil)


class TruncateMod(Operation):
    """C-style remainder (sign follows dividend) — TF TruncateMod.
    reference: utils/tf/loaders/TruncateMod.scala."""

    def compute(self, x):
        a, b = _pair(x)
        a, b = jnp.asarray(a), jnp.asarray(b)
        return a - b * (jnp.sign(a) * jnp.sign(b) *
                        (jnp.abs(a) // jnp.abs(b))).astype(a.dtype)


class Pack(Operation):
    """Stack N inputs on a new `axis` (TF Pack/stack).
    reference: utils/tf/loaders/Pack.scala -> nn/ops (Stack)."""

    def __init__(self, axis: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def compute(self, x):
        parts = [jnp.asarray(v) for v in (list(x) if isinstance(x, Table)
                                          else [x])]
        return jnp.stack(parts, axis=self.axis)


class UnpackSelect(Operation):
    """Output k of TF Unpack (unstack): take index k along `axis` and drop
    the axis. reference: utils/tf/loaders/Unpack.scala."""

    def __init__(self, axis: int, index: int, name: Optional[str] = None):
        super().__init__(name)
        self.axis, self.index = axis, index

    def compute(self, x):
        return jnp.take(jnp.asarray(x), self.index, axis=self.axis)


class SoftmaxGradOp(Operation):
    """Second output of SoftmaxCrossEntropyWithLogits: softmax(logits) -
    labels (the backprop tensor TF materializes).
    reference: utils/tf/loaders/SoftmaxCrossEntropyWithLogits.scala."""

    def compute(self, x):
        logits, labels = _pair(x)
        return jax.nn.softmax(jnp.asarray(logits), axis=-1) - jnp.asarray(labels)


class Pow(Operation):
    """{base, exponent} -> base ** exponent. reference: nn/ops/Pow.scala."""

    def compute(self, x):
        a, b = _pair(x)
        return jnp.power(jnp.asarray(a), jnp.asarray(b))


class FloorMod(Operation):
    """Python/TF-style modulo (sign follows divisor).
    reference: nn/ops/FloorMod.scala."""

    def compute(self, x):
        a, b = _pair(x)
        return jnp.mod(jnp.asarray(a), jnp.asarray(b))


class TruncateDiv(Operation):
    """Integer division truncating toward zero.
    reference: nn/ops/TruncateDiv.scala."""

    def compute(self, x):
        a, b = _pair(x)
        a, b = jnp.asarray(a), jnp.asarray(b)
        return (jnp.sign(a) * jnp.sign(b) *
                (jnp.abs(a) // jnp.abs(b))).astype(a.dtype)


class ApproximateEqual(Operation):
    """|a - b| < tolerance. reference: nn/ops/ApproximateEqual.scala."""

    def __init__(self, tolerance: float = 1e-5, name: Optional[str] = None):
        super().__init__(name)
        self.tolerance = tolerance

    def compute(self, x):
        a, b = _pair(x)
        return jnp.abs(jnp.asarray(a) - jnp.asarray(b)) < self.tolerance


class Prod(Operation):
    """Product along an axis. reference: nn/ops/Prod.scala (1-based axis in
    the reference; 0-based here like the rest of the port)."""

    def __init__(self, axis: int = 0, keep_dims: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis
        self.keep_dims = keep_dims

    def compute(self, x):
        return jnp.prod(jnp.asarray(x), axis=self.axis,
                        keepdims=self.keep_dims)


class RangeOps(Operation):
    """{start, limit, delta} -> arange. reference: nn/ops/RangeOps.scala.
    Host-side (shape depends on values, so it cannot live under jit)."""

    def compute(self, x):
        start, limit, delta = [np.asarray(v).item() for v in list(x)]
        return jnp.arange(start, limit, delta)


class L2Loss(Operation):
    """sum(x^2) / 2. reference: nn/ops/L2Loss.scala."""

    def compute(self, x):
        return jnp.sum(jnp.square(jnp.asarray(x))) / 2.0


class BatchMatMul(Operation):
    """Batched matmul with optional adjoints.
    reference: nn/ops/BatchMatMul.scala."""

    def __init__(self, adj_x: bool = False, adj_y: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.adj_x, self.adj_y = adj_x, adj_y

    def compute(self, x):
        a, b = _pair(x)
        if self.adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if self.adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b


class SegmentSum(Operation):
    """{data, segment_ids} -> per-segment sums over axis 0.
    reference: nn/ops/SegmentSum.scala:25-50 (ids sorted ascending; output
    rows = last id + 1).  Uses jax segment_sum (one scatter-add on device);
    num_segments read from the ids (host trip, like the reference)."""

    def compute(self, x):
        data, ids = _pair(x)
        ids = jnp.asarray(ids, jnp.int32)
        num = int(ids[-1]) + 1
        return jax.ops.segment_sum(jnp.asarray(data), ids, num_segments=num)


class TruncatedNormal(Operation):
    """Sample from a truncated normal (±2 sigma) of the given shape.
    reference: nn/ops/TruncatedNormal.scala (shape arrives as the input
    tensor, mean/stddev are constructor args)."""

    def __init__(self, mean: float = 0.0, stddev: float = 1.0, seed: int = 0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.mean, self.stddev, self.seed = mean, stddev, seed

    def apply(self, params, state, x, *, training=False, rng=None):
        shape = tuple(np.asarray(x).astype(int).tolist())
        if rng is None:  # seeded fallback; step rng gives fresh draws
            rng = jax.random.PRNGKey(self.seed)
        z = jax.random.truncated_normal(rng, -2.0, 2.0, shape)
        return lax.stop_gradient(z * self.stddev + self.mean), state


class CrossEntropyOp(Operation):
    """{logits, one-hot labels} -> per-sample softmax cross entropy.
    reference: nn/ops/CrossEntropy.scala (the forward-only TF op, distinct
    from the trainable CrossEntropyCriterion)."""

    def compute(self, x):
        logits, labels = _pair(x)
        logp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
        return -jnp.sum(jnp.asarray(labels) * logp, axis=-1)


class DepthwiseConv2DOp(Operation):
    """Forward-only depthwise conv (TF DepthwiseConv2dNative): input
    {x NHWC, filter (kh, kw, C, multiplier)}.
    reference: nn/ops/DepthwiseConv2D.scala."""

    def __init__(self, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = -1, pad_h: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)

    def compute(self, x):
        inp, filt = _pair(x)
        kh, kw, c, mult = filt.shape
        w = jnp.reshape(filt, (kh, kw, 1, c * mult))
        pad = ("SAME" if self.pad == (-1, -1)
               else [(self.pad[0], self.pad[0]), (self.pad[1], self.pad[1])])
        return lax.conv_general_dilated(
            inp, w, self.stride, pad,
            dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c)


class Dilation2D(Operation):
    """Greyscale morphological dilation: {x NHWC, filter (kh, kw, C)}.
    out[b,y,x,c] = max_{dy,dx} (x[b, y*s+dy*r, x*s+dx*r, c] + filter[dy,dx,c]).
    reference: nn/ops/Dilation2D.scala.  Realised as a max-plus
    reduce_window per filter tap (XLA fuses the unrolled taps)."""

    def __init__(self, strides: Sequence[int] = (1, 1, 1, 1),
                 rates: Sequence[int] = (1, 1, 1, 1),
                 padding: str = "SAME", name: Optional[str] = None):
        super().__init__(name)
        self.strides = tuple(strides)
        self.rates = tuple(rates)
        self.padding = padding.upper()

    def compute(self, x):
        inp, filt = _pair(x)
        kh, kw, _ = filt.shape
        sh, sw = self.strides[1], self.strides[2]
        rh, rw = self.rates[1], self.rates[2]
        eff_h, eff_w = (kh - 1) * rh + 1, (kw - 1) * rw + 1
        if self.padding == "SAME":
            ph = max(0, (-(-inp.shape[1] // sh) - 1) * sh + eff_h - inp.shape[1])
            pw = max(0, (-(-inp.shape[2] // sw) - 1) * sw + eff_w - inp.shape[2])
            pads = [(0, 0), (ph // 2, ph - ph // 2), (pw // 2, pw - pw // 2), (0, 0)]
        else:
            pads = [(0, 0)] * 4
        padded = jnp.pad(inp, pads, constant_values=-jnp.inf)
        oh = (padded.shape[1] - eff_h) // sh + 1
        ow = (padded.shape[2] - eff_w) // sw + 1
        out = jnp.full((inp.shape[0], oh, ow, inp.shape[3]), -jnp.inf, inp.dtype)
        for dy in range(kh):
            for dx in range(kw):
                win = lax.slice(
                    padded, (0, dy * rh, dx * rw, 0),
                    (padded.shape[0], dy * rh + (oh - 1) * sh + 1,
                     dx * rw + (ow - 1) * sw + 1, padded.shape[3]),
                    (1, sh, sw, 1))
                out = jnp.maximum(out, win + filt[dy, dx])
        return out


class ResizeBilinearOp(Operation):
    """Forward-only resize (TF ResizeBilinear op).
    reference: nn/ops/ResizeBilinear.scala — wraps the nn layer."""

    def __init__(self, align_corners: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.align_corners = align_corners

    def compute(self, x):
        from bigdl_tpu.nn.structural import ResizeBilinear as _RB
        inp, size = _pair(x)
        oh, ow = [int(v) for v in np.asarray(size).tolist()]
        y, _ = _RB(oh, ow, self.align_corners).apply({}, {}, inp)
        return y


class BucketizedCol(Operation):
    """Numeric column -> bucket index per `boundaries` (TF
    bucketized_column).  reference: nn/ops/BucketizedCol.scala."""

    def __init__(self, boundaries: Sequence[float], name: Optional[str] = None):
        super().__init__(name)
        self.boundaries = jnp.asarray(list(boundaries), jnp.float32)

    def compute(self, x):
        return jnp.searchsorted(self.boundaries, jnp.asarray(x, jnp.float32),
                                side="right").astype(jnp.int32)


class CategoricalColVocaList(Operation):
    """String column -> vocabulary ids (host-side strings).
    reference: nn/ops/CategoricalColVocaList.scala — OOV handling: dropped
    by default, mapped to len(vocab) when is_set_default, or hashed into
    [len(vocab), len(vocab)+num_oov_buckets) when num_oov_buckets > 0."""

    def __init__(self, vocabulary: Sequence[str], strDelimiter: str = ",",
                 is_set_default: bool = False, num_oov_buckets: int = 0,
                 name: Optional[str] = None):
        super().__init__(name)
        assert not (is_set_default and num_oov_buckets > 0), \
            "num_oov_buckets cannot be combined with default_value"
        self.vocab = {v: i for i, v in enumerate(vocabulary)}
        self.delim = strDelimiter
        self.is_set_default = is_set_default
        self.num_oov_buckets = num_oov_buckets

    def _lookup(self, s: str):
        if s in self.vocab:
            return self.vocab[s]
        if self.num_oov_buckets > 0:
            return len(self.vocab) + fnv1a(s) % self.num_oov_buckets
        if self.is_set_default:
            return len(self.vocab)
        return None

    def compute(self, x):
        rows = np.asarray(x, dtype=object).reshape(-1)
        out = []
        for row in rows:
            ids = [self._lookup(tok) for tok in str(row).split(self.delim)]
            out.append([i for i in ids if i is not None])
        width = max((len(r) for r in out), default=0)
        dense = np.full((len(out), width), -1, np.int32)
        for i, r in enumerate(out):
            dense[i, :len(r)] = r
        return jnp.asarray(dense)


class Substr(Operation):
    """{string scalar, pos, len} -> substring (host-side).
    reference: nn/ops/Substr.scala:25-38."""

    def compute(self, x):
        data, pos, ln = list(x)
        s = np.asarray(data, dtype=object).item()
        if isinstance(s, bytes):
            s = s.decode()
        p = int(np.asarray(pos).item())
        l = int(np.asarray(ln).item())
        return np.asarray(str(s)[p:p + l], dtype=object)


class ModuleToOperation(Operation):
    """Wrap any Module as a forward-only Operation (gradients blocked).
    reference: nn/ops/ModuleToOperation.scala."""

    def __init__(self, module: Module, name: Optional[str] = None):
        super().__init__(name)
        self.module = module

    def build(self, rng, input_shape):
        params, state, out = self.module.build(rng, input_shape)
        return params, state, out

    def apply(self, params, state, x, *, training=False, rng=None):
        y, new_state = self.module.apply(params, state, x, training=False,
                                         rng=rng)
        return jax.tree_util.tree_map(lax.stop_gradient, y), new_state

    def output_shape(self, input_shape):
        return self.module.output_shape(input_shape)


class TensorOp(Operation):
    """Composable closure-based tensor transform with operator sugar:
    `(TensorOp() * 2.3 + 1.2).sqrt()` builds one fused transform; `a >> b`
    chains.  reference: nn/ops/TensorOp.scala (the `->` chained closures
    and the arithmetic shortcut API)."""

    def __init__(self, fn: Optional[Callable] = None, name: Optional[str] = None):
        super().__init__(name)
        self.fn = fn or (lambda t: t)

    def compute(self, x):
        return self.fn(jnp.asarray(x))

    def _then(self, g: Callable) -> "TensorOp":
        f = self.fn
        return TensorOp(lambda t: g(f(t)))

    def __rshift__(self, other: "TensorOp") -> "TensorOp":
        return self._then(other.fn)

    def __add__(self, c):
        return self._then(lambda t: t + c)

    def __sub__(self, c):
        return self._then(lambda t: t - c)

    def __mul__(self, c):
        return self._then(lambda t: t * c)

    def __truediv__(self, c):
        return self._then(lambda t: t / c)

    def __pow__(self, c):
        return self._then(lambda t: t ** c)

    def abs(self):
        return self._then(jnp.abs)

    def sqrt(self):
        return self._then(jnp.sqrt)

    def log(self):
        return self._then(jnp.log)

    def log1p(self):
        return self._then(jnp.log1p)

    def exp(self):
        return self._then(jnp.exp)

    def floor(self):
        return self._then(jnp.floor)

    def ceil(self):
        return self._then(jnp.ceil)

    def tanh(self):
        return self._then(jnp.tanh)

    def sigmoid(self):
        return self._then(jax.nn.sigmoid)

    def softmax(self):
        return self._then(lambda t: jax.nn.softmax(t, axis=-1))

    def square(self):
        return self._then(jnp.square)

    def negative(self):
        return self._then(jnp.negative)

    def inv(self):
        return self._then(lambda t: 1.0 / t)


# Name-parity aliases for the reference's file names (nn/ops/CrossEntropy.
# scala, nn/ops/DepthwiseConv2D.scala, nn/ops/Compare.scala base)
Compare = Operation
CrossEntropy = CrossEntropyOp
DepthwiseConv2D = DepthwiseConv2DOp
