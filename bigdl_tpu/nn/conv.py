"""Convolution layers (NHWC, HWIO kernels — TPU-native layouts).

Reference: nn/SpatialConvolution.scala (im2col+gemm on MKL),
nn/SpatialDilatedConvolution.scala, nn/SpatialFullConvolution.scala
(deconvolution), nn/SpatialSeparableConvolution.scala,
nn/TemporalConvolution.scala.  All lower to `lax.conv_general_dilated`,
which XLA maps directly onto the MXU — no im2col materialization.

Padding semantics: BigDL uses explicit (padW, padH) with -1 meaning
TensorFlow-style SAME (nn/SpatialConvolution.scala scaladoc).  We keep that
contract: pad = -1 -> "SAME", else explicit symmetric padding.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module

_DIMSPEC_2D = ("NHWC", "HWIO", "NHWC")


def _same_pad(size: int, k: int, stride: int, dilation: int):
    eff = (k - 1) * dilation + 1
    total = max(0, (-(-size // stride) - 1) * stride + eff - size)
    return (total // 2, total - total // 2)


def _pad2d(pad_h: int, pad_w: int, in_hw=None, kernel=None, stride=None, dilation=(1, 1)):
    """pad = -1 means TF-style SAME, resolvable per-dim (mixed -1/explicit
    is supported, matching output_shape's per-dim computation)."""
    if pad_h == -1 or pad_w == -1:
        h, w = in_hw
        kh, kw = kernel
        sh, sw = stride
        ph = _same_pad(h, kh, sh, dilation[0]) if pad_h == -1 else (pad_h, pad_h)
        pw = _same_pad(w, kw, sw, dilation[1]) if pad_w == -1 else (pad_w, pad_w)
        return [ph, pw]
    return [(pad_h, pad_h), (pad_w, pad_w)]


def _conv_out(size: int, k: int, stride: int, pad: int, dilation: int = 1) -> int:
    if pad == -1:  # SAME
        return -(-size // stride)
    eff = (k - 1) * dilation + 1
    return (size + 2 * pad - eff) // stride + 1


class SpatialConvolution(Module):
    """2-D convolution.  reference: nn/SpatialConvolution.scala.

    Args mirror the reference: (nInputPlane, nOutputPlane, kernelW, kernelH,
    strideW, strideH, padW, padH, nGroup, withBias).  Input is NHWC.
    """

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, n_group: int = 1,
                 with_bias: bool = True, weight_init=None, bias_init=None,
                 w_regularizer=None, b_regularizer=None,
                 name: Optional[str] = None):
        super().__init__(name)
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        # reference: wRegularizer/bRegularizer (nn/SpatialConvolution.scala)
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.n_input = n_input_plane
        self.n_output = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.n_group = n_group
        self.with_bias = with_bias
        self.weight_init = weight_init or init_mod.MsraFiller(False)
        self.bias_init = bias_init or init_mod.Zeros()
        self.dilation = (1, 1)

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init is not None:
            self.weight_init = weight_init
        if bias_init is not None:
            self.bias_init = bias_init
        return self

    def _kernel_shape(self) -> Tuple[int, ...]:
        kh, kw = self.kernel
        return (kh, kw, self.n_input // self.n_group, self.n_output)

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input // self.n_group * kh * kw
        fan_out = self.n_output // self.n_group * kh * kw
        params = {"weight": self.weight_init(k_w, self._kernel_shape(), fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (self.n_output,), fan_in, fan_out)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            x, params["weight"], window_strides=self.stride,
            padding=_pad2d(*self.pad, in_hw=x.shape[1:3], kernel=self.kernel,
                           stride=self.stride, dilation=self.dilation),
            rhs_dilation=self.dilation,
            dimension_numbers=_DIMSPEC_2D, feature_group_count=self.n_group,
        )
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        n, h, w, _ = input_shape
        kh, kw = self.kernel
        oh = _conv_out(h, kh, self.stride[0], self.pad[0], self.dilation[0])
        ow = _conv_out(w, kw, self.stride[1], self.pad[1], self.dilation[1])
        return (n, oh, ow, self.n_output)


class SpatialDilatedConvolution(SpatialConvolution):
    """Atrous conv. reference: nn/SpatialDilatedConvolution.scala."""

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 dilation_w=1, dilation_h=1, name=None):
        super().__init__(n_input_plane, n_output_plane, kernel_w, kernel_h,
                         stride_w, stride_h, pad_w, pad_h, name=name)
        self.dilation = (dilation_h, dilation_w)


class SpatialSeparableConvolution(Module):
    """Depthwise + pointwise. reference: nn/SpatialSeparableConvolution.scala."""

    def __init__(self, n_input_channel: int, n_output_channel: int,
                 depth_multiplier: int, k_w: int, k_h: int,
                 s_w: int = 1, s_h: int = 1, p_w: int = 0, p_h: int = 0,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.depthwise = SpatialConvolution(
            n_input_channel, n_input_channel * depth_multiplier, k_w, k_h,
            s_w, s_h, p_w, p_h, n_group=n_input_channel, with_bias=False)
        self.pointwise = SpatialConvolution(
            n_input_channel * depth_multiplier, n_output_channel, 1, 1,
            with_bias=with_bias)

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        p1, s1, shape = self.depthwise.build(k1, input_shape)
        p2, s2, shape = self.pointwise.build(k2, shape)
        return {"depthwise": p1, "pointwise": p2}, {}, shape

    def apply(self, params, state, x, *, training=False, rng=None):
        y, _ = self.depthwise.apply(params["depthwise"], {}, x)
        y, _ = self.pointwise.apply(params["pointwise"], {}, y)
        return y, state

    def output_shape(self, input_shape):
        return self.pointwise.output_shape(self.depthwise.output_shape(input_shape))


class SpatialFullConvolution(Module):
    """Transposed convolution (deconv). reference:
    nn/SpatialFullConvolution.scala.  Implemented with lhs dilation so XLA
    emits a single fused transposed conv."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, adj_w: int = 0, adj_h: int = 0,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input_plane
        self.n_output = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.adj = (adj_h, adj_w)
        self.with_bias = with_bias
        self.weight_init = init_mod.Xavier()
        self.bias_init = init_mod.Zeros()

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input * kh * kw
        fan_out = self.n_output * kh * kw
        params = {"weight": self.weight_init(k_w, (kh, kw, self.n_input, self.n_output),
                                             fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (self.n_output,), fan_in, fan_out)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        kh, kw = self.kernel
        ph, pw = self.pad
        ah, aw = self.adj
        pad = [(kh - 1 - ph, kh - 1 - ph + ah), (kw - 1 - pw, kw - 1 - pw + aw)]
        w = jnp.flip(params["weight"], axis=(0, 1))
        y = lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding=pad,
            lhs_dilation=self.stride, dimension_numbers=_DIMSPEC_2D)
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        n, h, w, _ = input_shape
        kh, kw = self.kernel
        oh = (h - 1) * self.stride[0] - 2 * self.pad[0] + kh + self.adj[0]
        ow = (w - 1) * self.stride[1] - 2 * self.pad[1] + kw + self.adj[1]
        return (n, oh, ow, self.n_output)


class TemporalConvolution(Module):
    """1-D conv over (N, T, C). reference: nn/TemporalConvolution.scala."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1, with_bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_frame_size
        self.output_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias
        self.weight_init = init_mod.Xavier()
        self.bias_init = init_mod.Zeros()

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        fan_in = self.input_size * self.kernel_w
        params = {
            "weight": self.weight_init(k_w, (self.kernel_w, self.input_size, self.output_size),
                                       fan_in, self.output_size),
        }
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (self.output_size,), fan_in,
                                            self.output_size)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            x, params["weight"], window_strides=(self.stride_w,), padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        n, t, _ = input_shape
        ot = (t - self.kernel_w) // self.stride_w + 1
        return (n, ot, self.output_size)


class SpatialShareConvolution(SpatialConvolution):
    """Same math as SpatialConvolution.  The reference variant
    (nn/SpatialShareConvolution.scala) exists only to share im2col buffers
    across replicas on the JVM heap; under XLA buffer reuse is the
    compiler's job, so this is a name-parity alias."""


def full_connection_table(n_in: int, n_out: int):
    """Every input feature feeds every output feature
    (reference: SpatialConvolutionMap's full table / torch nn.tables.full)."""
    return [(i, o) for o in range(n_out) for i in range(n_in)]


def one_to_one_connection_table(n_features: int):
    """Feature i feeds only feature i (torch nn.tables.oneToOne)."""
    return [(i, i) for i in range(n_features)]


def random_connection_table(n_in: int, n_out: int, n_into: int, seed=None):
    """Each output feature draws `n_into` random input features
    (torch nn.tables.random).  Pass `seed` for a reproducible table;
    the default draws fresh entropy per call like the torch original."""
    import numpy as _np
    r = _np.random.default_rng(seed)
    pairs = []
    for o in range(n_out):
        for i in r.permutation(n_in)[:n_into]:
            pairs.append((int(i), o))
    return pairs


class SpatialConvolutionBN(Module):
    """FUSED 1x1 conv + SpatialBatchNormalization for training.

    Reference role: conv+BN fusion is the reference's marquee MKL-DNN
    optimization (`nn/mkldnn/Fusion.scala:26-31`); on its training side
    MKL-DNN's batchnorm primitive computes the stats inline.  Here the
    BN moments come out of the conv's pallas epilogue
    (`ops/conv_bn_stats.py`) while the output tile is still in VMEM —
    deleting the HBM stats-reduce read that makes the ResNet train step
    bandwidth-bound (BENCH_APPENDIX.md).

    Semantics match `Sequential(SpatialConvolution(cin, cout, 1, 1,
    stride, stride, with_bias=False), SpatialBatchNormalization(cout))`
    exactly — same param shapes (`weight` HWIO (1,1,cin,cout), BN
    `gamma`/`beta`), same biased/unbiased variance handling, same
    running-stat update; `axis_name` gives the same cross-replica
    sync-BN.  Eval mode folds the BN affine into one scale/shift after
    the conv (no stats pass at all)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 stride: int = 1, eps: float = 1e-5, momentum: float = 0.1,
                 zero_gamma: bool = False, weight_init=None,
                 axis_name: Optional[str] = None,
                 w_regularizer=None, interpret: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input_plane
        self.n_output = n_output_plane
        self.stride = stride
        self.eps = eps
        self.momentum = momentum
        self.zero_gamma = zero_gamma
        self.weight_init = weight_init or init_mod.MsraFiller(False)
        self.axis_name = axis_name
        self.w_regularizer = w_regularizer
        self.interpret = interpret

    def set_axis_name(self, axis_name: Optional[str]) -> "SpatialConvolutionBN":
        self.axis_name = axis_name
        return self

    def build(self, rng, input_shape):
        c_in, c_out = self.n_input, self.n_output
        params = {
            "weight": self.weight_init(rng, (1, 1, c_in, c_out),
                                       c_in, c_out),
            "gamma": (jnp.zeros if self.zero_gamma else jnp.ones)(
                (c_out,), jnp.float32),
            "beta": jnp.zeros((c_out,), jnp.float32),
        }
        state = {"running_mean": jnp.zeros((c_out,), jnp.float32),
                 "running_var": jnp.ones((c_out,), jnp.float32)}
        return params, state, self.output_shape(input_shape)

    def output_shape(self, input_shape):
        n, h, w, _ = input_shape
        s = self.stride
        return (n, -(-h // s), -(-w // s), self.n_output)

    def apply(self, params, state, x, *, training=False, rng=None):
        from bigdl_tpu.ops.conv_bn_stats import conv1x1_bn_stats

        w = params["weight"]
        gamma, beta = params["gamma"], params["beta"]
        if training:
            y, s1, s2 = conv1x1_bn_stats(x, w, stride=self.stride,
                                         interpret=self.interpret)
            m = y.shape[0] * y.shape[1] * y.shape[2]
            mean = s1 / m
            mean2 = s2 / m
            n_count = m
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
                n_count = m * lax.psum(1, self.axis_name)
            var = mean2 - jnp.square(mean)
            unbiased = var * (n_count / jnp.maximum(n_count - 1, 1))
            mm = self.momentum
            new_state = {
                "running_mean": (1 - mm) * state["running_mean"] + mm * mean,
                "running_var": (1 - mm) * state["running_var"]
                + mm * unbiased,
            }
        else:
            if self.stride > 1:
                x = x[:, ::self.stride, ::self.stride, :]
            y = lax.conv_general_dilated(
                x, w, window_strides=(1, 1), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            mean, var = state["running_mean"], state["running_var"]
            new_state = state
        inv = lax.rsqrt(var + self.eps)
        # Scale/shift form: fold the BN affine into two per-channel
        # vectors cast to y.dtype BEFORE touching y.  The naive
        # (y - mean) * inv * gamma + beta upcasts the whole conv output
        # to f32 and reverse-mode AD then keeps full-size f32 residuals
        # ((y - mean) * inv for gamma_bar) — ~0.4 GB per wide layer,
        # enough to blow HBM at b256.  With y * scale + shift the only
        # AD residuals besides y itself are the per-channel vectors.
        scale = (gamma * inv).astype(y.dtype)
        shift = (beta - mean * gamma * inv).astype(y.dtype)
        out = y * scale + shift
        return out.astype(x.dtype), new_state


class SpatialConvolutionMap(Module):
    """Convolution with a generic input->output connection table — the
    generalisation of SpatialConvolution (full table) and depthwise conv
    (one-to-one table).  reference: nn/SpatialConvolutionMap.scala.

    `conn_table` is a list of (in_feature, out_feature) pairs (0-based).
    TPU-first realisation: one dense conv with a static binary mask over the
    (kh, kw, cin, cout) kernel — the MXU runs the dense matmul either way,
    and the mask folds into the weights at trace time (no gather loops)."""

    def __init__(self, conn_table, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.conn_table = [(int(i), int(o)) for i, o in conn_table]
        self.n_input = 1 + max(i for i, _ in self.conn_table)
        self.n_output = 1 + max(o for _, o in self.conn_table)
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.with_bias = with_bias

    def _mask(self, n_input=None, n_output=None):
        import numpy as _np
        m = _np.zeros((n_input or self.n_input, n_output or self.n_output),
                      _np.float32)
        for i, o in self.conn_table:
            m[i, o] = 1.0
        return jnp.asarray(m)

    def build(self, rng, input_shape):
        kh, kw = self.kernel
        # the table's max input index under-counts when the highest input
        # features happen to be unconnected (legal for random tables —
        # torch's nn.tables.random can skip features); the real channel
        # count comes from the input
        self.n_input = max(self.n_input, int(input_shape[-1]))
        # torch init: stdv = 1/sqrt(kW*kH*nInputPlane) per connection
        fan = kh * kw * max(1, len(self.conn_table) // self.n_output)
        k_w, k_b = jax.random.split(rng)
        stdv = 1.0 / (fan ** 0.5)
        w = jax.random.uniform(k_w, (kh, kw, self.n_input, self.n_output),
                               jnp.float32, -stdv, stdv)
        params = {"weight": w * self._mask()}
        if self.with_bias:
            params["bias"] = jax.random.uniform(
                k_b, (self.n_output,), jnp.float32, -stdv, stdv)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        # mask dims come from the WEIGHT, not self.n_input: build() may
        # have widened the input width beyond the table's max index, and
        # a serializer-reloaded module only knows its __init__ args
        w = params["weight"]
        y = lax.conv_general_dilated(
            x, w * self._mask(w.shape[2], w.shape[3]),
            window_strides=self.stride,
            padding=[(self.pad[0], self.pad[0]), (self.pad[1], self.pad[1])],
            dimension_numbers=_DIMSPEC_2D)
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        n, h, w, _ = input_shape
        kh, kw = self.kernel
        oh = _conv_out(h, kh, self.stride[0], self.pad[0], 1)
        ow = _conv_out(w, kw, self.stride[1], self.pad[1], 1)
        return (n, oh, ow, self.n_output)


class LocallyConnected2D(Module):
    """Convolution with UNSHARED weights: a different filter bank at every
    output location.  reference: nn/LocallyConnected2D.scala.

    Patches are extracted with conv_general_dilated_patches and contracted
    against per-position weights in one einsum (a batched matmul on the
    MXU), instead of the reference's per-location gemm loop."""

    def __init__(self, n_input_plane: int, input_width: int, input_height: int,
                 n_output_plane: int, kernel_w: int, kernel_h: int,
                 stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input_plane
        self.n_output = n_output_plane
        self.in_hw = (input_height, input_width)
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.with_bias = with_bias

    def _out_hw(self):
        oh = _conv_out(self.in_hw[0], self.kernel[0], self.stride[0], self.pad[0], 1)
        ow = _conv_out(self.in_hw[1], self.kernel[1], self.stride[1], self.pad[1], 1)
        return oh, ow

    def build(self, rng, input_shape):
        kh, kw = self.kernel
        oh, ow = self._out_hw()
        fan_in = kh * kw * self.n_input
        k_w, k_b = jax.random.split(rng)
        stdv = 1.0 / (fan_in ** 0.5)
        params = {"weight": jax.random.uniform(
            k_w, (oh, ow, kh * kw * self.n_input, self.n_output),
            jnp.float32, -stdv, stdv)}
        if self.with_bias:
            params["bias"] = jax.random.uniform(
                k_b, (oh, ow, self.n_output), jnp.float32, -stdv, stdv)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        kh, kw = self.kernel
        # patches: (N, C*kh*kw, OH, OW) with feature-major ordering (C slowest)
        patches = lax.conv_general_dilated_patches(
            jnp.moveaxis(x, -1, 1), (kh, kw), self.stride,
            [(self.pad[0], self.pad[0]), (self.pad[1], self.pad[1])])
        # (N, C*kh*kw, OH, OW), feature dim C-major (C, kh, kw) — the same
        # ordering as torch unfold
        p = jnp.moveaxis(patches, 1, -1)  # (N, OH, OW, C*kh*kw)
        y = jnp.einsum("nhwk,hwko->nhwo", p, params["weight"])
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        oh, ow = self._out_hw()
        return (input_shape[0], oh, ow, self.n_output)


class LocallyConnected1D(Module):
    """1-D locally connected layer over (N, T, C) frames.
    reference: nn/LocallyConnected1D.scala."""

    def __init__(self, n_input_frame: int, input_frame_size: int,
                 output_frame_size: int, kernel_w: int, stride_w: int = 1,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.n_input_frame = n_input_frame
        self.in_size = input_frame_size
        self.out_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias

    def _out_frames(self):
        return (self.n_input_frame - self.kernel_w) // self.stride_w + 1

    def build(self, rng, input_shape):
        ot = self._out_frames()
        fan_in = self.kernel_w * self.in_size
        k_w, k_b = jax.random.split(rng)
        stdv = 1.0 / (fan_in ** 0.5)
        params = {"weight": jax.random.uniform(
            k_w, (ot, self.kernel_w * self.in_size, self.out_size),
            jnp.float32, -stdv, stdv)}
        if self.with_bias:
            params["bias"] = jax.random.uniform(
                k_b, (ot, self.out_size), jnp.float32, -stdv, stdv)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        ot = self._out_frames()
        idx = jnp.arange(ot) * self.stride_w
        # windows: (N, OT, kW, C)
        win = jax.vmap(lambda s: lax.dynamic_slice_in_dim(x, s, self.kernel_w, 1),
                       out_axes=1)(idx)
        n = x.shape[0]
        win = win.reshape(n, ot, self.kernel_w * self.in_size)
        y = jnp.einsum("ntk,tko->nto", win, params["weight"])
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        return (input_shape[0], self._out_frames(), self.out_size)
