"""Convolution layers (NHWC, HWIO kernels — TPU-native layouts).

Reference: nn/SpatialConvolution.scala (im2col+gemm on MKL),
nn/SpatialDilatedConvolution.scala, nn/SpatialFullConvolution.scala
(deconvolution), nn/SpatialSeparableConvolution.scala,
nn/TemporalConvolution.scala.  All lower to `lax.conv_general_dilated`,
which XLA maps directly onto the MXU — no im2col materialization.

Padding semantics: BigDL uses explicit (padW, padH) with -1 meaning
TensorFlow-style SAME (nn/SpatialConvolution.scala scaladoc).  We keep that
contract: pad = -1 -> "SAME", else explicit symmetric padding.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module

_DIMSPEC_2D = ("NHWC", "HWIO", "NHWC")


def _same_pad(size: int, k: int, stride: int, dilation: int):
    eff = (k - 1) * dilation + 1
    total = max(0, (-(-size // stride) - 1) * stride + eff - size)
    return (total // 2, total - total // 2)


def _pad2d(pad_h: int, pad_w: int, in_hw=None, kernel=None, stride=None, dilation=(1, 1)):
    """pad = -1 means TF-style SAME, resolvable per-dim (mixed -1/explicit
    is supported, matching output_shape's per-dim computation)."""
    if pad_h == -1 or pad_w == -1:
        h, w = in_hw
        kh, kw = kernel
        sh, sw = stride
        ph = _same_pad(h, kh, sh, dilation[0]) if pad_h == -1 else (pad_h, pad_h)
        pw = _same_pad(w, kw, sw, dilation[1]) if pad_w == -1 else (pad_w, pad_w)
        return [ph, pw]
    return [(pad_h, pad_h), (pad_w, pad_w)]


def _conv_out(size: int, k: int, stride: int, pad: int, dilation: int = 1) -> int:
    if pad == -1:  # SAME
        return -(-size // stride)
    eff = (k - 1) * dilation + 1
    return (size + 2 * pad - eff) // stride + 1


class SpatialConvolution(Module):
    """2-D convolution.  reference: nn/SpatialConvolution.scala.

    Args mirror the reference: (nInputPlane, nOutputPlane, kernelW, kernelH,
    strideW, strideH, padW, padH, nGroup, withBias).  Input is NHWC.
    """

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, n_group: int = 1,
                 with_bias: bool = True, weight_init=None, bias_init=None,
                 w_regularizer=None, b_regularizer=None,
                 name: Optional[str] = None):
        super().__init__(name)
        assert n_input_plane % n_group == 0 and n_output_plane % n_group == 0
        # reference: wRegularizer/bRegularizer (nn/SpatialConvolution.scala)
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer
        self.n_input = n_input_plane
        self.n_output = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.n_group = n_group
        self.with_bias = with_bias
        self.weight_init = weight_init or init_mod.MsraFiller(False)
        self.bias_init = bias_init or init_mod.Zeros()
        self.dilation = (1, 1)

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init is not None:
            self.weight_init = weight_init
        if bias_init is not None:
            self.bias_init = bias_init
        return self

    def _kernel_shape(self) -> Tuple[int, ...]:
        kh, kw = self.kernel
        return (kh, kw, self.n_input // self.n_group, self.n_output)

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input // self.n_group * kh * kw
        fan_out = self.n_output // self.n_group * kh * kw
        params = {"weight": self.weight_init(k_w, self._kernel_shape(), fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (self.n_output,), fan_in, fan_out)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            x, params["weight"], window_strides=self.stride,
            padding=_pad2d(*self.pad, in_hw=x.shape[1:3], kernel=self.kernel,
                           stride=self.stride, dilation=self.dilation),
            rhs_dilation=self.dilation,
            dimension_numbers=_DIMSPEC_2D, feature_group_count=self.n_group,
        )
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        n, h, w, _ = input_shape
        kh, kw = self.kernel
        oh = _conv_out(h, kh, self.stride[0], self.pad[0], self.dilation[0])
        ow = _conv_out(w, kw, self.stride[1], self.pad[1], self.dilation[1])
        return (n, oh, ow, self.n_output)


class SpatialDilatedConvolution(SpatialConvolution):
    """Atrous conv. reference: nn/SpatialDilatedConvolution.scala."""

    def __init__(self, n_input_plane, n_output_plane, kernel_w, kernel_h,
                 stride_w=1, stride_h=1, pad_w=0, pad_h=0,
                 dilation_w=1, dilation_h=1, name=None):
        super().__init__(n_input_plane, n_output_plane, kernel_w, kernel_h,
                         stride_w, stride_h, pad_w, pad_h, name=name)
        self.dilation = (dilation_h, dilation_w)


class SpatialSeparableConvolution(Module):
    """Depthwise + pointwise. reference: nn/SpatialSeparableConvolution.scala."""

    def __init__(self, n_input_channel: int, n_output_channel: int,
                 depth_multiplier: int, k_w: int, k_h: int,
                 s_w: int = 1, s_h: int = 1, p_w: int = 0, p_h: int = 0,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.depthwise = SpatialConvolution(
            n_input_channel, n_input_channel * depth_multiplier, k_w, k_h,
            s_w, s_h, p_w, p_h, n_group=n_input_channel, with_bias=False)
        self.pointwise = SpatialConvolution(
            n_input_channel * depth_multiplier, n_output_channel, 1, 1,
            with_bias=with_bias)

    def build(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        p1, s1, shape = self.depthwise.build(k1, input_shape)
        p2, s2, shape = self.pointwise.build(k2, shape)
        return {"depthwise": p1, "pointwise": p2}, {}, shape

    def apply(self, params, state, x, *, training=False, rng=None):
        y, _ = self.depthwise.apply(params["depthwise"], {}, x)
        y, _ = self.pointwise.apply(params["pointwise"], {}, y)
        return y, state

    def output_shape(self, input_shape):
        return self.pointwise.output_shape(self.depthwise.output_shape(input_shape))


class SpatialFullConvolution(Module):
    """Transposed convolution (deconv). reference:
    nn/SpatialFullConvolution.scala.  Implemented with lhs dilation so XLA
    emits a single fused transposed conv."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 kernel_w: int, kernel_h: int, stride_w: int = 1, stride_h: int = 1,
                 pad_w: int = 0, pad_h: int = 0, adj_w: int = 0, adj_h: int = 0,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input_plane
        self.n_output = n_output_plane
        self.kernel = (kernel_h, kernel_w)
        self.stride = (stride_h, stride_w)
        self.pad = (pad_h, pad_w)
        self.adj = (adj_h, adj_w)
        self.with_bias = with_bias
        self.weight_init = init_mod.Xavier()
        self.bias_init = init_mod.Zeros()

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        kh, kw = self.kernel
        fan_in = self.n_input * kh * kw
        fan_out = self.n_output * kh * kw
        params = {"weight": self.weight_init(k_w, (kh, kw, self.n_input, self.n_output),
                                             fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (self.n_output,), fan_in, fan_out)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        kh, kw = self.kernel
        ph, pw = self.pad
        ah, aw = self.adj
        pad = [(kh - 1 - ph, kh - 1 - ph + ah), (kw - 1 - pw, kw - 1 - pw + aw)]
        w = jnp.flip(params["weight"], axis=(0, 1))
        y = lax.conv_general_dilated(
            x, w, window_strides=(1, 1), padding=pad,
            lhs_dilation=self.stride, dimension_numbers=_DIMSPEC_2D)
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        n, h, w, _ = input_shape
        kh, kw = self.kernel
        oh = (h - 1) * self.stride[0] - 2 * self.pad[0] + kh + self.adj[0]
        ow = (w - 1) * self.stride[1] - 2 * self.pad[1] + kw + self.adj[1]
        return (n, oh, ow, self.n_output)


class TemporalConvolution(Module):
    """1-D conv over (N, T, C). reference: nn/TemporalConvolution.scala."""

    def __init__(self, input_frame_size: int, output_frame_size: int,
                 kernel_w: int, stride_w: int = 1, with_bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_frame_size
        self.output_size = output_frame_size
        self.kernel_w = kernel_w
        self.stride_w = stride_w
        self.with_bias = with_bias
        self.weight_init = init_mod.Xavier()
        self.bias_init = init_mod.Zeros()

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        fan_in = self.input_size * self.kernel_w
        params = {
            "weight": self.weight_init(k_w, (self.kernel_w, self.input_size, self.output_size),
                                       fan_in, self.output_size),
        }
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (self.output_size,), fan_in,
                                            self.output_size)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            x, params["weight"], window_strides=(self.stride_w,), padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        n, t, _ = input_shape
        ot = (t - self.kernel_w) // self.stride_w + 1
        return (n, ot, self.output_size)
