"""Distance, similarity and gating layers.

Reference files (all under nn/): Euclidean.scala, CosineDistance.scala,
PairwiseDistance.scala, Bilinear.scala, MixtureTable.scala, Maxout.scala,
Highway.scala, LookupTableSparse.scala.

All are small batched tensor-contraction ops; the bilinear form and maxout
lower to single einsum/matmul calls that XLA tiles onto the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.linear import Linear
from bigdl_tpu.nn.module import Module, Sequential


class Euclidean(Module):
    """y_j = ||x - w_j||_2 for each of output_size centers.
    reference: nn/Euclidean.scala."""

    def __init__(self, input_size: int, output_size: int,
                 fast_backward: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.output_size = input_size, output_size

    def build(self, rng, input_shape):
        bound = 1.0 / jnp.sqrt(self.input_size)
        w = jax.random.uniform(rng, (self.input_size, self.output_size),
                               jnp.float32, -bound, bound)
        return {"weight": w}, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        # (B, in) vs (in, out): expand the quadratic form so the dominant
        # term is one matmul (x @ w) instead of a (B, in, out) broadcast.
        w = params["weight"]
        x2 = jnp.sum(x * x, axis=-1, keepdims=True)          # (B, 1)
        w2 = jnp.sum(w * w, axis=0, keepdims=True)           # (1, out)
        cross = x @ w                                        # (B, out) MXU
        d2 = jnp.maximum(x2 + w2 - 2.0 * cross, 0.0)
        return jnp.sqrt(d2 + 1e-12), state

    def output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_size,)


class CosineDistance(Module):
    """Table(x1, x2) -> cosine similarity per row.
    reference: nn/CosineDistance.scala."""

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = x[1], x[2]
        num = jnp.sum(a * b, axis=-1)
        den = jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1)
        return num / jnp.maximum(den, 1e-12), state


class PairwiseDistance(Module):
    """Table(x1, x2) -> ||x1 - x2||_p per row. reference: nn/PairwiseDistance.scala."""

    def __init__(self, norm: int = 2, name: Optional[str] = None):
        super().__init__(name)
        self.norm = norm

    def apply(self, params, state, x, *, training=False, rng=None):
        d = x[1] - x[2]
        if self.norm == 1:
            return jnp.sum(jnp.abs(d), axis=-1), state
        if self.norm == 2:
            return jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-12), state
        p = float(self.norm)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p), state


class Bilinear(Module):
    """Table(x1, x2) -> x1^T W_k x2 + b_k for each output k.
    reference: nn/Bilinear.scala.  One einsum -> batched MXU contraction."""

    def __init__(self, input_size1: int, input_size2: int, output_size: int,
                 bias_res: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.input_size1 = input_size1
        self.input_size2 = input_size2
        self.output_size = output_size
        self.bias_res = bias_res

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        bound = 1.0 / jnp.sqrt(self.input_size1)
        w = jax.random.uniform(
            k_w, (self.output_size, self.input_size1, self.input_size2),
            jnp.float32, -bound, bound)
        params = {"weight": w}
        if self.bias_res:
            params["bias"] = jax.random.uniform(
                k_b, (self.output_size,), jnp.float32, -bound, bound)
        return params, {}, self.output_shape(input_shape)

    def output_shape(self, input_shape):
        return (input_shape[1][0], self.output_size)

    def apply(self, params, state, x, *, training=False, rng=None):
        a, b = x[1], x[2]
        y = jnp.einsum("bi,oij,bj->bo", a, params["weight"], b)
        if self.bias_res:
            y = y + params["bias"]
        return y, state


class MixtureTable(Module):
    """Table(gate (B, n), experts Table/tensor) -> gate-weighted sum of
    expert outputs. reference: nn/MixtureTable.scala."""

    def __init__(self, dim: int = 1, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        gate, experts = x[1], x[2]
        d = self.dim
        if isinstance(experts, Table):
            stacked = jnp.stack(list(experts), axis=d)
        else:
            stacked = experts
        # gate is (B, n); align n with the expert axis `d` for broadcasting
        gshape = [1] * stacked.ndim
        gshape[0] = gate.shape[0]
        gshape[d] = gate.shape[1]
        g = gate.reshape(gshape)
        return jnp.sum(stacked * g, axis=d), state


class Maxout(Module):
    """Linear to (out * pool) units, max over each pool group.
    reference: nn/Maxout.scala."""

    def __init__(self, input_size: int, output_size: int, maxout_number: int,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.maxout_number = maxout_number
        self.inner = Linear(input_size, output_size * maxout_number,
                            with_bias=with_bias)

    def build(self, rng, input_shape):
        p, s, _ = self.inner.build(rng, input_shape)
        return p, s, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        y, state = self.inner.apply(params, state, x, training=training)
        y = y.reshape(y.shape[:-1] + (self.output_size, self.maxout_number))
        return jnp.max(y, axis=-1), state

    def output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_size,)


class Highway(Module):
    """y = t * h(Wx+b) + (1-t) * x with transform gate t = sigmoid(Wt x + bt).
    reference: nn/Highway.scala."""

    def __init__(self, size: int, with_bias: bool = True, activation=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = size
        self.activation = activation  # a Module or None (identity)
        self.h = Linear(size, size, with_bias=with_bias)
        self.t = Linear(size, size, with_bias=with_bias,
                        bias_init=init_mod.ConstInit(-2.0))

    def build(self, rng, input_shape):
        k1, k2, k3 = jax.random.split(rng, 3)
        ph, sh, _ = self.h.build(k1, input_shape)
        pt, st, _ = self.t.build(k2, input_shape)
        params = {"h": ph, "t": pt}
        state = {"h": sh, "t": st}
        if self.activation is not None:
            pa, sa, _ = self.activation.build(k3, input_shape)
            params["act"] = pa
            state["act"] = sa
        return params, state, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        new_state = dict(state)
        h, new_state["h"] = self.h.apply(params["h"], state["h"], x,
                                         training=training)
        if self.activation is not None:
            h, new_state["act"] = self.activation.apply(
                params.get("act", {}), state.get("act", {}), h,
                training=training)
        t, new_state["t"] = self.t.apply(params["t"], state["t"], x,
                                         training=training)
        t = jax.nn.sigmoid(t)
        return t * h + (1.0 - t) * x, new_state


class LookupTableSparse(Module):
    """Embedding over (dense-encoded) sparse id bags: input Table(ids,
    weights) or ids tensor, ids padded with -1; combiner sum/mean/sqrtn.
    reference: nn/LookupTableSparse.scala (COO SparseTensor input there;
    padded dense bags here — same capability, MXU/gather-friendly layout)."""

    def __init__(self, n_index: int, n_output: int, combiner: str = "sum",
                 max_norm: float = -1.0, name: Optional[str] = None):
        super().__init__(name)
        self.n_index, self.n_output = n_index, n_output
        self.combiner = combiner
        self.max_norm = max_norm

    def build(self, rng, input_shape):
        w = jax.random.normal(rng, (self.n_index, self.n_output), jnp.float32)
        return {"weight": w}, {}, self.output_shape(input_shape)

    def output_shape(self, input_shape):
        ids_shape = input_shape[1] if isinstance(input_shape, Table) else input_shape
        # the bag axis reduces away: (B, bag) -> (B, n_output)
        return tuple(ids_shape[:-1]) + (self.n_output,)

    def apply(self, params, state, x, *, training=False, rng=None):
        if isinstance(x, Table):
            ids, weights = x[1], x[2]
        else:
            ids, weights = x, None
        valid = ids >= 0
        safe_ids = jnp.maximum(ids, 0).astype(jnp.int32)
        w = params["weight"]
        if self.max_norm > 0:
            norms = jnp.linalg.norm(w, axis=-1, keepdims=True)
            w = w * jnp.minimum(1.0, self.max_norm / jnp.maximum(norms, 1e-12))
        emb = w[safe_ids]                              # (B, bag, out)
        mask = valid.astype(emb.dtype)[..., None]
        if weights is not None:
            mask = mask * weights[..., None]
        summed = jnp.sum(emb * mask, axis=-2)
        if self.combiner == "sum":
            return summed, state
        count = jnp.maximum(jnp.sum(mask, axis=-2), 1e-12)
        if self.combiner == "mean":
            return summed / count, state
        if self.combiner == "sqrtn":
            sq = jnp.sqrt(jnp.maximum(jnp.sum(mask * mask, axis=-2), 1e-12))
            return summed / sq, state
        raise ValueError(f"unknown combiner {self.combiner}")
