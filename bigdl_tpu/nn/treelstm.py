"""Binary (constituency) Tree-LSTM.

Reference: nn/BinaryTreeLSTM.scala (+ nn/TreeLSTM.scala base), used by the
treeLSTMSentiment example.  The reference walks the tree recursively on the
JVM, cloning composer/leaf modules per node.

TPU-native redesign: trees are PADDED ARRAYS in children-before-parent
topological order, and the node loop is ONE `lax.scan` whose carry is the
(n_nodes, H) hidden/cell buffers — every step is the same fused XLA body,
batched with vmap.  Tree encoding per example:

  * `left`, `right`: (n_nodes,) int32 — child node indices, -1 for leaves
  * `word`: (n_nodes,) int32 — embedding-row index for leaves, -1 internal
  * padding nodes (beyond the real tree) have left=right=word=-1 and produce
    zero hidden states.

The ROOT is the last real node (topological order ⇒ parents after children).
Output is (B, n_nodes, H), matching the reference's per-node outputs.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module


class BinaryTreeLSTM(Module):
    """Input Table(embeddings (B, n_words, D), trees Table/stacked arrays
    (left, right, word) each (B, n_nodes)) -> (B, n_nodes, H) hiddens."""

    def __init__(self, input_size: int, hidden_size: int,
                 gate_output: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.gate_output = gate_output

    def build(self, rng, input_shape):
        d, h = self.input_size, self.hidden_size
        k1, k2, k3 = jax.random.split(rng, 3)
        xavier = init_mod.Xavier()
        params = {
            # leaf: i, o, u gates from the word embedding (f unused on leaves)
            "w_leaf": xavier(k1, (d, 3 * h), d, h),
            "b_leaf": jnp.zeros((3 * h,), jnp.float32),
            # composer: i, f_l, f_r, o, u from (h_l, h_r)
            "w_comp": xavier(k2, (2 * h, 5 * h), 2 * h, h),
            "b_comp": jnp.zeros((5 * h,), jnp.float32),
        }
        emb_shape = input_shape[1]
        tree_spec = input_shape[2]
        # Table of three (B, n_nodes) shapes, or one stacked (B, n_nodes[, 3])
        n_nodes = tree_spec[1][1] if isinstance(tree_spec, Table) else tree_spec[1]
        return params, {}, (emb_shape[0], n_nodes, h)

    def _leaf(self, params, x):
        gates = x @ params["w_leaf"] + params["b_leaf"]
        i, o, u = jnp.split(gates, 3, axis=-1)
        c = jax.nn.sigmoid(i) * jnp.tanh(u)
        h = jnp.tanh(c)
        if self.gate_output:
            h = jax.nn.sigmoid(o) * h
        return h, c

    def _compose(self, params, h_l, c_l, h_r, c_r):
        gates = jnp.concatenate([h_l, h_r], axis=-1) @ params["w_comp"] \
            + params["b_comp"]
        i, f_l, f_r, o, u = jnp.split(gates, 5, axis=-1)
        c = (jax.nn.sigmoid(i) * jnp.tanh(u)
             + jax.nn.sigmoid(f_l) * c_l + jax.nn.sigmoid(f_r) * c_r)
        h = jnp.tanh(c)
        if self.gate_output:
            h = jax.nn.sigmoid(o) * h
        return h, c

    def _one_tree(self, params, emb, left, right, word):
        n_nodes = left.shape[0]
        hsize = self.hidden_size
        h_buf = jnp.zeros((n_nodes, hsize), emb.dtype)
        c_buf = jnp.zeros((n_nodes, hsize), emb.dtype)

        def step(carry, idx):
            h_all, c_all = carry
            l, r, w = left[idx], right[idx], word[idx]
            is_leaf = l < 0
            x = emb[jnp.clip(w, 0, emb.shape[0] - 1)]
            h_leaf, c_leaf = self._leaf(params, x)
            h_l = h_all[jnp.clip(l, 0, n_nodes - 1)]
            c_l = c_all[jnp.clip(l, 0, n_nodes - 1)]
            h_r = h_all[jnp.clip(r, 0, n_nodes - 1)]
            c_r = c_all[jnp.clip(r, 0, n_nodes - 1)]
            h_comp, c_comp = self._compose(params, h_l, c_l, h_r, c_r)
            h_new = jnp.where(is_leaf, h_leaf, h_comp)
            c_new = jnp.where(is_leaf, c_leaf, c_comp)
            # padding node (leaf-coded but word < 0): zero state
            is_pad = jnp.logical_and(is_leaf, w < 0)
            h_new = jnp.where(is_pad, 0.0, h_new)
            c_new = jnp.where(is_pad, 0.0, c_new)
            return (h_all.at[idx].set(h_new), c_all.at[idx].set(c_new)), None

        (h_all, _), _ = lax.scan(step, (h_buf, c_buf), jnp.arange(n_nodes))
        return h_all

    def apply(self, params, state, x, *, training=False, rng=None):
        emb, tree = x[1], x[2]
        if isinstance(tree, Table):
            left, right, word = tree[1], tree[2], tree[3]
        else:  # stacked (B, n_nodes, 3)
            left, right, word = tree[..., 0], tree[..., 1], tree[..., 2]
        out = jax.vmap(lambda e, l, r, w: self._one_tree(params, e, l, r, w)
                       )(emb, left.astype(jnp.int32), right.astype(jnp.int32),
                         word.astype(jnp.int32))
        return out, state
