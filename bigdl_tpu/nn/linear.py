"""Dense layers.

Reference: nn/Linear.scala:83-153 (addmm -> gemm -> MKL vsgemm).  Here the
matmul is a plain `x @ W` that XLA tiles onto the MXU; weight layout is
(in, out) so no transpose appears in the hot path (the reference stores
(out, in) and transposes — an MKL-ism with no TPU benefit).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module


class Linear(Module):
    """y = x @ W + b.  reference: nn/Linear.scala:83-153."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 weight_init=None, bias_init=None,
                 w_regularizer=None, b_regularizer=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.weight_init = weight_init or init_mod.Xavier()
        self.bias_init = bias_init or init_mod.Zeros()
        # reference: wRegularizer/bRegularizer (nn/Linear.scala ctor),
        # applied by the trainer via optim.regularizer.collect_regularizers
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def set_init_method(self, weight_init=None, bias_init=None) -> "Linear":
        if weight_init is not None:
            self.weight_init = weight_init
        if bias_init is not None:
            self.bias_init = bias_init
        return self

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        fan_in, fan_out = self.input_size, self.output_size
        params = {"weight": self.weight_init(k_w, (fan_in, fan_out), fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (fan_out,), fan_in, fan_out)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = x @ params["weight"]
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_size,)


class SparseLinear(Linear):
    """Linear over sparse-ish inputs (reference: nn/SparseLinear.scala).

    The reference multiplies a COO SparseTensor against dense weights for
    wide-and-deep style features.  On TPU, scatter/gather-heavy sparse gemm
    loses to a dense matmul on the MXU for the feature widths BigDL targets,
    so the TPU-native design densifies at the input pipeline and reuses the
    dense kernel; the class exists for API parity and accepts already-dense
    input (e.g. multi-hot encoded).
    """

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 backward_start: int = -1, backward_length: int = -1,
                 name: Optional[str] = None):
        super().__init__(input_size, output_size, with_bias, name=name)
        self.backward_start = backward_start
        self.backward_length = backward_length
