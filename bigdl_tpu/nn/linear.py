"""Dense layers.

Reference: nn/Linear.scala:83-153 (addmm -> gemm -> MKL vsgemm).  Here the
matmul is a plain `x @ W` that XLA tiles onto the MXU; weight layout is
(in, out) so no transpose appears in the hot path (the reference stores
(out, in) and transposes — an MKL-ism with no TPU benefit).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module


class Linear(Module):
    """y = x @ W + b.  reference: nn/Linear.scala:83-153."""

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 weight_init=None, bias_init=None,
                 w_regularizer=None, b_regularizer=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size
        self.output_size = output_size
        self.with_bias = with_bias
        self.weight_init = weight_init or init_mod.Xavier()
        self.bias_init = bias_init or init_mod.Zeros()
        # reference: wRegularizer/bRegularizer (nn/Linear.scala ctor),
        # applied by the trainer via optim.regularizer.collect_regularizers
        self.w_regularizer = w_regularizer
        self.b_regularizer = b_regularizer

    def set_init_method(self, weight_init=None, bias_init=None) -> "Linear":
        if weight_init is not None:
            self.weight_init = weight_init
        if bias_init is not None:
            self.bias_init = bias_init
        return self

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        fan_in, fan_out = self.input_size, self.output_size
        params = {"weight": self.weight_init(k_w, (fan_in, fan_out), fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (fan_out,), fan_in, fan_out)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = x @ params["weight"]
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_size,)


class SparseLinear(Linear):
    """Linear over sparse inputs (reference: nn/SparseLinear.scala +
    tensor/SparseTensorMath.scala sparse gemm).

    Two input forms:
    - dense (B, input_size) multi-hot — plain MXU matmul (fine for the
      narrow vocabs BigDL's examples use);
    - a device-sparse bag pair `(ids, values)` / `Table(ids, values)` with
      ids (B, nnz) int padded -1 and values (B, nnz) — the wide-vocab
      path: y[b] = Σ_j values[b,j] · W[ids[b,j], :] + bias, computed as a
      batched row gather + masked weighted reduce.  Work and HBM traffic
      scale with nnz, not input_size; the gradient w.r.t. W is the gather
      transpose (a scatter-add XLA emits natively), never a dense
      (B, input_size) one-hot.  Equivalent to segment_sum over COO with
      static-size segments — the jit/TPU-friendly layout.  Batches in this
      form come from `VarLenFeature(..., encoding='bag')`.
    """

    def __init__(self, input_size: int, output_size: int, with_bias: bool = True,
                 backward_start: int = -1, backward_length: int = -1,
                 name: Optional[str] = None):
        super().__init__(input_size, output_size, with_bias, name=name)
        self.backward_start = backward_start
        self.backward_length = backward_length

    def apply(self, params, state, x, *, training=False, rng=None):
        from bigdl_tpu.core.table import Table
        if isinstance(x, (Table, tuple, list)):
            seq = list(x)
            if len(seq) != 2:
                raise ValueError(
                    f"SparseLinear bag input needs (ids, values), got "
                    f"{len(seq)} components")
            ids, vals = seq
            valid = ids >= 0
            safe = jnp.maximum(ids, 0).astype(jnp.int32)
            rows = params["weight"][safe]                 # (B, nnz, out)
            w = jnp.where(valid, vals, 0).astype(rows.dtype)
            y = jnp.einsum("bn,bno->bo", w, rows)
            if self.with_bias:
                y = y + params["bias"]
            return y, state
        return super().apply(params, state, x, training=training, rng=rng)

    def output_shape(self, input_shape):
        from bigdl_tpu.core.table import Table
        if isinstance(input_shape, (Table, tuple, list)):
            shapes = list(input_shape)
            if len(shapes) == 2 and isinstance(shapes[0], (tuple, list)):
                return (tuple(shapes[0])[0], self.output_size)  # (B, out)
        return super().output_shape(input_shape)
