"""Shape-manipulation layers.

Reference: nn/Reshape.scala, nn/View.scala, nn/Squeeze.scala,
nn/Unsqueeze.scala, nn/Transpose.scala, nn/Contiguous.scala,
nn/Identity.scala, nn/Select.scala, nn/Narrow.scala, nn/SplitTable.scala,
nn/JoinTable.scala, nn/Padding.scala.  All are metadata ops or cheap copies
under XLA; `Contiguous` is the identity (XLA owns layouts).

Axis convention: 0-based with negative indexing, batch dim included
(the reference is 1-based with batch handled via `batchMode` flags).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Module


class Reshape(Module):
    """Reshape non-batch dims. reference: nn/Reshape.scala."""

    def __init__(self, size: Sequence[int], batch_mode: Optional[bool] = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, state, x, *, training=False, rng=None):
        if self.batch_mode:
            return jnp.reshape(x, (x.shape[0],) + self.size), state
        return jnp.reshape(x, self.size), state

    def output_shape(self, input_shape):
        if self.batch_mode:
            return (input_shape[0],) + self.size
        return self.size


class View(Module):
    """Reshape with one -1 wildcard allowed. reference: nn/View.scala."""

    def __init__(self, *sizes: int, num_input_dims: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.sizes = tuple(sizes[0]) if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)) else tuple(sizes)

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.reshape(x, (x.shape[0],) + self.sizes), state

    def output_shape(self, input_shape):
        n = input_shape[0]
        if -1 in self.sizes:
            total = int(np.prod(input_shape[1:]))
            known = -int(np.prod(self.sizes))
            out = tuple(total // known if s == -1 else s for s in self.sizes)
            return (n,) + out
        return (n,) + self.sizes


class Flatten(Module):
    """Flatten non-batch dims (keras-style; reference InferReshape(-1))."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.reshape(x, (x.shape[0], -1)), state

    def output_shape(self, input_shape):
        return (input_shape[0], int(np.prod(input_shape[1:])))


class Squeeze(Module):
    def __init__(self, dim: Optional[int] = None, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.squeeze(x, axis=self.dim), state

    def output_shape(self, input_shape):
        if self.dim is None:
            return tuple(s for s in input_shape if s != 1)
        d = self.dim % len(input_shape)
        if input_shape[d] != 1:
            raise ValueError(
                f"{self.name}: cannot squeeze dim {self.dim} of size {input_shape[d]}")
        return tuple(s for i, s in enumerate(input_shape) if i != d)


class Unsqueeze(Module):
    def __init__(self, dim: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.expand_dims(x, self.dim), state

    def output_shape(self, input_shape):
        s = list(input_shape)
        s.insert(self.dim % (len(s) + 1), 1)
        return tuple(s)


class Transpose(Module):
    """Swap listed axis pairs in order. reference: nn/Transpose.scala."""

    def __init__(self, permutations: Sequence[Tuple[int, int]], name: Optional[str] = None):
        super().__init__(name)
        self.permutations = [tuple(p) for p in permutations]

    def _perm(self, ndim):
        axes = list(range(ndim))
        for a, b in self.permutations:
            axes[a], axes[b] = axes[b], axes[a]
        return axes

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.transpose(x, self._perm(x.ndim)), state

    def output_shape(self, input_shape):
        return tuple(input_shape[i] for i in self._perm(len(input_shape)))


class Contiguous(Module):
    """No-op on TPU (XLA owns memory layout). reference: nn/Contiguous.scala."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return x, state


class Identity(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return x, state


class Select(Module):
    """Index one slice along an axis. reference: nn/Select.scala."""

    def __init__(self, dim: int, index: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.index = dim, index

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.take(x, self.index, axis=self.dim), state

    def output_shape(self, input_shape):
        return tuple(s for i, s in enumerate(input_shape) if i != self.dim % len(input_shape))


class Narrow(Module):
    """Slice [offset, offset+length) along an axis. reference: nn/Narrow.scala."""

    def __init__(self, dim: int, offset: int, length: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.offset, self.length = dim, offset, length

    def apply(self, params, state, x, *, training=False, rng=None):
        idx = [slice(None)] * x.ndim
        idx[self.dim] = slice(self.offset, self.offset + self.length)
        return x[tuple(idx)], state

    def output_shape(self, input_shape):
        s = list(input_shape)
        s[self.dim] = self.length
        return tuple(s)


class SplitTable(Module):
    """Split an axis into a Table of slices. reference: nn/SplitTable.scala."""

    def __init__(self, dim: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        n = x.shape[self.dim]
        parts = jnp.split(x, n, axis=self.dim)
        t = Table(*[jnp.squeeze(p, axis=self.dim) for p in parts])
        return t, state


class JoinTable(Module):
    """Concatenate a Table of tensors along an axis. reference: nn/JoinTable.scala."""

    def __init__(self, dim: int, n_input_dims: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        parts = list(x)
        return jnp.concatenate(parts, axis=self.dim), state

    def output_shape(self, input_shape):
        shapes = list(input_shape)
        out = list(shapes[0])
        out[self.dim] = sum(s[self.dim] for s in shapes)
        return tuple(out)


class Padding(Module):
    """Pad `pad` entries (sign = side) along a dim. reference: nn/Padding.scala."""

    def __init__(self, dim: int, pad: int, n_input_dim: int = 0, value: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.pad, self.value = dim, pad, value

    def apply(self, params, state, x, *, training=False, rng=None):
        widths = [(0, 0)] * x.ndim
        widths[self.dim] = (abs(self.pad), 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value), state

    def output_shape(self, input_shape):
        s = list(input_shape)
        s[self.dim] += abs(self.pad)
        return tuple(s)
