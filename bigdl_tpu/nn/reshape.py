"""Shape-manipulation layers.

Reference: nn/Reshape.scala, nn/View.scala, nn/Squeeze.scala,
nn/Unsqueeze.scala, nn/Transpose.scala, nn/Contiguous.scala,
nn/Identity.scala, nn/Select.scala, nn/Narrow.scala, nn/SplitTable.scala,
nn/JoinTable.scala, nn/Padding.scala.  All are metadata ops or cheap copies
under XLA; `Contiguous` is the identity (XLA owns layouts).

Axis convention: 0-based with negative indexing, batch dim included
(the reference is 1-based with batch handled via `batchMode` flags).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Module


class Reshape(Module):
    """Reshape non-batch dims. reference: nn/Reshape.scala."""

    def __init__(self, size: Sequence[int], batch_mode: Optional[bool] = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)
        self.batch_mode = batch_mode

    def apply(self, params, state, x, *, training=False, rng=None):
        if self.batch_mode:
            return jnp.reshape(x, (x.shape[0],) + self.size), state
        return jnp.reshape(x, self.size), state

    def output_shape(self, input_shape):
        if self.batch_mode:
            return (input_shape[0],) + self.size
        return self.size


class View(Module):
    """Reshape with one -1 wildcard allowed. reference: nn/View.scala."""

    def __init__(self, *sizes: int, num_input_dims: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.sizes = tuple(sizes[0]) if len(sizes) == 1 and isinstance(sizes[0], (tuple, list)) else tuple(sizes)

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.reshape(x, (x.shape[0],) + self.sizes), state

    def output_shape(self, input_shape):
        n = input_shape[0]
        if -1 in self.sizes:
            total = int(np.prod(input_shape[1:]))
            known = -int(np.prod(self.sizes))
            out = tuple(total // known if s == -1 else s for s in self.sizes)
            return (n,) + out
        return (n,) + self.sizes


class Flatten(Module):
    """Flatten non-batch dims (keras-style; reference InferReshape(-1))."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.reshape(x, (x.shape[0], -1)), state

    def output_shape(self, input_shape):
        return (input_shape[0], int(np.prod(input_shape[1:])))


class Squeeze(Module):
    def __init__(self, dim: Optional[int] = None, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.squeeze(x, axis=self.dim), state

    def output_shape(self, input_shape):
        if self.dim is None:
            return tuple(s for s in input_shape if s != 1)
        d = self.dim % len(input_shape)
        if input_shape[d] != 1:
            raise ValueError(
                f"{self.name}: cannot squeeze dim {self.dim} of size {input_shape[d]}")
        return tuple(s for i, s in enumerate(input_shape) if i != d)


class Unsqueeze(Module):
    def __init__(self, dim: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.expand_dims(x, self.dim), state

    def output_shape(self, input_shape):
        s = list(input_shape)
        s.insert(self.dim % (len(s) + 1), 1)
        return tuple(s)


class Transpose(Module):
    """Swap listed axis pairs in order. reference: nn/Transpose.scala."""

    def __init__(self, permutations: Sequence[Tuple[int, int]], name: Optional[str] = None):
        super().__init__(name)
        self.permutations = [tuple(p) for p in permutations]

    def _perm(self, ndim):
        axes = list(range(ndim))
        for a, b in self.permutations:
            axes[a], axes[b] = axes[b], axes[a]
        return axes

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.transpose(x, self._perm(x.ndim)), state

    def output_shape(self, input_shape):
        return tuple(input_shape[i] for i in self._perm(len(input_shape)))


class Contiguous(Module):
    """No-op on TPU (XLA owns memory layout). reference: nn/Contiguous.scala."""

    def apply(self, params, state, x, *, training=False, rng=None):
        return x, state


class Identity(Module):
    def apply(self, params, state, x, *, training=False, rng=None):
        return x, state


class Select(Module):
    """Index one slice along an axis. reference: nn/Select.scala."""

    def __init__(self, dim: int, index: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.index = dim, index

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.take(x, self.index, axis=self.dim), state

    def output_shape(self, input_shape):
        return tuple(s for i, s in enumerate(input_shape) if i != self.dim % len(input_shape))


class Narrow(Module):
    """Slice [offset, offset+length) along an axis. reference: nn/Narrow.scala."""

    def __init__(self, dim: int, offset: int, length: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.offset, self.length = dim, offset, length

    def apply(self, params, state, x, *, training=False, rng=None):
        idx = [slice(None)] * x.ndim
        idx[self.dim] = slice(self.offset, self.offset + self.length)
        return x[tuple(idx)], state

    def output_shape(self, input_shape):
        s = list(input_shape)
        s[self.dim] = self.length
        return tuple(s)


class SplitTable(Module):
    """Split an axis into a Table of slices. reference: nn/SplitTable.scala."""

    def __init__(self, dim: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        n = x.shape[self.dim]
        parts = jnp.split(x, n, axis=self.dim)
        t = Table(*[jnp.squeeze(p, axis=self.dim) for p in parts])
        return t, state


class JoinTable(Module):
    """Concatenate a Table of tensors along an axis. reference: nn/JoinTable.scala."""

    def __init__(self, dim: int, n_input_dims: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def apply(self, params, state, x, *, training=False, rng=None):
        parts = list(x)
        return jnp.concatenate(parts, axis=self.dim), state

    def output_shape(self, input_shape):
        shapes = list(input_shape)
        out = list(shapes[0])
        out[self.dim] = sum(s[self.dim] for s in shapes)
        return tuple(out)


class Padding(Module):
    """Pad `pad` entries (sign = side) along a dim. reference: nn/Padding.scala."""

    def __init__(self, dim: int, pad: int, n_input_dim: int = 0, value: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dim, self.pad, self.value = dim, pad, value

    def apply(self, params, state, x, *, training=False, rng=None):
        widths = [(0, 0)] * x.ndim
        widths[self.dim] = (abs(self.pad), 0) if self.pad < 0 else (0, self.pad)
        return jnp.pad(x, widths, constant_values=self.value), state

    def output_shape(self, input_shape):
        s = list(input_shape)
        s[self.dim] += abs(self.pad)
        return tuple(s)


class SpatialZeroPadding(Module):
    """Zero-pad NHWC spatial dims (left, right, top, bottom).
    reference: nn/SpatialZeroPadding.scala."""

    def __init__(self, pad_left: int, pad_right: int, pad_top: int,
                 pad_bottom: int, name: Optional[str] = None):
        super().__init__(name)
        self.pads = (pad_left, pad_right, pad_top, pad_bottom)

    def apply(self, params, state, x, *, training=False, rng=None):
        l, r, t, b = self.pads
        return jnp.pad(x, [(0, 0), (t, b), (l, r), (0, 0)]), state

    def output_shape(self, input_shape):
        n, h, w, c = input_shape
        l, r, t, b = self.pads
        return (n, h + t + b, w + l + r, c)


class Cropping2D(Module):
    """Crop ((top, bottom), (left, right)) off NHWC spatial dims.
    reference: nn/Cropping2D.scala."""

    def __init__(self, height_crop: Sequence[int] = (0, 0),
                 width_crop: Sequence[int] = (0, 0), name: Optional[str] = None):
        super().__init__(name)
        self.hc = tuple(height_crop)
        self.wc = tuple(width_crop)

    def apply(self, params, state, x, *, training=False, rng=None):
        (t, b), (l, r) = self.hc, self.wc
        h, w = x.shape[1], x.shape[2]
        return x[:, t:h - b or None, l:w - r or None, :], state

    def output_shape(self, input_shape):
        n, h, w, c = input_shape
        return (n, h - sum(self.hc), w - sum(self.wc), c)


class UpSampling1D(Module):
    """Repeat each timestep `length` times on (N, T, C).
    reference: nn/UpSampling1D.scala."""

    def __init__(self, length: int = 2, name: Optional[str] = None):
        super().__init__(name)
        self.length = length

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.repeat(x, self.length, axis=1), state

    def output_shape(self, input_shape):
        n, t, c = input_shape
        return (n, t * self.length, c)


class UpSampling2D(Module):
    """Nearest-neighbour upsampling of NHWC by (sh, sw).
    reference: nn/UpSampling2D.scala."""

    def __init__(self, size: Sequence[int] = (2, 2), name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def apply(self, params, state, x, *, training=False, rng=None):
        sh, sw = self.size
        return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2), state

    def output_shape(self, input_shape):
        n, h, w, c = input_shape
        return (n, h * self.size[0], w * self.size[1], c)


class UpSampling3D(Module):
    """Nearest-neighbour upsampling of NDHWC by (sd, sh, sw).
    reference: nn/UpSampling3D.scala."""

    def __init__(self, size: Sequence[int] = (2, 2, 2), name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def apply(self, params, state, x, *, training=False, rng=None):
        sd, sh, sw = self.size
        y = jnp.repeat(x, sd, axis=1)
        y = jnp.repeat(y, sh, axis=2)
        return jnp.repeat(y, sw, axis=3), state

    def output_shape(self, input_shape):
        n, d, h, w, c = input_shape
        return (n, d * self.size[0], h * self.size[1], w * self.size[2], c)


class Cropping3D(Module):
    """Crop ((front, back), (top, bottom), (left, right)) off NDHWC volumes.
    reference: nn/Cropping3D.scala."""

    def __init__(self, dim1_crop: Sequence[int] = (1, 1),
                 dim2_crop: Sequence[int] = (1, 1),
                 dim3_crop: Sequence[int] = (1, 1), name: Optional[str] = None):
        super().__init__(name)
        self.crops = (tuple(dim1_crop), tuple(dim2_crop), tuple(dim3_crop))

    def apply(self, params, state, x, *, training=False, rng=None):
        (f, bk), (t, b), (l, r) = self.crops
        d, h, w = x.shape[1:4]
        return x[:, f:d - bk or None, t:h - b or None, l:w - r or None, :], state

    def output_shape(self, input_shape):
        n, d, h, w, c = input_shape
        return (n, d - sum(self.crops[0]), h - sum(self.crops[1]),
                w - sum(self.crops[2]), c)


class VolumetricZeroPadding(Module):
    """Zero-pad NDHWC dims symmetrically per spatial axis.
    reference: the keras ZeroPadding3D wrapper's core
    (nn/keras/ZeroPadding3D.scala pads the 3 spatial dims of 5-D input)."""

    def __init__(self, pad_d: int = 1, pad_h: int = 1, pad_w: int = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        self.pads = (pad_d, pad_h, pad_w)

    def apply(self, params, state, x, *, training=False, rng=None):
        d, h, w = self.pads
        return jnp.pad(x, [(0, 0), (d, d), (h, h), (w, w), (0, 0)]), state

    def output_shape(self, input_shape):
        n, D, H, W, c = input_shape
        d, h, w = self.pads
        return (n, D + 2 * d, H + 2 * h, W + 2 * w, c)
