"""Graph container — DAG execution.

Reference: nn/Graph.scala:72 (node DAG; backward graph is derived by
reversing edges) and nn/StaticGraph.scala:44,82-84 (pre-topo-sorted
execution arrays).  Here the DAG is topo-sorted once at construction and
`apply` walks it in order; the backward graph never exists because jax.grad
differentiates the whole walk.  BigDL's DynamicGraph/Scheduler/FrameManager
(TF-style control-flow frames) has no analogue: data-dependent control flow
inside jit is expressed with lax.cond/lax.while_loop at the layer level.

Build a graph with the node-calling sugar:

    inp = Input()
    h = Linear(10, 20)(inp)
    out = ReLU()(h)
    model = Graph(inp, out)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax

from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Container, Module, Node, child_rng


class Graph(Container):
    """Static DAG of modules. reference: nn/Graph.scala, nn/StaticGraph.scala."""

    def __init__(self, inputs: Union[Node, Sequence[Node]],
                 outputs: Union[Node, Sequence[Node]],
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_nodes: List[Node] = [inputs] if isinstance(inputs, Node) else list(inputs)
        self.output_nodes: List[Node] = [outputs] if isinstance(outputs, Node) else list(outputs)
        self.topo: List[Node] = self._topo_sort()
        for node in self.topo:
            if node.module is not None:
                self.children[node.name] = node.module

    def _topo_sort(self) -> List[Node]:
        """DFS post-order from outputs (reference: utils/DirectedGraph.scala
        topologySort, executed backwards from the output like StaticGraph)."""
        visited: Dict[int, bool] = {}
        order: List[Node] = []

        def visit(node: Node):
            if id(node) in visited:
                if not visited[id(node)]:
                    raise ValueError("cycle detected in Graph")
                return
            visited[id(node)] = False
            for p in node.prevs:
                visit(p)
            visited[id(node)] = True
            order.append(node)

        for out in self.output_nodes:
            visit(out)
        return order

    def _gather_inputs(self, node: Node, values: Dict[int, Any]) -> Any:
        ins = [values[id(p)] for p in node.prevs]
        return ins[0] if len(ins) == 1 else Table(*ins)

    def build(self, rng, input_shape):
        shapes_in = [input_shape] if not isinstance(input_shape, (list, Table)) else list(input_shape)
        if len(shapes_in) != len(self.input_nodes):
            raise ValueError(f"graph has {len(self.input_nodes)} inputs, got {len(shapes_in)} shapes")
        shape_vals: Dict[int, Any] = {}
        for node, sh in zip(self.input_nodes, shapes_in):
            shape_vals[id(node)] = tuple(sh)
        params, state = {}, {}
        for i, node in enumerate(self.topo):
            if node.module is None:
                if id(node) not in shape_vals:
                    raise ValueError(f"unbound graph input {node.name}")
                continue
            sh = self._gather_inputs(node, shape_vals)
            p, s, out = node.module.build(jax.random.fold_in(rng, i), sh)
            params[node.name], state[node.name] = p, s
            shape_vals[id(node)] = out
        outs = [shape_vals[id(n)] for n in self.output_nodes]
        return params, state, outs[0] if len(outs) == 1 else Table(*outs)

    def apply(self, params, state, x, *, training=False, rng=None):
        xs = [x] if not isinstance(x, (list, tuple, Table)) else list(x)
        if len(xs) != len(self.input_nodes):
            raise ValueError(
                f"graph has {len(self.input_nodes)} inputs, got {len(xs)} activities")
        values: Dict[int, Any] = {}
        for node, v in zip(self.input_nodes, xs):
            values[id(node)] = v
        new_state: Dict[str, Any] = {}
        for i, node in enumerate(self.topo):
            if node.module is None:
                continue
            inp = self._gather_inputs(node, values)
            y, s = node.module.apply(params[node.name], state[node.name], inp,
                                     training=training, rng=child_rng(rng, i))
            values[id(node)] = y
            new_state[node.name] = s
        outs = [values[id(n)] for n in self.output_nodes]
        return (outs[0] if len(outs) == 1 else Table(*outs)), new_state

    def output_shape(self, input_shape):
        raise NotImplementedError("use build() for graph shape inference")


# Name-parity aliases.  The reference splits Graph into StaticGraph
# (pre-topo-sorted execution arrays, nn/StaticGraph.scala:44) and
# DynamicGraph (breadth-first Scheduler/FrameManager control flow,
# nn/DynamicGraph.scala:28).  Under XLA the whole walk is traced once and
# compiled, so one Graph serves both roles; data-dependent control flow is
# expressed with the structured ops (nn.ops.Cond / nn.ops.WhileLoop).
StaticGraph = Graph
DynamicGraph = Graph
