"""Activation layers.

Reference: nn/ReLU.scala, nn/Tanh.scala, nn/Sigmoid.scala, nn/SoftMax.scala,
nn/LogSoftMax.scala, nn/ELU.scala, nn/LeakyReLU.scala, nn/PReLU.scala,
nn/HardTanh.scala, nn/SoftPlus.scala, nn/SoftSign.scala, nn/ReLU6.scala.
All are elementwise VPU ops that XLA fuses into neighbouring matmuls/convs;
the reference's in-place (`ip`) variants are meaningless under XLA (buffer
reuse is the compiler's job).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class _Elementwise(Module):
    def _fn(self, x):
        raise NotImplementedError

    def apply(self, params, state, x, *, training=False, rng=None):
        return self._fn(x), state


class ReLU(_Elementwise):
    def __init__(self, ip: bool = False, name: Optional[str] = None):
        super().__init__(name)

    def _fn(self, x):
        return jax.nn.relu(x)


class ReLU6(_Elementwise):
    def _fn(self, x):
        return jnp.clip(x, 0.0, 6.0)


class Tanh(_Elementwise):
    def _fn(self, x):
        return jnp.tanh(x)


class Sigmoid(_Elementwise):
    def _fn(self, x):
        return jax.nn.sigmoid(x)


class SoftMax(_Elementwise):
    def _fn(self, x):
        return jax.nn.softmax(x, axis=-1)


class LogSoftMax(_Elementwise):
    def _fn(self, x):
        return jax.nn.log_softmax(x, axis=-1)


class ELU(_Elementwise):
    def __init__(self, alpha: float = 1.0, ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.alpha = alpha

    def _fn(self, x):
        return jax.nn.elu(x, alpha=self.alpha)


class GELU(_Elementwise):
    def _fn(self, x):
        return jax.nn.gelu(x)


class SiLU(_Elementwise):
    def _fn(self, x):
        return jax.nn.silu(x)


class LeakyReLU(_Elementwise):
    def __init__(self, negval: float = 0.01, ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.negval = negval

    def _fn(self, x):
        return jax.nn.leaky_relu(x, negative_slope=self.negval)


class PReLU(Module):
    """Learnable negative slope per channel. reference: nn/PReLU.scala."""

    def __init__(self, n_output_plane: int = 0, name: Optional[str] = None):
        super().__init__(name)
        self.n_output_plane = n_output_plane  # 0 = single shared slope

    def build(self, rng, input_shape):
        n = self.n_output_plane if self.n_output_plane > 0 else 1
        return {"weight": jnp.full((n,), 0.25, jnp.float32)}, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        w = params["weight"]
        return jnp.where(x >= 0, x, x * w), state


class HardTanh(_Elementwise):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.min_value, self.max_value = min_value, max_value

    def _fn(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class HardSigmoid(_Elementwise):
    def _fn(self, x):
        return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


class SoftPlus(_Elementwise):
    def __init__(self, beta: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.beta = beta

    def _fn(self, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(_Elementwise):
    def _fn(self, x):
        return x / (1.0 + jnp.abs(x))
