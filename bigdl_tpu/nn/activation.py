"""Activation layers.

Reference: nn/ReLU.scala, nn/Tanh.scala, nn/Sigmoid.scala, nn/SoftMax.scala,
nn/LogSoftMax.scala, nn/ELU.scala, nn/LeakyReLU.scala, nn/PReLU.scala,
nn/HardTanh.scala, nn/SoftPlus.scala, nn/SoftSign.scala, nn/ReLU6.scala.
All are elementwise VPU ops that XLA fuses into neighbouring matmuls/convs;
the reference's in-place (`ip`) variants are meaningless under XLA (buffer
reuse is the compiler's job).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module


class _Elementwise(Module):
    def _fn(self, x):
        raise NotImplementedError

    def apply(self, params, state, x, *, training=False, rng=None):
        return self._fn(x), state


class ReLU(_Elementwise):
    def __init__(self, ip: bool = False, name: Optional[str] = None):
        super().__init__(name)

    def _fn(self, x):
        return jax.nn.relu(x)


class ReLU6(_Elementwise):
    def _fn(self, x):
        return jnp.clip(x, 0.0, 6.0)


class Tanh(_Elementwise):
    def _fn(self, x):
        return jnp.tanh(x)


class Sigmoid(_Elementwise):
    def _fn(self, x):
        return jax.nn.sigmoid(x)


class SoftMax(_Elementwise):
    def _fn(self, x):
        return jax.nn.softmax(x, axis=-1)


class LogSoftMax(_Elementwise):
    def _fn(self, x):
        return jax.nn.log_softmax(x, axis=-1)


class ELU(_Elementwise):
    def __init__(self, alpha: float = 1.0, ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.alpha = alpha

    def _fn(self, x):
        return jax.nn.elu(x, alpha=self.alpha)


class GELU(_Elementwise):
    def _fn(self, x):
        return jax.nn.gelu(x)


class SiLU(_Elementwise):
    def _fn(self, x):
        return jax.nn.silu(x)


class LeakyReLU(_Elementwise):
    def __init__(self, negval: float = 0.01, ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.negval = negval

    def _fn(self, x):
        return jax.nn.leaky_relu(x, negative_slope=self.negval)


class PReLU(Module):
    """Learnable negative slope per channel. reference: nn/PReLU.scala.
    `shape` overrides the per-channel layout with an explicit broadcastable
    alpha shape (keras-1 PReLU learns one slope per ELEMENT over the full
    feature shape)."""

    def __init__(self, n_output_plane: int = 0, shape=None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_output_plane = n_output_plane  # 0 = single shared slope
        self.shape = tuple(shape) if shape else None

    def build(self, rng, input_shape):
        if self.shape is not None:
            return ({"weight": jnp.full(self.shape, 0.25, jnp.float32)},
                    {}, input_shape)
        n = self.n_output_plane if self.n_output_plane > 0 else 1
        return {"weight": jnp.full((n,), 0.25, jnp.float32)}, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        w = params["weight"]
        return jnp.where(x >= 0, x, x * w), state


class HardTanh(_Elementwise):
    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.min_value, self.max_value = min_value, max_value

    def _fn(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class HardSigmoid(_Elementwise):
    def _fn(self, x):
        return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


class SoftPlus(_Elementwise):
    def __init__(self, beta: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.beta = beta

    def _fn(self, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(_Elementwise):
    def _fn(self, x):
        return x / (1.0 + jnp.abs(x))


class SoftMin(_Elementwise):
    """softmax of -x. reference: nn/SoftMin.scala."""

    def _fn(self, x):
        return jax.nn.softmax(-x, axis=-1)


class LogSigmoid(_Elementwise):
    """log(sigmoid(x)). reference: nn/LogSigmoid.scala."""

    def _fn(self, x):
        return jax.nn.log_sigmoid(x)


class HardShrink(_Elementwise):
    """0 inside [-lambda, lambda], identity outside. reference: nn/HardShrink.scala."""

    def __init__(self, lambd: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.lambd = lambd

    def _fn(self, x):
        return jnp.where(jnp.abs(x) > self.lambd, x, 0.0)


class SoftShrink(_Elementwise):
    """Shrink towards zero by lambda. reference: nn/SoftShrink.scala."""

    def __init__(self, lambd: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.lambd = lambd

    def _fn(self, x):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.lambd, 0.0)


class TanhShrink(_Elementwise):
    """x - tanh(x). reference: nn/TanhShrink.scala."""

    def _fn(self, x):
        return x - jnp.tanh(x)


class Threshold(_Elementwise):
    """x where x > th else value. reference: nn/Threshold.scala."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, ip: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.th, self.v = th, v

    def _fn(self, x):
        return jnp.where(x > self.th, x, self.v)


class BinaryThreshold(_Elementwise):
    """1 where x > th else 0. reference: nn/BinaryThreshold.scala."""

    def __init__(self, th: float = 1e-6, ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.th = th

    def _fn(self, x):
        return (x > self.th).astype(x.dtype)


class RReLU(Module):
    """Randomized leaky ReLU: slope ~ U[lower, upper] per element at train
    time, fixed mean slope at eval. reference: nn/RReLU.scala."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3,
                 ip: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.lower, self.upper = lower, upper

    def apply(self, params, state, x, *, training=False, rng=None):
        if training:
            if rng is None:
                raise ValueError("RReLU in training mode requires an rng")
            slope = jax.random.uniform(rng, x.shape, x.dtype,
                                       self.lower, self.upper)
        else:
            slope = (self.lower + self.upper) / 2.0
        return jnp.where(x >= 0, x, x * slope), state


class SReLU(Module):
    """S-shaped ReLU with four learned per-channel params (t_left, a_left,
    t_right, a_right). reference: nn/SReLU.scala."""

    def __init__(self, shape=None, share_axes=None, name: Optional[str] = None):
        super().__init__(name)
        self.shape = tuple(shape) if shape else None
        self.share_axes = tuple(share_axes) if share_axes else None

    def _param_shape(self, input_shape):
        shp = list(self.shape or input_shape[1:])
        if self.share_axes:
            for ax in self.share_axes:
                shp[ax - 1] = 1  # share_axes count feature dims from 1
        return tuple(shp)

    def build(self, rng, input_shape):
        ps = self._param_shape(input_shape)
        k = jax.random.split(rng, 2)
        params = {
            "t_left": jnp.zeros(ps, jnp.float32),
            "a_left": jnp.zeros(ps, jnp.float32),
            "t_right": jax.random.uniform(k[0], ps, jnp.float32, 0.0, 1.0),
            "a_right": jnp.ones(ps, jnp.float32),
        }
        return params, {}, input_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        y = jnp.where(x >= tr, tr + ar * (x - tr), x)
        y = jnp.where(x <= tl, tl + al * (x - tl), y)
        return y, state
