"""Volumetric (3-D) convolution and pooling layers (NDHWC, DHWIO kernels).

Reference: nn/VolumetricConvolution.scala, nn/VolumetricFullConvolution.scala,
nn/VolumetricMaxPooling.scala, nn/VolumetricAveragePooling.scala.  The
reference unfolds 3-D volumes into im2col matrices per output frame; here a
single `lax.conv_general_dilated` over three spatial dims hits the MXU
directly.

Argument order mirrors the reference: (kT, kW, kH, dT, dW, dH, padT, padW,
padH) — temporal first, then width, then height.  Internally everything is
(D=T, H, W) with NDHWC activations.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn import init as init_mod
from bigdl_tpu.nn.module import Module
from bigdl_tpu.nn.pooling import _pool_out, _window_pad

_DIMSPEC_3D = ("NDHWC", "DHWIO", "NDHWC")


def _conv_out(size: int, k: int, stride: int, pad: int) -> int:
    if pad == -1:  # SAME
        return -(-size // stride)
    return (size + 2 * pad - k) // stride + 1


def _pad3d(pads, in_dhw, kernel, stride):
    out = []
    for p, s, k, st in zip(pads, in_dhw, kernel, stride):
        if p == -1:  # TF-style SAME
            total = max(0, (-(-s // st) - 1) * st + k - s)
            out.append((total // 2, total - total // 2))
        else:
            out.append((p, p))
    return out


class VolumetricConvolution(Module):
    """3-D convolution. reference: nn/VolumetricConvolution.scala."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.n_input = n_input_plane
        self.n_output = n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.with_bias = with_bias
        self.weight_init = init_mod.MsraFiller(False)
        self.bias_init = init_mod.Zeros()

    def set_init_method(self, weight_init=None, bias_init=None):
        if weight_init is not None:
            self.weight_init = weight_init
        if bias_init is not None:
            self.bias_init = bias_init
        return self

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        kt, kh, kw = self.kernel
        fan_in = self.n_input * kt * kh * kw
        fan_out = self.n_output * kt * kh * kw
        params = {"weight": self.weight_init(
            k_w, (kt, kh, kw, self.n_input, self.n_output), fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (self.n_output,), fan_in, fan_out)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = lax.conv_general_dilated(
            x, params["weight"], window_strides=self.stride,
            padding=_pad3d(self.pad, x.shape[1:4], self.kernel, self.stride),
            dimension_numbers=_DIMSPEC_3D)
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        n, d, h, w, _ = input_shape
        kt, kh, kw = self.kernel
        st, sh, sw = self.stride
        return (n, _conv_out(d, kt, st, self.pad[0]),
                _conv_out(h, kh, sh, self.pad[1]),
                _conv_out(w, kw, sw, self.pad[2]), self.n_output)


class VolumetricFullConvolution(Module):
    """3-D transposed convolution (deconvolution).
    reference: nn/VolumetricFullConvolution.scala (adjT/adjW/adjH extend the
    output on the high side, as in Torch)."""

    def __init__(self, n_input_plane: int, n_output_plane: int,
                 k_t: int, k_w: int, k_h: int,
                 d_t: int = 1, d_w: int = 1, d_h: int = 1,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 adj_t: int = 0, adj_w: int = 0, adj_h: int = 0,
                 with_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        assert adj_t < d_t and adj_w < d_w and adj_h < d_h, \
            "adj must be smaller than the stride"
        self.n_input = n_input_plane
        self.n_output = n_output_plane
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t, d_h, d_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.adj = (adj_t, adj_h, adj_w)
        self.with_bias = with_bias
        self.weight_init = init_mod.MsraFiller(False)
        self.bias_init = init_mod.Zeros()

    def build(self, rng, input_shape):
        k_w, k_b = jax.random.split(rng)
        kt, kh, kw = self.kernel
        fan_in = self.n_input * kt * kh * kw
        fan_out = self.n_output * kt * kh * kw
        params = {"weight": self.weight_init(
            k_w, (kt, kh, kw, self.n_input, self.n_output), fan_in, fan_out)}
        if self.with_bias:
            params["bias"] = self.bias_init(k_b, (self.n_output,), fan_in, fan_out)
        return params, {}, self.output_shape(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        # transposed conv = lhs-dilated conv with flipped-effective padding
        pads = []
        for k, p, a in zip(self.kernel, self.pad, self.adj):
            pads.append((k - 1 - p, k - 1 - p + a))
        y = lax.conv_general_dilated(
            x, jnp.flip(params["weight"], axis=(0, 1, 2)),
            window_strides=(1, 1, 1), padding=pads,
            lhs_dilation=self.stride,
            dimension_numbers=_DIMSPEC_3D)
        if self.with_bias:
            y = y + params["bias"]
        return y, state

    def output_shape(self, input_shape):
        n, d, h, w, _ = input_shape
        out = [(s - 1) * st - 2 * p + k + a
               for s, st, p, k, a in zip((d, h, w), self.stride, self.pad,
                                         self.kernel, self.adj)]
        return (n, *out, self.n_output)


class VolumetricMaxPooling(Module):
    """reference: nn/VolumetricMaxPooling.scala."""

    def __init__(self, k_t: int, k_w: Optional[int] = None, k_h: Optional[int] = None,
                 d_t: Optional[int] = None, d_w: Optional[int] = None,
                 d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 ceil_mode: bool = False, name: Optional[str] = None):
        super().__init__(name)
        k_w = k_t if k_w is None else k_w
        k_h = k_t if k_h is None else k_h
        self.kernel = (k_t, k_h, k_w)
        self.stride = (d_t or k_t, d_h or k_h, d_w or k_w)
        self.pad = (pad_t, pad_h, pad_w)
        self.ceil_mode = ceil_mode

    def set_ceil_mode(self):
        self.ceil_mode = True
        return self

    def _pads(self, dhw):
        return [_window_pad(s, k, st, p, self.ceil_mode)
                for s, k, st, p in zip(dhw, self.kernel, self.stride, self.pad)]

    def apply(self, params, state, x, *, training=False, rng=None):
        pads = self._pads(x.shape[1:4])
        y = lax.reduce_window(
            x, -jnp.inf, lax.max, (1, *self.kernel, 1), (1, *self.stride, 1),
            [(0, 0), *pads, (0, 0)])
        return y, state

    def output_shape(self, input_shape):
        n, d, h, w, c = input_shape
        out = [_pool_out(s, k, st, p, self.ceil_mode)
               for s, k, st, p in zip((d, h, w), self.kernel, self.stride, self.pad)]
        return (n, *out, c)


class VolumetricAveragePooling(VolumetricMaxPooling):
    """reference: nn/VolumetricAveragePooling.scala.  `count_include_pad`
    matches the reference's countIncludePad."""

    def __init__(self, k_t: int, k_w: Optional[int] = None, k_h: Optional[int] = None,
                 d_t: Optional[int] = None, d_w: Optional[int] = None,
                 d_h: Optional[int] = None,
                 pad_t: int = 0, pad_w: int = 0, pad_h: int = 0,
                 ceil_mode: bool = False, count_include_pad: bool = True,
                 name: Optional[str] = None):
        super().__init__(k_t, k_w, k_h, d_t, d_w, d_h, pad_t, pad_w, pad_h,
                         ceil_mode, name=name)
        self.count_include_pad = count_include_pad

    def apply(self, params, state, x, *, training=False, rng=None):
        pads = self._pads(x.shape[1:4])
        window = (1, *self.kernel, 1)
        strides = (1, *self.stride, 1)
        full_pads = [(0, 0), *pads, (0, 0)]
        total = lax.reduce_window(x, 0.0, lax.add, window, strides, full_pads)
        if self.count_include_pad:
            y = total / float(self.kernel[0] * self.kernel[1] * self.kernel[2])
        else:
            ones = jnp.ones(x.shape[1:4], x.dtype)[None, ..., None]
            count = lax.reduce_window(ones, 0.0, lax.add, window, strides, full_pads)
            y = total / count
        return y, state
