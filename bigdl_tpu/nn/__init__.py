"""Torch-style NN module zoo, re-designed functionally for JAX/XLA.

The reference's `AbstractModule` carries hand-written
`updateOutput/updateGradInput/accGradParameters` per layer (reference:
nn/abstractnn/AbstractModule.scala:58).  Here every module is a pure function
`apply(params, state, input) -> (output, state)`; gradients come from
`jax.grad` over the whole model, and the entire forward+backward+update step
lowers to one XLA program — the role BigDL's mkldnn fused `DnnGraph` plays
(nn/mkldnn/DnnGraph.scala:314-415) is played by XLA fusion for free.
"""

from bigdl_tpu.nn.module import Module, Container, Sequential, Node, Input

# name-parity aliases: the reference's DynamicContainer (nn/DynamicContainer
# .scala, the add()-able container base) is our Container; TreeLSTM
# (nn/TreeLSTM.scala, the tree-recursive base) has BinaryTreeLSTM as its one
# concrete implementation here as there
DynamicContainer = Container
from bigdl_tpu.nn.graph import Graph, StaticGraph, DynamicGraph
from bigdl_tpu.nn import init
from bigdl_tpu.nn.linear import Linear, SparseLinear
from bigdl_tpu.nn.conv import (
    SpatialConvolution,
    SpatialDilatedConvolution,
    SpatialSeparableConvolution,
    SpatialFullConvolution,
    TemporalConvolution,
    SpatialShareConvolution,
    SpatialConvolutionBN,
    SpatialConvolutionMap,
    LocallyConnected1D,
    LocallyConnected2D,
    full_connection_table,
    one_to_one_connection_table,
    random_connection_table,
)
from bigdl_tpu.nn.pooling import (
    SpatialMaxPooling,
    SpatialAveragePooling,
    TemporalMaxPooling,
    GlobalAveragePooling2D,
    GlobalMaxPooling2D,
)
from bigdl_tpu.nn.norm import (
    BatchNormalization,
    TemporalBatchNormalization,
    SpatialBatchNormalization,
    LayerNormalization,
    Normalize,
    SpatialCrossMapLRN,
    NormalizeScale,
    SpatialWithinChannelLRN,
    SpatialSubtractiveNormalization,
    SpatialDivisiveNormalization,
    SpatialContrastiveNormalization,
)
from bigdl_tpu.nn.activation import (
    ReLU,
    ReLU6,
    Tanh,
    Sigmoid,
    SoftMax,
    LogSoftMax,
    ELU,
    GELU,
    SiLU,
    LeakyReLU,
    PReLU,
    HardTanh,
    HardSigmoid,
    SoftPlus,
    SoftSign,
)
from bigdl_tpu.nn.dropout import (Dropout, GaussianDropout, GaussianNoise,
                                  SpatialDropout1D, SpatialDropout2D,
                                  SpatialDropout3D, GaussianSampler)
from bigdl_tpu.nn.embedding import LookupTable
from bigdl_tpu.nn.reshape import (
    Reshape,
    View,
    Flatten,
    SpatialZeroPadding,
    Cropping2D,
    UpSampling1D,
    UpSampling2D,
    UpSampling3D,
    Squeeze,
    Unsqueeze,
    Transpose,
    Contiguous,
    Identity,
    Select,
    Narrow,
    SplitTable,
    JoinTable,
    Padding,
    Cropping3D,
    VolumetricZeroPadding,
)
from bigdl_tpu.nn.arithmetic import (
    CAddTable,
    CSubTable,
    CMulTable,
    CDivTable,
    CMaxTable,
    CMinTable,
    CAveTable,
    MM,
    MV,
    Mul,
    Add,
    CMul,
    CAdd,
    Scale,
    MulConstant,
    AddConstant,
    Power,
    Sqrt,
    Square,
    Log,
    Exp,
    Abs,
    Clamp,
    Mean,
    Sum,
    Max,
    Min,
    Cosine,
    DotProduct,
)
from bigdl_tpu.nn.table_ops import ConcatTable, ParallelTable, MapTable, SelectTable, FlattenTable
from bigdl_tpu.nn.concat import Concat, Bottle
from bigdl_tpu.nn.recurrent import (
    RnnCell,
    LSTMCell,
    GRUCell,
    LSTM,
    GRU,
    RnnLayer,
    Recurrent,
    BiRecurrent,
    TimeDistributed,
    LSTMPeephole,
    ConvLSTMPeephole,
    ConvLSTMPeephole3D,
    MultiRNNCell,
    RecurrentDecoder,
)
from bigdl_tpu.nn.attention import (
    MultiHeadAttention,
    TransformerBlock,
    apply_rope,
)
from bigdl_tpu.nn.moe import MoE
from bigdl_tpu.nn.quantized import (
    QuantizedLinear,
    QuantizedSpatialConvolution,
    WeightOnlyInt8,
    calibrate,
    quantize,
)
from bigdl_tpu.nn import ops
from bigdl_tpu.nn import tf_ops
from bigdl_tpu.nn.criterion import (
    Criterion,
    ClassNLLCriterion,
    CrossEntropyCriterion,
    MSECriterion,
    AbsCriterion,
    BCECriterion,
    BCEWithLogitsCriterion,
    SmoothL1Criterion,
    MultiLabelSoftMarginCriterion,
    MarginCriterion,
    HingeEmbeddingCriterion,
    CosineEmbeddingCriterion,
    KLDCriterion,
    DiceCoefficientCriterion,
    L1Cost,
    MultiCriterion,
    ParallelCriterion,
    TimeDistributedCriterion,
    ClassSimplexCriterion,
    DistKLDivCriterion,
    SoftmaxWithCriterion,
)
from bigdl_tpu.nn.activation import (
    SoftMin,
    LogSigmoid,
    HardShrink,
    SoftShrink,
    TanhShrink,
    Threshold,
    BinaryThreshold,
    RReLU,
    SReLU,
)
from bigdl_tpu.nn.structural import (
    Remat,
    ResizeBilinear,
    Negative,
    Echo,
    GradientReversal,
    ActivityRegularization,
    L1Penalty,
    NegativeEntropyPenalty,
    Index,
    Masking,
    MaskedSelect,
    Pack,
    Replicate,
    Reverse,
    Tile,
    InferReshape,
    NarrowTable,
    BifurcateSplitTable,
    CrossProduct,
    DenseToSparse,
    SparseJoinTable,
)
from bigdl_tpu.nn.distance import (
    Euclidean,
    CosineDistance,
    PairwiseDistance,
    Bilinear,
    MixtureTable,
    Maxout,
    Highway,
    LookupTableSparse,
)
from bigdl_tpu.nn.criterion import (
    MarginRankingCriterion,
    MultiMarginCriterion,
    MultiLabelMarginCriterion,
    SoftMarginCriterion,
    L1HingeEmbeddingCriterion,
    CosineDistanceCriterion,
    CosineProximityCriterion,
    DotProductCriterion,
    PGCriterion,
    GaussianCriterion,
    KullbackLeiblerDivergenceCriterion,
    MeanAbsolutePercentageCriterion,
    MeanSquaredLogarithmicCriterion,
    PoissonCriterion,
    SmoothL1CriterionWithWeights,
    TimeDistributedMaskCriterion,
    TransformerCriterion,
)
from bigdl_tpu.nn.volumetric import (
    VolumetricConvolution,
    VolumetricFullConvolution,
    VolumetricMaxPooling,
    VolumetricAveragePooling,
)
from bigdl_tpu.nn.detection import (
    Anchor,
    Nms,
    PriorBox,
    Proposal,
    RoiPooling,
    RoiAlign,
    DetectionOutputSSD,
    DetectionOutputFrcnn,
    bbox_iou,
    bbox_transform_inv,
    nms,
)
from bigdl_tpu.nn.treelstm import BinaryTreeLSTM
TreeLSTM = BinaryTreeLSTM
