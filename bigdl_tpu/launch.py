"""Multi-host launcher: `python -m bigdl_tpu.launch [opts] script.py [args]`.

Reference: scripts/spark-submit-with-bigdl.sh + Engine.createSparkConf —
the reference ships a submit wrapper that injects the bigdl conf before
handing the program to Spark.  On TPU the cluster runtime is
`jax.distributed`: this launcher injects the coordinator/process topology
(flags or the TPU pod environment) as BIGDL_TPU_* env vars and executes
the training script in-process; `Engine.init()` inside the script then
joins the cluster (core/engine.py).

On Cloud TPU pod slices the topology is auto-detected (jax.distributed
with no arguments), so the common invocation is simply:

    python -m bigdl_tpu.launch train.py --epochs 90    # every host

For explicit CPU/GPU multi-process clusters:

    python -m bigdl_tpu.launch --coordinator host0:1234 \
        --num-processes 4 --process-id $RANK train.py
"""

from __future__ import annotations

import argparse
import os
import runpy
import sys

_PREFIX = "BIGDL_TPU_"


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        prog="python -m bigdl_tpu.launch",
        description="Launch a training script into a jax.distributed cluster")
    ap.add_argument("--coordinator", default=None,
                    help="coordinator host:port (omit on TPU pod slices — "
                         "auto-detected)")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--mesh", default=None,
                    help="mesh spec like 'data=8,model=4' exported as "
                         f"{_PREFIX}MESH for Engine.init")
    ap.add_argument("script", help="training script to run")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)

    if args.coordinator is None and (args.num_processes is not None
                                     or args.process_id is not None):
        ap.error("--num-processes/--process-id require --coordinator "
                 "(TPU pod slices auto-detect all three)")
    if args.coordinator is not None:
        os.environ[_PREFIX + "COORDINATOR_ADDRESS"] = args.coordinator
        if args.num_processes is not None:
            os.environ[_PREFIX + "NUM_PROCESSES"] = str(args.num_processes)
        if args.process_id is not None:
            os.environ[_PREFIX + "PROCESS_ID"] = str(args.process_id)
    if args.mesh is not None:
        os.environ[_PREFIX + "MESH"] = args.mesh

    sys.argv = [args.script] + list(args.script_args)
    sys.path.insert(0, os.path.dirname(os.path.abspath(args.script)) or ".")
    runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
