"""Pipeline parallelism over the `pipeline` mesh axis.

No reference counterpart — survey §2.10 records pipeline parallelism as
absent from BigDL; this is beyond-reference TPU capability for models too
large for one chip's HBM.

Design (the scaling-book recipe): the model's REPEATED blocks are stacked
on a leading dim sharded `P('pipeline')`, so under `shard_map` each device
holds a contiguous group of layers.  Heterogeneous ends (embedding, final
norm, LM head) run OUTSIDE the pipelined region, replicated over the
pipeline axis — on an SPMD mesh every device executes the same program, so
pipelining only the uniform block stack (and keeping the cheap ends
data-parallel) is the idiomatic partitioning, not a limitation: the pytree
per stage is "k transformer blocks", and embed/head stages need no relay
slot of their own.

Two schedules, both expressed as a `lax.scan` of compute + `ppermute`
ticks so that JAX autodiff yields the backward pipeline automatically (no
hand-written 1F1B backward; wrap stages in `jax.checkpoint` via
`remat=True` to keep activation memory at one-microbatch-per-tick):

  * GPipe (default): microbatch m enters stage 0 at tick m; each tick every
    device applies its WHOLE local group (k layers).  Ticks = M + S - 1,
    bubble (S-1)/(M+S-1) of k-layer ticks.
  * Interleaved / circular (`interleave=True`): each of the k local layers
    is its own virtual stage (v = k groups per device, V = S*v virtual
    stages); microbatches travel the ring v times, one LAYER per tick, new
    chunks of S microbatches injected as the previous chunk drains.
    Ticks = M*v + S - 1 single-layer ticks vs GPipe's (M + S - 1)*v — the
    fill/drain bubble shrinks by ~v, the Megatron interleaved-schedule
    effect.  Requires S | M.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.engine import AXIS_PIPELINE


def _local_stack(stage_params: Any) -> int:
    """Leading (local layer-group) dim of the per-device param stack."""
    leaves = jax.tree_util.tree_leaves(stage_params)
    if not leaves:
        raise ValueError("pipeline stage_params has no leaves")
    k = leaves[0].shape[0]
    for leaf in leaves:
        if leaf.ndim < 1 or leaf.shape[0] != k:
            raise ValueError(
                f"stage_params leaves must share the leading stacked layer "
                f"dim, got {leaf.shape} vs {k}")
    return k


def pipeline_apply(stage_fn: Callable[..., jnp.ndarray],
                   stage_params: Any, x: jnp.ndarray, n_microbatch: int,
                   axis_name: str = AXIS_PIPELINE,
                   remat: bool = False,
                   interleave: bool = False,
                   with_uid: bool = False) -> jnp.ndarray:
    """Run `stage_fn` (ONE layer: params-without-stack-dim, h -> h) as a
    pipeline over `axis_name`.  MUST be called inside `shard_map` with
    `stage_params` carrying a leading layer-stacked dim sharded
    `P(axis_name)` (k >= 1 local layers per device) and `x` the full
    (pipeline-replicated) batch whose leading dim splits into
    `n_microbatch` equal microbatches.  Layers apply in global stacked
    order: device d holds layers [d*k, (d+1)*k).  Returns the pipeline
    output, replicated to every stage.

    with_uid=True calls `stage_fn(layer_params, h, uid)` where `uid` is a
    scalar unique per (microbatch, global layer) — the RNG-folding handle
    for dropout inside pipelined blocks.
    """
    n_stage = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    k = _local_stack(stage_params)
    my_params = stage_params

    b = x.shape[0]
    assert b % n_microbatch == 0, (b, n_microbatch)
    mb = b // n_microbatch
    micro = x.reshape((n_microbatch, mb) + x.shape[1:])

    raw = stage_fn if with_uid else (lambda p, h, uid: stage_fn(p, h))
    fn = jax.checkpoint(raw) if remat else raw
    # activation shape probe (pipelined layers must be shape-preserving so
    # the relay buffer has one static shape; true of transformer blocks —
    # shape-CHANGING ends like embed/head run outside the pipelined region)
    probe_params = jax.tree_util.tree_map(lambda a: a[0], my_params)
    out_struct = jax.eval_shape(fn, probe_params, jax.ShapeDtypeStruct(
        micro.shape[1:], micro.dtype), jax.ShapeDtypeStruct((), jnp.int32))
    assert out_struct.shape == micro.shape[1:], (
        f"pipelined layers must preserve activation shape, got "
        f"{out_struct.shape} vs {micro.shape[1:]}")

    if interleave:
        outputs = _interleaved_schedule(fn, my_params, micro, n_stage, idx,
                                        axis_name, k)
    else:
        outputs = _gpipe_schedule(fn, my_params, micro, n_stage, idx,
                                  axis_name, k)

    # broadcast the last stage's collected outputs to every stage
    outputs = lax.psum(
        jnp.where(idx == n_stage - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape((b,) + x.shape[1:])


def _apply_group(fn, my_params, h, base_uid, k):
    """Apply all k local layers in stacked order (one GPipe tick).  Layer
    j's uid = base_uid + j (base encodes microbatch and device offset)."""

    def body(h, pj):
        layer_params, j = pj
        return fn(layer_params, h, (base_uid + j).astype(jnp.int32)), None

    h, _ = lax.scan(body, h, (my_params, jnp.arange(k)))
    return h


def _varying(axis_name, *arrays):
    """Mark scan-carry init values as varying over the pipeline axis (the
    body outputs depend on axis_index, so carry types must match)."""
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        return arrays
    return tuple(pcast(a, (axis_name,), to="varying") for a in arrays)


def _gpipe_schedule(fn, my_params, micro, n_stage, idx, axis_name, k):
    n_microbatch = micro.shape[0]
    fwd_perm = [(i, i + 1) for i in range(n_stage - 1)]
    n_tick = n_microbatch + n_stage - 1

    def tick(carry, t):
        relay, outputs = carry
        # stage 0 injects microbatch t (clamped; masked later), others take
        # the relayed activation from the previous stage
        feed = micro[jnp.minimum(t, n_microbatch - 1)]
        inp = jnp.where(idx == 0, feed, relay)
        # the microbatch this device computes at tick t is m = t - idx
        m = jnp.clip(t - idx, 0, n_microbatch - 1)
        out = _apply_group(fn, my_params, inp,
                           m * (n_stage * k) + idx * k, k)
        # the LAST stage finished microbatch t - (S-1) this tick
        done = t - (n_stage - 1)
        outputs = jnp.where(
            (idx == n_stage - 1) & (done >= 0),
            lax.dynamic_update_index_in_dim(
                outputs, out, jnp.maximum(done, 0), axis=0),
            outputs)
        relay = lax.ppermute(out, axis_name, fwd_perm)
        return (relay, outputs), None

    relay0, outputs0 = _varying(axis_name, jnp.zeros_like(micro[0]),
                                jnp.zeros_like(micro))
    (_, outputs), _ = lax.scan(tick, (relay0, outputs0), jnp.arange(n_tick))
    return outputs


def _interleaved_schedule(fn, my_params, micro, n_stage, idx, axis_name, v):
    """Circular schedule: v = k virtual stages per device, one LAYER per
    tick, ring ppermute (stage S-1 wraps to stage 0).  Microbatch m (in
    chunks of S) is injected at tick inj(m) = (m // S)*(v*S) + (m % S) and
    occupies virtual stage vs = t - inj(m) at tick t — device vs % S, local
    layer vs // S.  Closed form per (tick, device): r = (t - d) mod S is
    the microbatch's index within its chunk, c = (t - r) // (v*S) its
    chunk.  Chunk injections are spaced v*S ticks so ring slots never
    collide.  Ticks = (M/S)*v*S + S - 1 = M*v + S - 1.
    """
    n_microbatch = micro.shape[0]
    if n_microbatch % n_stage != 0:
        raise ValueError(
            f"interleaved pipeline needs n_microbatch ({n_microbatch}) "
            f"divisible by pipeline size ({n_stage})")
    ring_perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
    n_tick = n_microbatch * v + n_stage - 1

    def tick(carry, t):
        relay, outputs = carry
        r = jnp.mod(t - idx, n_stage)          # index within chunk
        c = (t - r) // (v * n_stage)            # chunk id
        m = c * n_stage + r                     # global microbatch id
        vs = (t - r) - c * (v * n_stage)        # virtual stage
        g = jnp.clip(vs // n_stage, 0, v - 1)   # local layer index
        active = (m >= 0) & (m < n_microbatch) & (vs >= 0) & (vs < v * n_stage)
        layer_params = jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, g, 0, keepdims=False),
            my_params)
        feed = micro[jnp.clip(m, 0, n_microbatch - 1)]
        inp = jnp.where(vs == 0, feed, relay)
        uid = jnp.clip(m, 0, n_microbatch - 1) * (v * n_stage) \
            + jnp.clip(vs, 0, v * n_stage - 1)
        out = fn(layer_params, inp, uid.astype(jnp.int32))
        # keep the relay clean on idle ticks so a microbatch's activation
        # survives the ring hop even if schedule holes appear
        out = jnp.where(active, out, relay)
        finished = active & (idx == n_stage - 1) & (vs == v * n_stage - 1)
        outputs = jnp.where(
            finished,
            lax.dynamic_update_index_in_dim(
                outputs, out, jnp.clip(m, 0, n_microbatch - 1), axis=0),
            outputs)
        relay = lax.ppermute(out, axis_name, ring_perm)
        return (relay, outputs), None

    relay0, outputs0 = _varying(axis_name, jnp.zeros_like(micro[0]),
                                jnp.zeros_like(micro))
    (_, outputs), _ = lax.scan(tick, (relay0, outputs0), jnp.arange(n_tick))
    return outputs


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of per-layer param trees on a new leading dim (shard it
    `P('pipeline')`); each device's shard is its local layer group inside
    `pipeline_apply`."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def interleave_stack(stacked: Any, n_stage: int) -> Any:
    """Permute a layer-stacked param tree (L, ...) from MODEL order into the
    interleaved schedule's layout: virtual stage vs runs on device vs % S at
    local slot vs // S, and `P('pipeline')` sharding gives device d the
    contiguous slice [d*k, (d+1)*k) — so physical[d*k + j] must hold logical
    layer j*S + d.  That is a (v, S) -> (S, v) transpose of the leading dim.
    Call at the GLOBAL (jit) level, before entering shard_map; gradients
    flow back through the permutation automatically."""

    def perm(a):
        L = a.shape[0]
        if L % n_stage != 0:
            raise ValueError(f"layer count {L} not divisible by {n_stage} stages")
        v = L // n_stage
        return a.reshape((v, n_stage) + a.shape[1:]).swapaxes(0, 1) \
                .reshape((L,) + a.shape[1:])

    return jax.tree_util.tree_map(perm, stacked)


def deinterleave_stack(stacked: Any, n_stage: int) -> Any:
    """Inverse of `interleave_stack` (schedule layout back to model order)."""

    def perm(a):
        L = a.shape[0]
        v = L // n_stage
        return a.reshape((n_stage, v) + a.shape[1:]).swapaxes(0, 1) \
                .reshape((L,) + a.shape[1:])

    return jax.tree_util.tree_map(perm, stacked)
