"""Pipeline parallelism over the `pipeline` mesh axis.

No reference counterpart — survey §2.10 records pipeline parallelism as
absent from BigDL; this is beyond-reference TPU capability for models too
large for one chip's HBM.

Design (the scaling-book recipe): the model's REPEATED blocks are stacked
on a leading dim sharded `P('pipeline')`, so under `shard_map` each device
holds a contiguous group of layers.  Heterogeneous ends (embedding, final
norm, LM head) run OUTSIDE the pipelined region, replicated over the
pipeline axis — on an SPMD mesh every device executes the same program, so
pipelining only the uniform block stack (and keeping the cheap ends
data-parallel) is the idiomatic partitioning, not a limitation: the pytree
per stage is "k transformer blocks", and embed/head stages need no relay
slot of their own.

Two schedules, both expressed as a `lax.scan` of compute + `ppermute`
ticks so that JAX autodiff yields the backward pipeline automatically (no
hand-written 1F1B backward; wrap stages in `jax.checkpoint` via
`remat=True` to keep activation memory at one-microbatch-per-tick):

  * GPipe (default): microbatch m enters stage 0 at tick m; each tick every
    device applies its WHOLE local group (k layers).  Ticks = M + S - 1,
    bubble (S-1)/(M+S-1) of k-layer ticks.
  * Interleaved / circular (`interleave=True`): each of the k local layers
    is its own virtual stage (v = k groups per device, V = S*v virtual
    stages); microbatches travel the ring v times, one LAYER per tick, new
    chunks of S microbatches injected as the previous chunk drains.
    Ticks = M*v + S - 1 single-layer ticks vs GPipe's (M + S - 1)*v — the
    fill/drain bubble shrinks by ~v, the Megatron interleaved-schedule
    effect.  Requires S | M.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.engine import AXIS_PIPELINE


def _local_stack(stage_params: Any) -> int:
    """Leading (local layer-group) dim of the per-device param stack."""
    leaves = jax.tree_util.tree_leaves(stage_params)
    if not leaves:
        raise ValueError("pipeline stage_params has no leaves")
    k = leaves[0].shape[0]
    for leaf in leaves:
        if leaf.ndim < 1 or leaf.shape[0] != k:
            raise ValueError(
                f"stage_params leaves must share the leading stacked layer "
                f"dim, got {leaf.shape} vs {k}")
    return k


def pipeline_apply(stage_fn: Callable[..., jnp.ndarray],
                   stage_params: Any, x: jnp.ndarray, n_microbatch: int,
                   axis_name: str = AXIS_PIPELINE,
                   remat: bool = False,
                   interleave: bool = False,
                   with_uid: bool = False,
                   stage_state: Any = None):
    """Run `stage_fn` (ONE layer: params-without-stack-dim, h -> h) as a
    pipeline over `axis_name`.  MUST be called inside `shard_map` with
    `stage_params` carrying a leading layer-stacked dim sharded
    `P(axis_name)` (k >= 1 local layers per device) and `x` the full
    (pipeline-replicated) batch whose leading dim splits into
    `n_microbatch` equal microbatches.  Layers apply in global stacked
    order: device d holds layers [d*k, (d+1)*k).  Returns the pipeline
    output, replicated to every stage.

    with_uid=True calls `stage_fn(layer_params, h, uid)` where `uid` is a
    scalar unique per (microbatch, global layer) — the RNG-folding handle
    for dropout inside pipelined blocks.

    stage_state (optional) carries PER-LAYER STATE stacked like the params
    (same leading dim, same `P(axis_name)` sharding) for stateful layers —
    BatchNorm running stats being the canonical case.  The stage_fn
    signature becomes `(layer_params, layer_state, h[, uid]) ->
    (h, new_layer_state)` and pipeline_apply returns `(out,
    new_stage_state)`.  Each layer sees the microbatches in order
    0..M-1 and updates its state sequentially (masked off on fill/drain
    ticks), so the result is EXACTLY the microbatch-sequential reference:
    pipelining changes the execution schedule, not the stats semantics.
    (Microbatching itself changes BN's normalization batch vs a full-batch
    step — the standard GPipe property — which is why parity is defined
    against the microbatched sequential program.)
    """
    n_stage = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    k = _local_stack(stage_params)
    my_params = stage_params
    with_state = stage_state is not None
    my_state = stage_state if with_state else {}

    b = x.shape[0]
    assert b % n_microbatch == 0, (b, n_microbatch)
    mb = b // n_microbatch
    micro = x.reshape((n_microbatch, mb) + x.shape[1:])

    # canonical internal signature: (params, state, h, uid) -> (h, state)
    if with_state and with_uid:
        raw = stage_fn
    elif with_state:
        raw = lambda p, s, h, uid: stage_fn(p, s, h)  # noqa: E731
    elif with_uid:
        raw = lambda p, s, h, uid: (stage_fn(p, h, uid), s)  # noqa: E731
    else:
        raw = lambda p, s, h, uid: (stage_fn(p, h), s)  # noqa: E731
    fn = jax.checkpoint(raw) if remat else raw
    # activation shape probe (pipelined layers must be shape-preserving so
    # the relay buffer has one static shape; true of transformer blocks —
    # shape-CHANGING ends like embed/head run outside the pipelined region)
    take0 = lambda a: a[0]  # noqa: E731
    probe_params = jax.tree_util.tree_map(take0, my_params)
    probe_state = jax.tree_util.tree_map(take0, my_state)
    out_struct, _ = jax.eval_shape(
        fn, probe_params, probe_state,
        jax.ShapeDtypeStruct(micro.shape[1:], micro.dtype),
        jax.ShapeDtypeStruct((), jnp.int32))
    assert out_struct.shape == micro.shape[1:], (
        f"pipelined layers must preserve activation shape, got "
        f"{out_struct.shape} vs {micro.shape[1:]}")

    if interleave:
        outputs, new_state = _interleaved_schedule(
            fn, my_params, my_state, micro, n_stage, idx, axis_name, k)
    else:
        outputs, new_state = _gpipe_schedule(
            fn, my_params, my_state, micro, n_stage, idx, axis_name, k)

    # broadcast the last stage's collected outputs to every stage
    outputs = lax.psum(
        jnp.where(idx == n_stage - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    outputs = outputs.reshape((b,) + x.shape[1:])
    if with_state:
        return outputs, new_state
    return outputs


def _apply_group(fn, my_params, my_state, h, base_uid, k):
    """Apply all k local layers in stacked order (one GPipe tick).  Layer
    j's uid = base_uid + j (base encodes microbatch and device offset).
    Returns (h, k-stacked new layer states)."""

    def body(h, psj):
        layer_params, layer_state, j = psj
        h2, s2 = fn(layer_params, layer_state, h,
                    (base_uid + j).astype(jnp.int32))
        return h2, s2

    h, new_states = lax.scan(body, h, (my_params, my_state, jnp.arange(k)))
    return h, new_states


def _varying(axis_name, *trees):
    """Mark scan-carry init values as varying over the pipeline axis (the
    body outputs depend on axis_index, so carry types must match)."""
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        return trees

    def cast(a):
        # typed check, not error-message parsing: jax.typeof().vma is the
        # set of axes a value already varies over under shard_map tracing
        vma = getattr(jax.typeof(a), "vma", None)
        if vma is not None and axis_name in vma:
            return a  # already varying (e.g. P(pipeline)-sharded state)
        return pcast(a, (axis_name,), to="varying")

    return tuple(jax.tree_util.tree_map(cast, t) for t in trees)


def _masked_state(active, new, old):
    """Keep `new` state only on active ticks (fill/drain ticks compute
    garbage microbatches whose stat updates must not land)."""
    return jax.tree_util.tree_map(
        lambda n, o: jnp.where(active, n, o), new, old)


def _gpipe_schedule(fn, my_params, my_state, micro, n_stage, idx,
                    axis_name, k):
    n_microbatch = micro.shape[0]
    fwd_perm = [(i, i + 1) for i in range(n_stage - 1)]
    n_tick = n_microbatch + n_stage - 1

    def tick(carry, t):
        relay, outputs, state = carry
        # stage 0 injects microbatch t (clamped; masked later), others take
        # the relayed activation from the previous stage
        feed = micro[jnp.minimum(t, n_microbatch - 1)]
        inp = jnp.where(idx == 0, feed, relay)
        # the microbatch this device computes at tick t is m = t - idx
        m = jnp.clip(t - idx, 0, n_microbatch - 1)
        out, new_state = _apply_group(fn, my_params, state, inp,
                                      m * (n_stage * k) + idx * k, k)
        # this stage holds a real microbatch only for idx <= t < idx + M
        active = (t >= idx) & (t - idx < n_microbatch)
        state = _masked_state(active, new_state, state)
        # the LAST stage finished microbatch t - (S-1) this tick
        done = t - (n_stage - 1)
        outputs = jnp.where(
            (idx == n_stage - 1) & (done >= 0),
            lax.dynamic_update_index_in_dim(
                outputs, out, jnp.maximum(done, 0), axis=0),
            outputs)
        relay = lax.ppermute(out, axis_name, fwd_perm)
        return (relay, outputs, state), None

    relay0, outputs0, state0 = _varying(
        axis_name, jnp.zeros_like(micro[0]), jnp.zeros_like(micro), my_state)
    (_, outputs, new_state), _ = lax.scan(
        tick, (relay0, outputs0, state0), jnp.arange(n_tick))
    return outputs, new_state


def _interleaved_schedule(fn, my_params, my_state, micro, n_stage, idx,
                          axis_name, v):
    """Circular schedule: v = k virtual stages per device, one LAYER per
    tick, ring ppermute (stage S-1 wraps to stage 0).  Microbatch m (in
    chunks of S) is injected at tick inj(m) = (m // S)*(v*S) + (m % S) and
    occupies virtual stage vs = t - inj(m) at tick t — device vs % S, local
    layer vs // S.  Closed form per (tick, device): r = (t - d) mod S is
    the microbatch's index within its chunk, c = (t - r) // (v*S) its
    chunk.  Chunk injections are spaced v*S ticks so ring slots never
    collide.  Ticks = (M/S)*v*S + S - 1 = M*v + S - 1.
    """
    n_microbatch = micro.shape[0]
    if n_microbatch % n_stage != 0:
        raise ValueError(
            f"interleaved pipeline needs n_microbatch ({n_microbatch}) "
            f"divisible by pipeline size ({n_stage})")
    ring_perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
    n_tick = n_microbatch * v + n_stage - 1

    def tick(carry, t):
        relay, outputs, state = carry
        r = jnp.mod(t - idx, n_stage)          # index within chunk
        c = (t - r) // (v * n_stage)            # chunk id
        m = c * n_stage + r                     # global microbatch id
        vs = (t - r) - c * (v * n_stage)        # virtual stage
        g = jnp.clip(vs // n_stage, 0, v - 1)   # local layer index
        active = (m >= 0) & (m < n_microbatch) & (vs >= 0) & (vs < v * n_stage)
        take_g = lambda a: lax.dynamic_index_in_dim(  # noqa: E731
            a, g, 0, keepdims=False)
        layer_params = jax.tree_util.tree_map(take_g, my_params)
        layer_state = jax.tree_util.tree_map(take_g, state)
        feed = micro[jnp.clip(m, 0, n_microbatch - 1)]
        inp = jnp.where(vs == 0, feed, relay)
        uid = jnp.clip(m, 0, n_microbatch - 1) * (v * n_stage) \
            + jnp.clip(vs, 0, v * n_stage - 1)
        out, new_ls = fn(layer_params, layer_state, inp, uid.astype(jnp.int32))
        # write local layer g's new state back, only on active ticks
        state = jax.tree_util.tree_map(
            lambda buf, new: jnp.where(
                active, lax.dynamic_update_index_in_dim(buf, new, g, 0),
                buf),
            state, new_ls)
        # keep the relay clean on idle ticks so a microbatch's activation
        # survives the ring hop even if schedule holes appear
        out = jnp.where(active, out, relay)
        finished = active & (idx == n_stage - 1) & (vs == v * n_stage - 1)
        outputs = jnp.where(
            finished,
            lax.dynamic_update_index_in_dim(
                outputs, out, jnp.clip(m, 0, n_microbatch - 1), axis=0),
            outputs)
        relay = lax.ppermute(out, axis_name, ring_perm)
        return (relay, outputs, state), None

    relay0, outputs0, state0 = _varying(
        axis_name, jnp.zeros_like(micro[0]), jnp.zeros_like(micro), my_state)
    (_, outputs, new_state), _ = lax.scan(
        tick, (relay0, outputs0, state0), jnp.arange(n_tick))
    return outputs, new_state


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of per-layer param trees on a new leading dim (shard it
    `P('pipeline')`); each device's shard is its local layer group inside
    `pipeline_apply`."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def interleave_stack(stacked: Any, n_stage: int) -> Any:
    """Permute a layer-stacked param tree (L, ...) from MODEL order into the
    interleaved schedule's layout: virtual stage vs runs on device vs % S at
    local slot vs // S, and `P('pipeline')` sharding gives device d the
    contiguous slice [d*k, (d+1)*k) — so physical[d*k + j] must hold logical
    layer j*S + d.  That is a (v, S) -> (S, v) transpose of the leading dim.
    Call at the GLOBAL (jit) level, before entering shard_map; gradients
    flow back through the permutation automatically."""

    def perm(a):
        L = a.shape[0]
        if L % n_stage != 0:
            raise ValueError(f"layer count {L} not divisible by {n_stage} stages")
        v = L // n_stage
        return a.reshape((v, n_stage) + a.shape[1:]).swapaxes(0, 1) \
                .reshape((L,) + a.shape[1:])

    return jax.tree_util.tree_map(perm, stacked)


def deinterleave_stack(stacked: Any, n_stage: int) -> Any:
    """Inverse of `interleave_stack` (schedule layout back to model order)."""

    def perm(a):
        L = a.shape[0]
        v = L // n_stage
        return a.reshape((n_stage, v) + a.shape[1:]).swapaxes(0, 1) \
                .reshape((L,) + a.shape[1:])

    return jax.tree_util.tree_map(perm, stacked)
