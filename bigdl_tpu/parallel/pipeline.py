"""GPipe-style pipeline parallelism over the `pipeline` mesh axis.

No reference counterpart — survey §2.10 records pipeline parallelism as
absent from BigDL; this is beyond-reference TPU capability for models too
large for one chip's HBM.

Design (the scaling-book recipe): layer stages are STACKED on a leading
dim sharded `P('pipeline')`, so under `shard_map` each device holds one
stage's parameters.  The batch is split into M microbatches; the schedule
runs M + S - 1 ticks of a `lax.scan`, each tick computing every stage on
its in-flight microbatch and `ppermute`-ing activations one stage forward
(the bubble is the standard (S-1)/(M+S-1) fraction).  Autodiff through
the scan + ppermute yields the backward pipeline automatically — no
hand-written 1F1B schedule; wrap the stage in `jax.checkpoint` (remat=True)
to keep activation memory at one-microbatch-per-tick.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.engine import AXIS_PIPELINE


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stage_params: Any, x: jnp.ndarray, n_microbatch: int,
                   axis_name: str = AXIS_PIPELINE,
                   remat: bool = False) -> jnp.ndarray:
    """Run `stage_fn` as a pipeline over `axis_name`.  MUST be called
    inside `shard_map` with `stage_params` carrying a leading
    stage-stacked dim of size 1 per device (sharded `P(axis_name)`) and
    `x` the full (replicated) batch whose leading dim splits into
    `n_microbatch` equal microbatches.  Returns the pipeline output,
    replicated to every stage.
    """
    n_stage = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    for leaf in jax.tree_util.tree_leaves(stage_params):
        assert leaf.shape[0] == 1, (
            f"stage_params' local stacked dim is {leaf.shape[0]}, expected 1 "
            f"per device — shard the stacked stage dim P({axis_name!r}) with "
            f"exactly one stage per pipeline-axis device")
    my_params = jax.tree_util.tree_map(lambda a: a[0], stage_params)

    b = x.shape[0]
    assert b % n_microbatch == 0, (b, n_microbatch)
    mb = b // n_microbatch
    micro = x.reshape((n_microbatch, mb) + x.shape[1:])

    fn = jax.checkpoint(stage_fn) if remat else stage_fn
    # activation shape probe (stages must be shape-preserving so the relay
    # buffer has one static shape; true of transformer blocks)
    out_struct = jax.eval_shape(fn, my_params, jax.ShapeDtypeStruct(
        micro.shape[1:], micro.dtype))
    assert out_struct.shape == micro.shape[1:], (
        f"pipeline stages must preserve activation shape, got "
        f"{out_struct.shape} vs {micro.shape[1:]}")

    fwd_perm = [(i, i + 1) for i in range(n_stage - 1)]
    n_tick = n_microbatch + n_stage - 1

    def tick(carry, t):
        relay, outputs = carry
        # stage 0 injects microbatch t (clamped; masked later), others take
        # the relayed activation from the previous stage
        feed = micro[jnp.minimum(t, n_microbatch - 1)]
        inp = jnp.where(idx == 0, feed, relay)
        out = fn(my_params, inp)
        # the LAST stage finished microbatch t - (S-1) this tick
        done = t - (n_stage - 1)
        outputs = jnp.where(
            (idx == n_stage - 1) & (done >= 0),
            lax.dynamic_update_index_in_dim(
                outputs, out, jnp.maximum(done, 0), axis=0),
            outputs)
        relay = lax.ppermute(out, axis_name, fwd_perm)
        return (relay, outputs), None

    # zeros_like(micro) inherits micro's varying axes (e.g. a data axis the
    # batch is sharded over); the body's outputs additionally vary over the
    # pipeline axis (they depend on axis_index), so cast that in too or the
    # scan carry types won't match
    relay0 = jnp.zeros_like(micro[0])
    outputs0 = jnp.zeros_like(micro)
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        relay0 = pcast(relay0, (axis_name,), to="varying")
        outputs0 = pcast(outputs0, (axis_name,), to="varying")
    (_, outputs), _ = lax.scan(tick, (relay0, outputs0), jnp.arange(n_tick))

    # broadcast the last stage's collected outputs to every stage
    outputs = lax.psum(
        jnp.where(idx == n_stage - 1, outputs, jnp.zeros_like(outputs)),
        axis_name)
    return outputs.reshape((b,) + x.shape[1:])


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of per-stage param trees on a new leading dim (shard it
    `P('pipeline')`); the inverse of what each device's `tree_map(a[0])`
    sees inside pipeline_apply."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)
