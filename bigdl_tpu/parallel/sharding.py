"""Parameter/batch sharding rules over a named-axis mesh.

The reference's only inter-node strategy is data parallelism with a
parameter-server-sharded update (survey §2.10); its AllReduceParameter
slices the flattened parameter vector 1/N per node
(parameters/AllReduceParameter.scala:73-76).  On TPU, parallelism is
declarative: params/batches carry `NamedSharding`s and XLA inserts the
collectives.  This module is the one place sharding layouts are decided:

  * `batch_sharding(mesh)` — batch dim over the `data` axis (dp; sequence
    models can add the `sequence` axis on their length dim — sp).
  * `ShardingRules` — ordered (path-regex -> PartitionSpec) rules mapping
    parameter pytree paths to shardings (tp for wide layers; anything the
    rules don't match is replicated).

Rules are matched against "/"-joined tree paths, e.g. "10/weight" for
Sequential child 10 or "fc/weight" for a named Graph node.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.core.engine import AXIS_DATA


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


class ShardingRules:
    """Ordered path-regex -> PartitionSpec table (first match wins)."""

    def __init__(self, rules: Optional[Sequence[Tuple[str, P]]] = None):
        self.rules: List[Tuple[re.Pattern, P]] = [
            (re.compile(pat), spec) for pat, spec in (rules or [])]

    def add(self, pattern: str, spec: P) -> "ShardingRules":
        self.rules.append((re.compile(pattern), spec))
        return self

    def spec_for(self, path_str: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if pat.search(path_str):
                if len(spec) > ndim:
                    raise ValueError(
                        f"sharding rule {pat.pattern!r} -> {spec} has "
                        f"{len(spec)} dims but parameter {path_str!r} has "
                        f"only {ndim}")
                return spec
        return P()  # replicate


def shard_params(params: Any, mesh: Mesh,
                 rules: Optional[ShardingRules] = None) -> Any:
    """device_put each param leaf with its rule's NamedSharding."""
    rules = rules or ShardingRules()
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        spec = rules.spec_for(_path_str(path), np.ndim(leaf))
        out.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, out)


def shard_opt_state(opt_state: Any, params: Any, mesh: Mesh,
                    rules: Optional[ShardingRules] = None) -> Any:
    """Shard optimizer state to MATCH the params' rules.

    OptimMethod state is a dict of slot trees (velocity/exp_avg/...), each
    mirroring the params pytree, plus scalar counters (neval/epoch).  Slot
    subtrees whose structure equals the params' get the params' rules
    (their in-subtree paths line up with parameter paths); anything else —
    counters, schedule state — is replicated.
    """
    if not isinstance(opt_state, dict):
        return replicate(opt_state, mesh)
    params_def = jax.tree_util.tree_structure(params)
    out = {}
    for k, v in opt_state.items():
        if jax.tree_util.tree_structure(v) == params_def:
            out[k] = shard_params(v, mesh, rules)
        else:
            out[k] = replicate(v, mesh)
    return out


def spec_tree(params: Any, rules: Optional[ShardingRules] = None) -> Any:
    """PartitionSpec pytree matching `params` leaf-for-leaf (rule-matched
    leaves get their rule's spec, everything else P()) — the form
    `jax.shard_map` in_specs wants."""
    rules = rules or ShardingRules()
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    specs = [rules.spec_for(_path_str(path), np.ndim(leaf))
             for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


def batch_sharding(mesh: Mesh, axis: str = AXIS_DATA) -> NamedSharding:
    """Shard dim 0 (batch) over the data axis; rest replicated."""
    return NamedSharding(mesh, P(axis))


def replicate(tree: Any, mesh: Mesh) -> Any:
    return jax.device_put(tree, NamedSharding(mesh, P()))
