from bigdl_tpu.parallel.sharding import (
    ShardingRules, shard_params, batch_sharding, replicate,
)

__all__ = ["ShardingRules", "shard_params", "batch_sharding", "replicate"]
