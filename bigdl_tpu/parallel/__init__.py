from bigdl_tpu.parallel.sharding import (
    ShardingRules, shard_params, shard_opt_state, spec_tree, batch_sharding,
    replicate,
)
from bigdl_tpu.parallel.pipeline import (
    pipeline_apply, stack_stage_params, interleave_stack, deinterleave_stack,
)

__all__ = ["ShardingRules", "shard_params", "shard_opt_state", "spec_tree",
           "batch_sharding", "replicate", "pipeline_apply",
           "stack_stage_params", "interleave_stack", "deinterleave_stack"]
