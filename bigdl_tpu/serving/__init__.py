"""TPU-native model serving runtime.

Reference: optim/PredictionService.scala:56,79-128 — BigDL's serving story
is a pool of stateful module clones behind a LinkedBlockingQueue; each
request runs its own forward.  On TPU that design is wrong twice over:
jitted forwards are pure (no clones needed), and per-request forwards give
XLA one compiled shape per distinct request size plus per-request dispatch
overhead.  The TPU-native redesign is the serving-side dual of the
trainer's one-sync step:

  * `MicroBatcher` coalesces concurrent single requests into a SMALL FIXED
    SET of bucketed batch shapes (pad-to-bucket, max-wait deadline), so
    the hot path is one jitted forward per bucket and XLA compiles at most
    `len(buckets)` executables, ever.
  * `ModelRegistry` holds versioned immutable (params, state) snapshots
    with atomic hot-swap under load (a dispatching batch sees exactly one
    version) and AOT warmup on registration so the first request after a
    swap never eats a compile.
  * Admission control: bounded queue, per-request deadlines, graceful
    rejection, and drain/shutdown that completes in-flight batches —
    mirroring the trainer's telemetry-ring drain guard.
  * `ServingMetrics` exports p50/p99 latency, queue depth, batch occupancy
    and rejection counters through the summary/TensorBoard machinery.

`optim.PredictionService` remains as a thin compatibility facade over
`ServingRuntime`.
"""

from bigdl_tpu.serving.batcher import (
    DeadlineExceeded,
    MicroBatcher,
    Rejected,
    ServingClosed,
)
from bigdl_tpu.serving.metrics import (GenerationMetrics, LatencyHistogram,
                                       ServingMetrics)
from bigdl_tpu.serving.registry import ModelRegistry, ModelVersion
from bigdl_tpu.serving.runtime import (
    NonFiniteOutput,
    ServingConfig,
    ServingRuntime,
)

__all__ = [
    "DeadlineExceeded",
    "GenerationMetrics",
    "LatencyHistogram",
    "MicroBatcher",
    "ModelRegistry",
    "ModelVersion",
    "NonFiniteOutput",
    "Rejected",
    "ServingClosed",
    "ServingConfig",
    "ServingMetrics",
    "ServingRuntime",
]
