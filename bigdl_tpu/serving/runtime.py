"""ServingRuntime: bucketed jitted forwards behind the micro-batcher.

Reference: optim/PredictionService.scala:79-128 (the byte API survives on
the `optim.PredictionService` facade).  The runtime owns the TPU side of
serving:

  * ONE jitted forward, shared by every model version.  The jit cache is
    keyed on input shapes, and every dispatch pads to a configured bucket,
    so the executable set is exactly `len(buckets)` — 64 concurrent b1
    requests compile at most 3 shapes (asserted by the compile-count
    probe, `tests/test_serving.py`), the serving dual of the trainer's
    one-compiled-step discipline.
  * Padding reuses the Predictor's pad/mask rules (optim/predictor.py):
    pad rows repeat the last real row, outputs are sliced back to real
    rows before futures resolve — padded rows never leak.
  * Hot-swap: `swap()/swap_checkpoint()` register a new version through
    `ModelRegistry` (AOT-warmed per bucket BEFORE activation); dispatch
    grabs one registry snapshot per batch, so results are always
    single-version consistent.
  * `metrics` (ServingMetrics) tracks p50/p99 latency, queue depth, batch
    occupancy, rejections; `export_metrics()` writes them through the
    summary/TensorBoard machinery.
"""

from __future__ import annotations

import time
from contextlib import nullcontext
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np

from bigdl_tpu import obs as _obs
from bigdl_tpu.analysis.runtime import strict_transfers, strict_transfers_enabled
from bigdl_tpu.core.table import Table
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.predictor import _batch_rows, _pad_batch
from bigdl_tpu.serving.batcher import MicroBatcher
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.registry import ModelRegistry, ModelVersion

_NULL = nullcontext()  # reusable: hot paths must not allocate one per call


class NonFiniteOutput(RuntimeError):
    """The model produced NaN/Inf in this request's output rows and the
    runtime's `reject_nonfinite` guard refused to return them (serving's
    dual of the trainer's divergence watchdog: a poisoned model version
    fails requests loudly instead of shipping garbage scores)."""


class ServingConfig:
    """Knobs for the micro-batching scheduler (docs/serving.md)."""

    def __init__(self, buckets: Sequence[int] = (1, 8, 32),
                 max_wait_ms: float = 2.0, capacity: int = 128,
                 default_deadline_ms: Optional[float] = None,
                 strict_transfers: Optional[bool] = None,
                 reject_nonfinite: bool = False):
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.max_wait_ms = float(max_wait_ms)
        self.capacity = int(capacity)
        self.default_deadline_ms = default_deadline_ms
        # None = env BIGDL_TPU_STRICT_TRANSFERS; True wraps every batch
        # dispatch in jax.transfer_guard("disallow") (docs/analysis.md)
        self.strict_transfers = strict_transfers
        # per-request non-finite output guard: a request whose OWN rows
        # contain NaN/Inf gets NonFiniteOutput; finite co-batched rows
        # still succeed.  Costs one np.isfinite pass over host outputs.
        self.reject_nonfinite = bool(reject_nonfinite)


def _concat_rows(xs: List[Any]) -> Any:
    if len(xs) == 1:
        return xs[0]
    head = xs[0]
    if isinstance(head, Table):
        return Table(*[_concat_rows([x[i] for x in xs])
                       for i in range(1, len(head) + 1)])
    if isinstance(head, (list, tuple)):
        return type(head)(_concat_rows([x[i] for x in xs])
                          for i in range(len(head)))
    return np.concatenate([np.asarray(x) for x in xs], axis=0)


def _slice_rows(y: Any, lo: int, hi: int) -> Any:
    if isinstance(y, (Table, list, tuple)):  # multi-head -> list per head
        return [np.asarray(h)[lo:hi] for h in y]
    return np.asarray(y)[lo:hi]


class ServingRuntime:
    """Dynamic micro-batching inference runtime over a versioned registry."""

    def __init__(self, model: Module, params: Any, state: Any = None, *,
                 config: Optional[ServingConfig] = None,
                 example_input: Any = None, version: str = "v0",
                 summary=None, **config_kw):
        self.model = model
        self.config = config or ServingConfig(**config_kw)
        self.metrics = ServingMetrics()
        self.summary = summary
        self._example = example_input  # one-row example for AOT warmup
        self._export_step = 0
        self._generation = None  # GenerationEngine via enable_generation()

        def fwd(p, s, x):
            out, _ = model.apply(p, s, x, training=False)
            return out

        self._fwd = jax.jit(fwd)
        self._shapes = set()  # distinct padded input shapes ever dispatched
        # warmed executables keyed by padded-input shape signature: the
        # jit fn when the compile cache is off, an AOT-loaded executable
        # when it's on.  `_warmed_psig` pins the param/state tree shapes
        # the entries were warmed for — a params-only swap (same shapes)
        # reuses them outright instead of re-lowering every bucket.
        self._warmed: dict = {}
        self._warmed_psig = None
        self._psig_cache: dict = {}  # (version, registered_at) -> tree sig

        self.registry = ModelRegistry(warmup=self._warmup)
        self.registry.register(version, params, state if state is not None else {})
        # warmup compiled every bucket above: from here on any compile
        # under a serving/ signature is a steady-state recompile alarm
        mon = _obs.compile_monitor()
        if mon is not None:
            mon.mark_steady("serving/")
        self._batcher = MicroBatcher(
            self._dispatch, buckets=self.config.buckets,
            max_wait_ms=self.config.max_wait_ms,
            capacity=self.config.capacity,
            default_deadline_ms=self.config.default_deadline_ms,
            metrics=self.metrics)

    # -- warmup / compile probe -------------------------------------------

    @staticmethod
    def _shape_key(x: Any) -> tuple:
        leaves = jax.tree_util.tree_leaves(x)
        return tuple(tuple(np.shape(l)) for l in leaves)

    @staticmethod
    def _tree_sig(tree: Any) -> tuple:
        """Shape+dtype signature of a params/state tree: two versions with
        the same signature share every compiled executable (the jit cache —
        and the AOT store — key on avals, never on values)."""
        return tuple((tuple(np.shape(l)), str(getattr(l, "dtype", type(l))))
                     for l in jax.tree_util.tree_leaves(tree))

    def _psig_of(self, snap: ModelVersion) -> tuple:
        """`_tree_sig` of a registry snapshot, memoized per version (the
        dispatch path pays one dict lookup, not a tree walk per batch)."""
        key = (snap.version, snap.registered_at)
        sig = self._psig_cache.get(key)
        if sig is None:
            sig = self._psig_cache[key] = self._tree_sig((snap.params,
                                                          snap.state))
            if len(self._psig_cache) > 16:
                self._psig_cache.pop(next(iter(self._psig_cache)))
        return sig

    def _record_shape(self, x: Any) -> None:
        self._shapes.add(self._shape_key(x))

    def _warmup(self, params: Any, state: Any) -> None:
        """Warm every bucket shape BEFORE a version activates so no
        request ever eats a compile.

        Three tiers, cheapest first:
          * params-only swap (identical param/state + bucket signatures):
            every live executable is reused outright — no re-trace, no
            forward, just a counter bump per bucket.
          * compile cache ON (`BIGDL_TPU_COMPILE_CACHE`): each bucket
            resolves through `compilecache.load_or_compile` — a restarted
            server deserializes its executables from disk instead of
            recompiling them.
          * compile cache OFF: original behaviour, one jitted forward per
            bucket (compile on first registration, jit-cache hits after).
        """
        from bigdl_tpu import compilecache as _cc
        if self._example is None:
            return
        psig = self._tree_sig((params, state))
        if psig != self._warmed_psig:
            # shape-drifted version: every warmed executable is stale
            self._warmed.clear()
        use_cache = _cc.enabled()
        reg = _obs.registry()
        for bucket in self.config.buckets:
            xp = _pad_batch(self._example, bucket)
            isig = self._shape_key(xp)
            self._shapes.add(isig)
            if isig in self._warmed:
                # identical function signature/buckets: reuse the live
                # compiled executable — a params-only swap re-traces nothing
                reg.inc("serving/warmup_reused")
                _obs.instant("serve.warmup_reused", cat="serving",
                             bucket=bucket)
                continue
            with _obs.attribute(f"serving/bucket={bucket}"), \
                    _obs.span("serve.warmup", cat="serving", bucket=bucket):
                xd = self._to_device(xp)
                if use_cache:
                    fn, status = _cc.load_or_compile(
                        self._fwd, (params, state, xd),
                        signature=f"serving/bucket={bucket}",
                        extra_key={"kind": "serving", "bucket": bucket},
                        process_scope="serving")
                    self._warmed[isig] = fn if status != "error" else self._fwd
                else:
                    y = self._fwd(params, state, xd)
                    jax.tree_util.tree_map(
                        lambda l: getattr(l, "block_until_ready",
                                          lambda: l)(), y)
                    self._warmed[isig] = self._fwd
        self._warmed_psig = psig

    def compile_count(self) -> int:
        """Distinct compiled forward shapes.  The jit cache size is the
        ground truth when the runtime exposes it (plus the AOT-loaded
        executables, which live outside the jit cache); the dispatched-
        shape set is the structural fallback (identical whenever padding
        is sound)."""
        aot = sum(1 for fn in self._warmed.values() if fn is not self._fwd)
        try:
            n = self._fwd._cache_size()  # pjit probe (jax >= 0.4)
            if n is not None:
                return int(n) + aot
        except Exception:
            pass
        return len(self._shapes)

    # -- hot path ----------------------------------------------------------

    @staticmethod
    def _to_device(x: Any) -> Any:
        if isinstance(x, Table):
            return Table(*[ServingRuntime._to_device(v) for v in x])
        if isinstance(x, (list, tuple)):
            return type(x)(ServingRuntime._to_device(v) for v in x)
        return jax.device_put(np.asarray(x))  # explicit h2d, guard-friendly

    def _dispatch(self, requests, bucket: int) -> None:
        tr = _obs.tracer()
        mon = _obs.compile_monitor()
        t_dispatch = time.perf_counter()
        snap: ModelVersion = self.registry.active()
        if self._example is None:
            # first traffic fixes the row spec; later hot-swaps AOT-warm
            self._example = _slice_rows_like(requests[0].x, 0, 1)
        rows = sum(r.rows for r in requests)
        x = _concat_rows([r.x for r in requests])
        xp = _pad_batch(x, bucket) if rows < bucket else x
        isig = self._shape_key(xp)
        self._shapes.add(isig)
        # warmed executable for this shape (AOT-loaded when the compile
        # cache is on, the jit fn otherwise); the psig check keeps a
        # shape-drifted snapshot off executables warmed for another tree
        fwd = self._fwd
        if self._warmed and self._warmed_psig == self._psig_of(snap):
            fwd = self._warmed.get(isig, self._fwd)
        with (tr.span("serve.dispatch", cat="serving", bucket=bucket,
                      rows=rows, cids=[r.cid for r in requests])
              if tr is not None else _NULL), \
                (mon.attribute(f"serving/bucket={bucket}")
                 if mon is not None else _NULL):
            with strict_transfers(strict_transfers_enabled(
                    self.config.strict_transfers)):
                y = fwd(snap.params, snap.state, self._to_device(xp))
            y = jax.device_get(y)  # ONE host sync per batch, post-dispatch
        t_done = time.perf_counter()
        self.metrics.on_batch(bucket, rows, (t_done - t_dispatch) * 1e3)
        off = 0
        depth = self._batcher.queue_depth
        reject_nonfinite = self.config.reject_nonfinite
        for req in requests:
            out = _slice_rows(y, off, off + req.rows)
            off += req.rows
            req.future.meta = {
                "cid": req.cid,
                "version": snap.version, "bucket": bucket, "batch_rows": rows,
                "queue_ms": (t_dispatch - req.t_enqueue) * 1e3,
                "batch_ms": (t_done - t_dispatch) * 1e3,
            }
            if reject_nonfinite and not _rows_finite(out):
                # per-request: only the poisoned rows fail; finite rows
                # co-batched with them still resolve normally below
                self.metrics.on_nonfinite()
                if tr is not None:
                    tr.instant("serve.nonfinite", cat="serving",
                               cid=req.cid, version=snap.version)
                req.future.set_error(NonFiniteOutput(
                    f"non-finite values in output rows (model version "
                    f"{snap.version!r}, bucket {bucket})"))
                continue
            self.metrics.on_complete((t_dispatch - req.t_enqueue) * 1e3,
                                     (t_done - req.t_enqueue) * 1e3, depth)
            if tr is not None:
                tr.instant("serve.complete", cat="serving", cid=req.cid,
                           queue_ms=round(req.future.meta["queue_ms"], 3))
            req.future.set_result(out)

    def submit(self, x: Any, deadline_ms: Optional[float] = None,
               cid: Optional[str] = None):
        """Async admission: returns a future (result(timeout=...)).
        `cid` overrides the minted correlation id (the fleet router
        passes its own so one id spans replicas)."""
        return self._batcher.submit(x, _batch_rows(x),
                                    deadline_ms=deadline_ms, cid=cid)

    def predict(self, x: Any, deadline_ms: Optional[float] = None,
                timeout: Optional[float] = 60.0) -> Any:
        """Blocking single-request predict.  Requests wider than the
        largest bucket are chunked and reassembled."""
        max_rows = self.config.buckets[-1]
        n = _batch_rows(x)
        if n <= max_rows:
            return self.submit(x, deadline_ms).result(timeout)
        outs = [self.submit(_slice_rows_like(x, lo, min(lo + max_rows, n)),
                            deadline_ms).result(timeout)
                for lo in range(0, n, max_rows)]
        if isinstance(outs[0], list):  # multi-head
            return [np.concatenate([o[i] for o in outs], axis=0)
                    for i in range(len(outs[0]))]
        return np.concatenate(outs, axis=0)

    # -- autoregressive generation ----------------------------------------

    def enable_generation(self, config=None, **config_kw):
        """Attach a `GenerationEngine` (bigdl_tpu.generation) behind this
        runtime's registry: prefill/decode executables are AOT-warmed for
        the active version now, every later `swap()`/`swap_checkpoint()`
        warms them BEFORE activation (the registry warmup chain), and
        `export_metrics()` reports the per-token surface alongside the
        batch-forward latencies.  The model must be cache-aware
        (`init_cache`/`apply_cached` — TransformerLM or a quantized
        wrapper).  Returns the engine (`submit()`/`generate()` live there;
        `close()` here closes it too)."""
        if self._generation is not None:
            return self._generation
        from bigdl_tpu.generation import GenerationConfig, GenerationEngine

        # speculative decoding: the draft model rides through to the
        # engine (and the registry's draft slot), not GenerationConfig
        draft_model = config_kw.pop("draft_model", None)
        draft_params = config_kw.pop("draft_params", None)
        draft_version = config_kw.pop("draft_version", "draft")
        cfg = config or GenerationConfig(**config_kw)
        if cfg.strict_transfers is None:
            cfg.strict_transfers = self.config.strict_transfers
        self._generation = GenerationEngine(
            self.model, config=cfg, registry=self.registry,
            summary=self.summary, draft_model=draft_model,
            draft_params=draft_params, draft_version=draft_version)
        return self._generation

    @property
    def generation(self):
        """The attached GenerationEngine, or None."""
        return self._generation

    # -- versioning --------------------------------------------------------

    def swap(self, version: str, params: Any, state: Any = None) -> None:
        """Atomic params hot-swap: warm (AOT, off the request path), then
        activate.  In-flight batches finish on the previous snapshot."""
        self.registry.register(version, params, state if state is not None else {})
        self.metrics.on_swap()

    def swap_checkpoint(self, version: str, ckpt_dir: str) -> None:
        """Load a trainer checkpoint dir and hot-swap to it."""
        self.registry.register_checkpoint(version, ckpt_dir)
        self.metrics.on_swap()

    @property
    def active_version(self) -> Optional[str]:
        return self.registry.active_version

    # -- observability / lifecycle ----------------------------------------

    def export_metrics(self, step: Optional[int] = None) -> dict:
        """Snapshot the metrics; when a summary is attached, also write
        the scalar set + latency histogram through it."""
        snap = self.metrics.snapshot()
        if self.summary is not None:
            if step is None:
                step = self._export_step
            self._export_step = step + 1
            self.metrics.export(self.summary, step)
        if self._generation is not None:
            snap["generation"] = self._generation.export_metrics(step)
        return snap

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        if self._generation is not None:
            self._generation.close(drain=drain, timeout=timeout)
        self._batcher.close(drain=drain, timeout=timeout)
        if self.summary is not None:
            self.export_metrics()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _rows_finite(out: Any) -> bool:  # tpu-lint: disable=host-sync
    """True when every float leaf of one request's output is finite
    (int/bool outputs are finite by construction).  Leaves are host rows
    already — sliced from the one post-batch d2h — so the np calls here
    are no-op wraps, not device syncs."""
    leaves = out if isinstance(out, list) else [out]
    for leaf in leaves:
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
            return False
    return True


def _slice_rows_like(x: Any, lo: int, hi: int) -> Any:
    """Row-slice an INPUT (keeps Table/tuple structure, unlike the output
    splitter which flattens multi-head outputs to a list)."""
    if isinstance(x, Table):
        return Table(*[_slice_rows_like(v, lo, hi) for v in x])
    if isinstance(x, (list, tuple)):
        return type(x)(_slice_rows_like(v, lo, hi) for v in x)
    return np.asarray(x)[lo:hi]
