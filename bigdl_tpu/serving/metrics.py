"""Serving observability: latency histograms + counters.

Reference: the reference's serving facade has NO metrics at all
(optim/PredictionService.scala); its training-side observability is the
TrainSummary scalar stream (visualization/TrainSummary.scala:32).  Serving
reuses that exact export machinery (`utils/summary.py` -> the hand-rolled
TF-event writer) so serving latency lands next to training loss in the
same TensorBoard, plus a lock-free-enough in-process snapshot API for
benchmarks.

Latencies accumulate into fixed log-spaced buckets (60 buckets over
0.01 ms..100 s) rather than a sample list: a runtime serving millions of
requests must not grow memory per request, and quantile error from the
bucket width (~25%/decade step, i.e. <13% relative) is far below the
run-to-run noise of any real latency measurement.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import obs as _obs

_LO_MS = 1e-2
_HI_MS = 1e5
_N_BUCKETS = 60


class LatencyHistogram:
    """Log-bucketed latency accumulator with percentile read-back."""

    def __init__(self):
        # bucket i covers [_edges[i], _edges[i+1]); first/last are catch-all
        self._edges = np.logspace(math.log10(_LO_MS), math.log10(_HI_MS),
                                  _N_BUCKETS + 1)
        # observe() runs per request on serving hot paths: bisect on a
        # plain list is ~10x cheaper than np.searchsorted on a scalar
        self._edge_list = self._edges.tolist()
        self._counts = np.zeros(_N_BUCKETS + 2, np.int64)
        self._sum_ms = 0.0
        self._count = 0
        self._max_ms = 0.0

    def observe(self, ms: float) -> None:
        idx = bisect.bisect_right(self._edge_list, ms)
        self._counts[idx] += 1
        self._sum_ms += ms
        self._count += 1
        if ms > self._max_ms:
            self._max_ms = ms

    @property
    def count(self) -> int:
        return self._count

    @property
    def mean_ms(self) -> float:
        return self._sum_ms / self._count if self._count else 0.0

    @property
    def max_ms(self) -> float:
        return self._max_ms

    def count_above(self, ms: float) -> int:
        """Samples in buckets whose lower edge is >= `ms` (slightly
        conservative: the bucket straddling `ms` does not count).  The
        SLO burn-rate evaluator differences this against a prior
        snapshot to get bad-request counts per window."""
        jmin = bisect.bisect_left(self._edge_list, ms) + 1
        return int(self._counts[jmin:].sum())

    def percentile(self, q: float) -> float:
        """q in [0, 100].  Returns the upper edge of the bucket holding the
        q-th sample (conservative: never understates latency)."""
        if self._count == 0:
            return 0.0
        target = max(1, int(math.ceil(self._count * q / 100.0)))
        acc = 0
        for i, c in enumerate(self._counts):
            acc += int(c)
            if acc >= target:
                if i == 0:
                    return float(self._edges[0])
                if i >= _N_BUCKETS + 1:
                    return float(self._max_ms)
                return float(self._edges[i])
        return float(self._max_ms)

    def values_for_tensorboard(self) -> np.ndarray:
        """Approximate sample reconstruction (bucket midpoints repeated by
        count, capped) for Summary.add_histogram export."""
        out: List[float] = []
        mids = np.sqrt(self._edges[:-1] * self._edges[1:])
        for i, c in enumerate(self._counts[1:-1]):
            if c:
                out.extend([float(mids[i])] * min(int(c), 1000))
        return np.asarray(out if out else [0.0])


class ServingMetrics:
    """Thread-safe counters + histograms for the serving runtime.

    Tracked (the ISSUE/VERDICT serving-observability set):
      * latency histograms: queue wait, on-device batch, end-to-end
      * queue depth (current + high-water)
      * batch occupancy: real rows / padded bucket rows, per bucket
      * rejection counters: queue-full, deadline, shutdown

    `tenant=` adds a label dimension to every MetricsRegistry mirror
    (`serving/requests_admitted|tenant=<name>`, rendered by the
    Prometheus-textfile exporter as `{tenant="<name>"}`): the fleet
    front door gives each tenant its own ServingMetrics so per-tenant
    p50/p99/occupancy export through the SAME registry names instead of
    a parallel metrics path.  Unlabeled (tenant=None) behaviour is
    byte-identical to before.
    """

    def __init__(self, tenant: Optional[str] = None):
        self.tenant = tenant
        self._label = "" if tenant is None else f"|tenant={tenant}"
        # per-request registry keys, built once (hot-path string concat)
        self._k_admitted = "serving/requests_admitted" + self._label
        self._k_completed = "serving/requests_completed" + self._label
        self._k_batches = "serving/batches" + self._label
        self._lock = threading.Lock()
        self.queue_ms = LatencyHistogram()
        self.batch_ms = LatencyHistogram()
        self.total_ms = LatencyHistogram()
        self.requests_admitted = 0
        self.requests_completed = 0
        self.rejected_queue_full = 0
        self.rejected_deadline = 0
        self.rejected_shutdown = 0
        self.rejected_nonfinite = 0
        self.batches = 0
        self.rows_real = 0
        self.rows_padded = 0
        self.queue_depth = 0
        self.queue_depth_peak = 0
        self.swaps = 0
        self._per_bucket: Dict[int, Tuple[int, int]] = {}  # bucket -> (batches, rows)

    # -- recording ---------------------------------------------------------

    def on_admit(self, depth: int) -> None:
        with self._lock:
            self.requests_admitted += 1
            self.queue_depth = depth
            if depth > self.queue_depth_peak:
                self.queue_depth_peak = depth
        _obs.registry().inc(self._k_admitted)

    def on_reject(self, reason: str) -> None:
        with self._lock:
            if reason == "queue_full":
                self.rejected_queue_full += 1
            elif reason == "deadline":
                self.rejected_deadline += 1
            else:
                self.rejected_shutdown += 1
        _obs.registry().inc(f"serving/rejected_{reason}{self._label}")

    def on_batch(self, bucket: int, rows: int, batch_ms: float) -> None:
        with self._lock:
            self.batches += 1
            self.rows_real += rows
            self.rows_padded += bucket - rows
            self.batch_ms.observe(batch_ms)
            b, r = self._per_bucket.get(bucket, (0, 0))
            self._per_bucket[bucket] = (b + 1, r + rows)
        _obs.registry().inc(self._k_batches)

    def on_complete(self, queue_ms: float, total_ms: float, depth: int) -> None:
        with self._lock:
            self.requests_completed += 1
            self.queue_ms.observe(queue_ms)
            self.total_ms.observe(total_ms)
            self.queue_depth = depth
        _obs.registry().inc(self._k_completed)

    def on_nonfinite(self) -> None:
        """A request's OUTPUT rows contained NaN/Inf and the runtime's
        reject_nonfinite guard refused to return them (health policy —
        the serving dual of the trainer's divergence watchdog)."""
        with self._lock:
            self.rejected_nonfinite += 1
        _obs.registry().inc("serving/rejected_nonfinite" + self._label)

    def on_swap(self) -> None:
        with self._lock:
            self.swaps += 1
        _obs.registry().inc("serving/swaps" + self._label)

    # -- read-back ---------------------------------------------------------

    @property
    def occupancy(self) -> float:
        """Real rows / dispatched bucket rows (1.0 = no padding waste)."""
        dispatched = self.rows_real + self.rows_padded
        return self.rows_real / dispatched if dispatched else 0.0

    def snapshot(self) -> Dict:
        snap = self._snapshot_locked()
        # gauge mirror: the registry's serving/ view tracks the last
        # snapshot (counters above are incremented at record time)
        reg = _obs.registry()
        reg.set_gauge("serving/latency_p50_ms" + self._label, snap["latency_ms"]["p50"])
        reg.set_gauge("serving/latency_p99_ms" + self._label, snap["latency_ms"]["p99"])
        reg.set_gauge("serving/batch_occupancy" + self._label, snap["batch_occupancy"])
        reg.set_gauge("serving/queue_depth_peak" + self._label, snap["queue_depth_peak"])
        return snap

    def _snapshot_locked(self) -> Dict:
        with self._lock:
            per_bucket = {
                str(b): {"batches": n, "rows": r,
                         "occupancy": round(r / (n * b), 4) if n else 0.0}
                for b, (n, r) in sorted(self._per_bucket.items())}
            return {
                "requests_admitted": self.requests_admitted,
                "requests_completed": self.requests_completed,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_deadline": self.rejected_deadline,
                "rejected_shutdown": self.rejected_shutdown,
                "rejected_nonfinite": self.rejected_nonfinite,
                "batches": self.batches,
                "batch_occupancy": round(self.occupancy, 4),
                "per_bucket": per_bucket,
                "queue_depth_peak": self.queue_depth_peak,
                "swaps": self.swaps,
                "latency_ms": {
                    "p50": round(self.total_ms.percentile(50), 3),
                    "p99": round(self.total_ms.percentile(99), 3),
                    "mean": round(self.total_ms.mean_ms, 3),
                    "max": round(self.total_ms.max_ms, 3),
                },
                "queue_wait_ms": {
                    "p50": round(self.queue_ms.percentile(50), 3),
                    "p99": round(self.queue_ms.percentile(99), 3),
                },
                "device_batch_ms": {
                    "p50": round(self.batch_ms.percentile(50), 3),
                    "p99": round(self.batch_ms.percentile(99), 3),
                },
            }

    def export(self, summary, step: int, prefix: str = "serving") -> None:
        """Write the scalar set through `utils/summary.Summary` (lands in
        the same TB event stream as training Loss/Throughput)."""
        snap = self.snapshot()
        scalars = {
            f"{prefix}/latency_p50_ms": snap["latency_ms"]["p50"],
            f"{prefix}/latency_p99_ms": snap["latency_ms"]["p99"],
            f"{prefix}/queue_wait_p99_ms": snap["queue_wait_ms"]["p99"],
            f"{prefix}/queue_depth_peak": snap["queue_depth_peak"],
            f"{prefix}/batch_occupancy": snap["batch_occupancy"],
            f"{prefix}/rejected_queue_full": snap["rejected_queue_full"],
            f"{prefix}/rejected_deadline": snap["rejected_deadline"],
            f"{prefix}/rejected_nonfinite": snap["rejected_nonfinite"],
            f"{prefix}/requests_completed": snap["requests_completed"],
            f"{prefix}/batches": snap["batches"],
        }
        for tag, value in scalars.items():
            summary.add_scalar(tag, float(value), step)
        summary.add_histogram(f"{prefix}/latency_ms",
                              self.total_ms.values_for_tensorboard(), step)


class GenerationMetrics:
    """Per-token observability for the generation engine
    (bigdl_tpu/generation/engine.py) — the autoregressive dual of
    `ServingMetrics`.  The units shift from per-request to per-TOKEN:

      * `ttft_ms` — time-to-first-token (submit -> prefill's sampled
        token), the interactive-latency number.
      * `per_token_ms` — decode-step wall time; every in-flight request
        advances one token per step, so this IS ms/token under load.
      * `prefill_ms` — on-device prompt fold cost per admission.
      * `tokens_generated`, active-slot occupancy, rejection counters.

    Same log-bucketed histograms (no per-token memory growth) and the
    same Summary/TensorBoard export spine as serving.
    """

    def __init__(self, tenant: Optional[str] = None):
        self.tenant = tenant
        self._label = "" if tenant is None else f"|tenant={tenant}"
        self._lock = threading.Lock()
        self.ttft_ms = LatencyHistogram()
        # TTFT of requests admitted while another request's chunked long
        # prefill was in flight — the interactive-latency-under-long-
        # prompt number the chunked-prefill admission policy protects
        self.ttft_long_ms = LatencyHistogram()
        self.per_token_ms = LatencyHistogram()
        self.prefill_ms = LatencyHistogram()
        self.e2e_ms = LatencyHistogram()
        self.prefill_chunks = 0
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0
        # failover recovery: requests re-admitted with a dead replica's
        # progress snapshot (resume_tokens), their restart latency, and
        # how many rode a warm prefix instead of a cold recompute
        self.recovery_ttft_ms = LatencyHistogram()
        self.recoveries = 0
        self.recovered_tokens = 0
        self.recovery_prefix_hits = 0
        self.spec_rounds = 0
        self.draft_steps = 0
        self.draft_tokens_proposed = 0
        self.draft_tokens_accepted = 0
        self.tokens_generated = 0
        self.requests_admitted = 0
        self.requests_completed = 0
        self.rejected_queue_full = 0
        self.rejected_shutdown = 0
        self.rejected_nonfinite = 0
        self.prefills = 0
        self.decode_steps = 0
        self.queue_depth = 0
        self.queue_depth_peak = 0
        self.active_slots = 0
        self.active_slots_peak = 0
        self.swaps = 0

    # -- recording ---------------------------------------------------------

    def on_admit(self, depth: int) -> None:
        with self._lock:
            self.requests_admitted += 1
            self.queue_depth = depth
            if depth > self.queue_depth_peak:
                self.queue_depth_peak = depth
        _obs.registry().inc("generation/requests_admitted" + self._label)

    def on_reject(self, reason: str) -> None:
        with self._lock:
            if reason == "queue_full":
                self.rejected_queue_full += 1
            else:
                self.rejected_shutdown += 1
        _obs.registry().inc(f"generation/rejected_{reason}{self._label}")

    def on_prefill(self, prefill_ms: float, ttft_ms: float,
                   contended: bool = False) -> None:
        """One admission: prompt folded, first token sampled.
        `contended=True` marks a request whose admission overlapped a
        chunked long prefill — its TTFT additionally lands in the
        under-long-prompt histogram."""
        with self._lock:
            self.prefills += 1
            self.tokens_generated += 1  # prefill samples token #1
            self.prefill_ms.observe(prefill_ms)
            self.ttft_ms.observe(ttft_ms)
            if contended:
                self.ttft_long_ms.observe(ttft_ms)
        _obs.registry().inc("generation/prefills" + self._label)
        _obs.registry().inc("generation/tokens" + self._label)

    def on_prefill_chunk(self) -> None:
        """One prefill_chunk executable ran (chunked prompt ingestion)."""
        with self._lock:
            self.prefill_chunks += 1
        _obs.registry().inc("generation/prefill_chunks" + self._label)

    def on_prefix_hit(self, tokens_reused: int) -> None:
        """One admission mapped a warm prefix from the prefix store
        (prefixcache.py): `tokens_reused` prompt tokens were skipped by
        chunked prefill because their KV blocks were already resident."""
        with self._lock:
            self.prefix_hits += 1
            self.prefix_tokens_reused += int(tokens_reused)
        reg = _obs.registry()
        reg.inc("generation/prefix_hits" + self._label)
        reg.inc("generation/prefix_tokens_reused" + self._label,
                int(tokens_reused))

    def on_recovery(self, ttft_ms: float, resumed_tokens: int,
                    prefix_tokens: int) -> None:
        """One resumed request reached its first NEW token on this engine
        after a replica death: `ttft_ms` is submit-on-survivor to first
        fresh token (the recovery-latency number the warm-prefix path
        exists to shrink), `resumed_tokens` came from the victim's
        progress snapshot, `prefix_tokens` of the effective prompt were
        skipped via the prefix store (0 = cold recompute)."""
        with self._lock:
            self.recoveries += 1
            self.recovered_tokens += int(resumed_tokens)
            self.recovery_ttft_ms.observe(ttft_ms)
            if prefix_tokens > 0:
                self.recovery_prefix_hits += 1
        reg = _obs.registry()
        reg.inc("generation/recoveries" + self._label)
        reg.inc("generation/recovered_tokens" + self._label,
                int(resumed_tokens))
        if prefix_tokens > 0:
            reg.inc("generation/recovery_prefix_hits" + self._label)

    def on_spec_round(self, proposed: int, accepted: int,
                      draft_steps: int) -> None:
        """One speculative decode round: `proposed` draft tokens offered
        across active slots, `accepted` survived verification,
        `draft_steps` draft-model forwards ran.  The acceptance-rate
        gauge is cumulative (accepted / proposed over the engine's
        life) — the number to watch when deciding whether the draft is
        worth its steps (docs/serving.md)."""
        with self._lock:
            self.spec_rounds += 1
            self.draft_steps += draft_steps
            self.draft_tokens_proposed += proposed
            self.draft_tokens_accepted += accepted
            rate = self.draft_tokens_accepted / self.draft_tokens_proposed \
                if self.draft_tokens_proposed else 0.0
        reg = _obs.registry()
        reg.inc("generation/spec_rounds" + self._label)
        reg.inc("generation/draft_steps" + self._label, draft_steps)
        reg.set_gauge("generation/spec_accept_rate" + self._label, rate)

    def on_tokens(self, n: int, step_ms: float) -> None:
        """One decode step advancing `n` in-flight requests a token each."""
        with self._lock:
            self.decode_steps += 1
            self.tokens_generated += n
            self.per_token_ms.observe(step_ms)
        _obs.registry().inc("generation/tokens" + self._label, n)
        _obs.registry().inc("generation/decode_steps" + self._label)

    def on_complete(self, e2e_ms: float, tokens: int) -> None:
        with self._lock:
            self.requests_completed += 1
            self.e2e_ms.observe(e2e_ms)
        _obs.registry().inc("generation/requests_completed" + self._label)

    def on_nonfinite(self) -> None:
        with self._lock:
            self.rejected_nonfinite += 1
        _obs.registry().inc("generation/rejected_nonfinite" + self._label)

    def on_swap(self) -> None:
        with self._lock:
            self.swaps += 1
        _obs.registry().inc("generation/swaps" + self._label)

    def set_active(self, n: int) -> None:
        with self._lock:
            self.active_slots = n
            if n > self.active_slots_peak:
                self.active_slots_peak = n

    # -- read-back ---------------------------------------------------------

    def snapshot(self) -> Dict:
        with self._lock:
            snap = {
                "requests_admitted": self.requests_admitted,
                "requests_completed": self.requests_completed,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_shutdown": self.rejected_shutdown,
                "rejected_nonfinite": self.rejected_nonfinite,
                "tokens_generated": self.tokens_generated,
                "prefills": self.prefills,
                "decode_steps": self.decode_steps,
                "queue_depth_peak": self.queue_depth_peak,
                "active_slots": self.active_slots,
                "active_slots_peak": self.active_slots_peak,
                "swaps": self.swaps,
                "ttft_ms": {
                    "p50": round(self.ttft_ms.percentile(50), 3),
                    "p99": round(self.ttft_ms.percentile(99), 3),
                    "mean": round(self.ttft_ms.mean_ms, 3),
                },
                "ms_per_token": {
                    "p50": round(self.per_token_ms.percentile(50), 3),
                    "p99": round(self.per_token_ms.percentile(99), 3),
                    "mean": round(self.per_token_ms.mean_ms, 3),
                    "max": round(self.per_token_ms.max_ms, 3),
                },
                "prefill_ms": {
                    "p50": round(self.prefill_ms.percentile(50), 3),
                    "p99": round(self.prefill_ms.percentile(99), 3),
                },
                "e2e_ms": {
                    "p50": round(self.e2e_ms.percentile(50), 3),
                    "p99": round(self.e2e_ms.percentile(99), 3),
                },
                "prefill_chunks": self.prefill_chunks,
                "prefix_hits": self.prefix_hits,
                "prefix_tokens_reused": self.prefix_tokens_reused,
                "recoveries": self.recoveries,
                "recovered_tokens": self.recovered_tokens,
                "recovery_prefix_hits": self.recovery_prefix_hits,
                "recovery_ttft_ms": {
                    "count": self.recovery_ttft_ms.count,
                    "p50": round(self.recovery_ttft_ms.percentile(50), 3),
                    "p99": round(self.recovery_ttft_ms.percentile(99), 3),
                },
                "spec_rounds": self.spec_rounds,
                "draft_steps": self.draft_steps,
                "spec_accept_rate": round(
                    self.draft_tokens_accepted / self.draft_tokens_proposed,
                    4) if self.draft_tokens_proposed else 0.0,
                "ttft_under_long_prefill_ms": {
                    "count": self.ttft_long_ms.count,
                    "p50": round(self.ttft_long_ms.percentile(50), 3),
                    "p99": round(self.ttft_long_ms.percentile(99), 3),
                },
            }
        reg = _obs.registry()
        reg.set_gauge("generation/ms_per_token_p50" + self._label, snap["ms_per_token"]["p50"])
        reg.set_gauge("generation/ms_per_token_p99" + self._label, snap["ms_per_token"]["p99"])
        reg.set_gauge("generation/ttft_p50_ms" + self._label, snap["ttft_ms"]["p50"])
        reg.set_gauge("generation/active_slots_peak" + self._label, snap["active_slots_peak"])
        return snap

    def export(self, summary, step: int, prefix: str = "generation") -> None:
        """Scalar set through `utils/summary.Summary` — attach a
        `ServingSummary` and generation latency lands beside the serving
        p50/p99 in the same TensorBoard stream."""
        snap = self.snapshot()
        scalars = {
            f"{prefix}/tokens_generated": snap["tokens_generated"],
            f"{prefix}/ms_per_token_p50": snap["ms_per_token"]["p50"],
            f"{prefix}/ms_per_token_p99": snap["ms_per_token"]["p99"],
            f"{prefix}/ttft_p50_ms": snap["ttft_ms"]["p50"],
            f"{prefix}/ttft_p99_ms": snap["ttft_ms"]["p99"],
            f"{prefix}/prefill_p99_ms": snap["prefill_ms"]["p99"],
            f"{prefix}/requests_completed": snap["requests_completed"],
            f"{prefix}/rejected_queue_full": snap["rejected_queue_full"],
            f"{prefix}/rejected_nonfinite": snap["rejected_nonfinite"],
            f"{prefix}/active_slots_peak": snap["active_slots_peak"],
            f"{prefix}/decode_steps": snap["decode_steps"],
            f"{prefix}/prefill_chunks": snap["prefill_chunks"],
            f"{prefix}/prefix_hits": snap["prefix_hits"],
            f"{prefix}/prefix_tokens_reused": snap["prefix_tokens_reused"],
            f"{prefix}/recoveries": snap["recoveries"],
            f"{prefix}/recovered_tokens": snap["recovered_tokens"],
            f"{prefix}/recovery_prefix_hits": snap["recovery_prefix_hits"],
            f"{prefix}/recovery_ttft_p99_ms":
                snap["recovery_ttft_ms"]["p99"],
            f"{prefix}/spec_rounds": snap["spec_rounds"],
            f"{prefix}/draft_steps": snap["draft_steps"],
            f"{prefix}/spec_accept_rate": snap["spec_accept_rate"],
            f"{prefix}/ttft_under_long_prefill_p99_ms":
                snap["ttft_under_long_prefill_ms"]["p99"],
        }
        for tag, value in scalars.items():
            summary.add_scalar(tag, float(value), step)
        summary.add_histogram(f"{prefix}/ms_per_token",
                              self.per_token_ms.values_for_tensorboard(),
                              step)
