"""Versioned model registry with atomic hot-swap and AOT warmup.

Reference: the reference has no model versioning; swapping weights under
load means tearing down the PredictionService pool and rebuilding it
(optim/PredictionService.scala:56 — the pool is constructed once from one
module).  Here a version is an IMMUTABLE snapshot (params pytree + model
state + metadata); swap is one reference assignment under a lock, so a
dispatching batch that grabbed the previous snapshot keeps computing with
a consistent single version — no torn reads, no half-old-half-new params.

Warmup: `register()` runs the runtime-supplied warmup callable (one jitted
forward per serving bucket) BEFORE the version becomes active, so the
first post-swap request never pays an XLA compile.  Because the jit cache
is keyed on shapes — not on param VALUES — a swap between same-shaped
checkpoints warms from cache in microseconds.

Checkpoints load through `utils/checkpoint.load_params` (the trainer's own
schema: `ckpt_<step>/params.npz` + `model_state.npz`), templated on the
active version so a shape-drifted checkpoint is rejected loudly at
registration, never at request time.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional

import jax

from bigdl_tpu import obs as _obs


class ModelVersion(NamedTuple):
    version: str
    params: Any
    state: Any
    registered_at: float
    source: str  # "memory" | checkpoint dir


class ModelRegistry:
    """Thread-safe version store; `active()` is the single hot-path read."""

    def __init__(self, warmup: Optional[Callable[[Any, Any], None]] = None):
        self._lock = threading.Lock()
        self._versions: Dict[str, ModelVersion] = {}
        self._active: Optional[ModelVersion] = None
        self._draft: Optional[ModelVersion] = None
        self._warmups: List[Callable[[Any, Any], None]] = \
            [warmup] if warmup is not None else []

    def add_warmup(self, warmup: Callable[[Any, Any], None]) -> None:
        """Join the pre-activation warmup chain (e.g. a GenerationEngine
        layering its prefill/decode executables behind the same registry:
        one hot-swap warms every consumer before the version goes live)."""
        self._warmups.append(warmup)

    # -- hot path ----------------------------------------------------------

    def active(self) -> ModelVersion:
        """One atomic reference read; callers hold the returned snapshot
        for the whole batch so every row sees the same version."""
        snap = self._active
        if snap is None:
            raise RuntimeError("no active model version registered")
        return snap

    # -- management --------------------------------------------------------

    def register(self, version: str, params: Any, state: Any = None, *,
                 activate: bool = True, source: str = "memory") -> ModelVersion:
        if state is None:
            state = {}
        # commit the trees once here: host-resident leaves (checkpoint
        # loads arrive as numpy) would re-transfer on every dispatch
        params = jax.device_put(params)
        state = jax.device_put(state)
        mv = ModelVersion(str(version), params, state, time.time(), source)
        for warmup in self._warmups:
            # compile/warm BEFORE the swap: requests keep hitting the old
            # version until the new one is ready to serve at full speed
            with _obs.span("registry.warmup", cat="serving",
                           version=mv.version):
                warmup(mv.params, mv.state)
        with self._lock:
            self._versions[mv.version] = mv
            if activate or self._active is None:
                self._active = mv
        _obs.registry().inc("serving/registrations")
        _obs.instant("registry.activate", cat="serving", version=mv.version,
                     source=source)
        return mv

    def register_checkpoint(self, version: str, ckpt_dir: str, *,
                            activate: bool = True) -> ModelVersion:
        """Load `ckpt_dir` (a trainer `ckpt_<step>` dir) templated on the
        active version's trees and register it.

        Both layouts load: a chunked (v2) checkpoint saved under the
        TRAINING mesh reshards on load — each leaf is assembled onto the
        active version's own (inference) sharding from exactly the chunks
        intersecting it, per-chunk CRC-verified, so a tp=4 training save
        serves on a tp=2 inference mesh without a host-side gather.  The
        warmup chain + compilecache reuse in `register` are unchanged."""
        from bigdl_tpu.utils.checkpoint import load_params

        current = self.active()
        params, state = load_params(ckpt_dir, current.params,
                                    current.state if current.state else None)
        return self.register(version, params, state if state is not None else {},
                             activate=activate, source=str(ckpt_dir))

    def register_from_checkpoint(self, path: str, *,
                                 version: Optional[str] = None,
                                 activate: bool = True) -> ModelVersion:
        """Register straight from a trainer checkpoint tree: `path` may be
        either one `ckpt_<step>` dir or the checkpoint ROOT the trainer
        wrote into — the newest COMMITTED step is resolved via
        `latest_checkpoint` (interrupted partial saves never load; the
        meta.json commit marker gates them out).  `version` defaults to
        the resolved dir's basename (e.g. "ckpt_1200"), so rolling
        promotion from a training run is one call per save point.

        Integrity: unless `BIGDL_TPU_CKPT_VERIFY` is off, the candidate's
        CRC32C checksums are verified before it can become a serving
        version — per-leaf for monolithic (v1) saves, per-chunk for
        sharded (v2) saves — and root resolution walks PAST corrupt saves
        to the newest intact one; a directly-named corrupt dir raises
        `CorruptCheckpointError` instead of serving flipped bits."""
        import os

        from bigdl_tpu.health.integrity import verify_enabled
        from bigdl_tpu.utils.checkpoint import (latest_checkpoint,
                                                verify_checkpoint)

        verify = verify_enabled(None)
        ckpt_dir = path
        base = os.path.basename(str(path).rstrip("/"))
        if not (base.startswith("ckpt_")
                and base[len("ckpt_"):].isdigit()):
            resolved = latest_checkpoint(path, verify=verify or None)
            if resolved is None:
                raise FileNotFoundError(
                    f"no committed checkpoint under {path!r}")
            ckpt_dir = resolved
        elif verify:
            verify_checkpoint(ckpt_dir)
        if version is None:
            version = os.path.basename(str(ckpt_dir).rstrip("/"))
        return self.register_checkpoint(version, ckpt_dir, activate=activate)

    def set_draft(self, version: str, params: Any,
                  state: Any = None) -> ModelVersion:
        """Install the speculative-decoding DRAFT model's weights beside
        the target versions.  The draft is not a serving version (it
        never becomes `active()`); it exists so the warmup chain can warm
        the draft/verify executables exactly like target swaps warm
        prefill/decode — when a target is already active, the chain is
        re-run here so replacing the draft never cold-compiles the spec
        lane mid-traffic.  Conversely `register()` re-runs the same
        chain, so a TARGET hot-swap re-warms the verify executable (it
        traces against target params) before activation."""
        if state is None:
            state = {}
        params = jax.device_put(params)
        state = jax.device_put(state)
        mv = ModelVersion(str(version), params, state, time.time(), "draft")
        with self._lock:
            self._draft = mv
        active = self._active
        if active is not None:
            for warmup in self._warmups:
                with _obs.span("registry.warmup", cat="serving",
                               version=f"draft:{mv.version}"):
                    warmup(active.params, active.state)
        _obs.instant("registry.set_draft", cat="serving", version=mv.version)
        return mv

    def draft(self) -> Optional[ModelVersion]:
        """The installed draft version, or None (one atomic read, same
        contract as `active()`)."""
        return self._draft

    def activate(self, version: str) -> ModelVersion:
        """Atomic swap to an already-registered version (e.g. rollback)."""
        with self._lock:
            if version not in self._versions:
                raise KeyError(f"unknown model version {version!r}; "
                               f"registered: {sorted(self._versions)}")
            self._active = self._versions[version]
            return self._active

    def retire(self, version: str) -> None:
        with self._lock:
            if self._active is not None and self._active.version == version:
                raise ValueError(
                    f"version {version!r} is active; activate another "
                    "version before retiring it")
            self._versions.pop(version, None)

    def versions(self) -> List[str]:
        with self._lock:
            return sorted(self._versions)

    @property
    def active_version(self) -> Optional[str]:
        snap = self._active
        return snap.version if snap is not None else None
