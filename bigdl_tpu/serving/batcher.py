"""Dynamic micro-batching scheduler with admission control.

Reference: optim/PredictionService.scala:56 — the reference admits every
request immediately and runs it alone on a pooled module clone; under
overload the JVM queue grows without bound and every distinct request
shape is a fresh execution plan.  Here the scheduler is shaped by the TPU
cost model instead:

  * Requests park in a BOUNDED queue (overload rejects loudly at admission
    instead of queueing into timeout oblivion).
  * A single scheduler thread coalesces waiting requests into the
    smallest configured BUCKET that fits them (pad-to-bucket), dispatching
    either when the largest bucket is full or when the oldest waiting
    request has waited `max_wait_ms` — the classic latency/occupancy
    trade, made explicit.
  * Per-request deadlines: a request whose deadline passes while it waits
    is failed with `DeadlineExceeded` at coalesce time and never occupies
    device rows; requests that expire mid-collection simply drop out of
    the forming batch.
  * `close(drain=True)` stops admission, runs the queue dry (in-flight
    batches complete), then joins the scheduler — the serving analogue of
    the trainer's telemetry-ring drain guard
    (tests/test_trainer_drain_guard.py).

The batcher is model-agnostic: `dispatch(requests, bucket)` is injected by
`ServingRuntime`, which owns padding, the jitted forward, and result
splitting.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from bigdl_tpu import obs as _obs

logger = logging.getLogger("bigdl_tpu.serving")


class Rejected(RuntimeError):
    """Request refused at admission (queue full / runtime closed)."""


class ServingClosed(Rejected):
    """Runtime is shut down (or shutting down) — request not admitted."""


class DeadlineExceeded(Rejected):
    """Request deadline passed before its batch dispatched."""


class _Future:
    """Single-assignment result slot (stdlib concurrent.futures would drag
    in an executor; the scheduler thread IS the executor here)."""

    __slots__ = ("_event", "_value", "_error", "meta", "_cb_lock",
                 "_callbacks")

    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self.meta: dict = {}
        self._cb_lock = threading.Lock()
        self._callbacks: list = []

    def _settle(self) -> None:
        """Fire registered callbacks exactly once (the first set_result/
        set_error wins; a late overwrite finds the list already drained)."""
        with self._cb_lock:
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — a broken callback must not
                logger.exception("future done-callback raised")  # hang peers

    def set_result(self, value) -> None:
        self._value = value
        self._event.set()
        self._settle()

    def set_error(self, err: BaseException) -> None:
        self._error = err
        self._event.set()
        self._settle()

    def done(self) -> bool:
        return self._event.is_set()

    def error(self) -> Optional[BaseException]:
        """Peek the failure without raising (None while pending/ok)."""
        return self._error

    def add_done_callback(self, fn) -> None:
        """`fn(future)` when the future settles — immediately if it
        already has.  Runs on the settling thread (the fleet router's
        completion chaining; keep callbacks cheap and non-blocking)."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("serving request did not complete in time")
        if self._error is not None:
            raise self._error
        return self._value


class _Request:
    __slots__ = ("x", "rows", "future", "t_enqueue", "deadline", "cid")

    def __init__(self, x: Any, rows: int, deadline: Optional[float],
                 cid: Optional[str] = None):
        self.x = x
        self.rows = rows
        self.future = _Future()
        self.t_enqueue = time.perf_counter()
        self.deadline = deadline  # absolute perf_counter time, or None
        # correlation id: stitches this request across the submitter
        # thread, the batcher lane and the dispatch lane in the trace,
        # and lands in future.meta + the driver log.  The fleet router
        # passes its own cid down so ONE id follows a request across
        # replicas (including redispatch); direct submits mint here.
        self.cid = cid if cid is not None else _obs.next_cid()

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


def pick_bucket(buckets: Sequence[int], rows: int) -> int:
    """Smallest configured bucket that fits `rows` (buckets sorted asc)."""
    for b in buckets:
        if rows <= b:
            return b
    raise ValueError(f"{rows} rows exceed the largest bucket {buckets[-1]}")


class MicroBatcher:
    """Bounded-queue request coalescer around an injected dispatch fn.

    dispatch(requests, bucket) must fulfil every request's future (it owns
    padding + forward + splitting); an exception from dispatch fails the
    whole batch.
    """

    def __init__(self, dispatch: Callable[[List[_Request], int], None],
                 *, buckets: Sequence[int] = (1, 8, 32),
                 max_wait_ms: float = 2.0, capacity: int = 128,
                 default_deadline_ms: Optional[float] = None,
                 metrics=None, name: str = "serving-batcher"):
        if not buckets:
            raise ValueError("need at least one bucket size")
        self.buckets: Tuple[int, ...] = tuple(sorted(set(int(b) for b in buckets)))
        if self.buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {buckets}")
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.default_deadline_ms = default_deadline_ms
        self._dispatch = dispatch
        self._metrics = metrics
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=int(capacity))
        self._closed = False
        self._abort = False
        self._drained = threading.Event()
        self._carry: Optional[_Request] = None  # overflow from the last batch
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    # -- admission ---------------------------------------------------------

    def submit(self, x: Any, rows: int,
               deadline_ms: Optional[float] = None,
               cid: Optional[str] = None) -> _Future:
        if rows < 1 or rows > self.buckets[-1]:
            raise ValueError(
                f"request rows {rows} outside [1, {self.buckets[-1]}] "
                f"(largest bucket); chunk oversized requests before submit")
        if self._closed:
            if self._metrics:
                self._metrics.on_reject("shutdown")
            raise ServingClosed("serving runtime is closed")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline = (time.perf_counter() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = _Request(x, rows, deadline, cid=cid)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            if self._metrics:
                self._metrics.on_reject("queue_full")
            _obs.instant("serve.reject", cat="serving", cid=req.cid,
                         reason="queue_full")
            raise Rejected(
                f"serving queue full ({self._queue.maxsize} requests); "
                "backpressure — retry with backoff or raise capacity")
        if self._metrics:
            self._metrics.on_admit(self._queue.qsize())
        tr = _obs.tracer()
        if tr is not None:
            tr.instant("serve.admit", cat="serving", cid=req.cid,
                       rows=rows, depth=self._queue.qsize())
        logger.debug("admitted request %s (%d rows)", req.cid, rows,
                     extra={"cid": req.cid})
        return req.future

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    # -- scheduler loop ----------------------------------------------------

    def _take(self, timeout: Optional[float]) -> Optional[_Request]:
        if self._carry is not None:
            req, self._carry = self._carry, None
            return req
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def _collect(self) -> Optional[Tuple[List[_Request], int]]:
        """Block for the first request, then gather until the largest
        bucket fills or the first request has waited max_wait_ms."""
        first = self._take(timeout=0.05)
        if first is None:
            return None
        batch = [first]
        rows = first.rows
        deadline = time.perf_counter() + self.max_wait_s
        max_rows = self.buckets[-1]
        while rows < max_rows:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            nxt = self._take(timeout=remaining)
            if nxt is None:
                break
            if rows + nxt.rows > max_rows:
                self._carry = nxt  # heads the next batch
                break
            batch.append(nxt)
            rows += nxt.rows
        return batch, rows

    def _expire(self, batch: List[_Request]) -> List[_Request]:
        """Fail deadline-expired requests; they never occupy device rows."""
        now = time.perf_counter()
        live = []
        for req in batch:
            if req.expired(now):
                if self._metrics:
                    self._metrics.on_reject("deadline")
                _obs.instant("serve.reject", cat="serving", cid=req.cid,
                             reason="deadline")
                req.future.set_error(DeadlineExceeded(
                    f"deadline passed after {1e3 * (now - req.t_enqueue):.1f} ms "
                    "in queue (coalesced but not dispatched)"))
            else:
                live.append(req)
        return live

    def _loop(self) -> None:
        while True:
            got = self._collect()
            if got is None:
                if self._closed and self._carry is None and self._queue.empty():
                    break
                continue
            batch, _ = got
            if self._abort:
                for req in batch:
                    if self._metrics:
                        self._metrics.on_reject("shutdown")
                    req.future.set_error(ServingClosed("runtime shut down"))
                continue
            batch = self._expire(batch)
            if not batch:
                continue
            bucket = pick_bucket(self.buckets, sum(r.rows for r in batch))
            try:
                self._dispatch(batch, bucket)
            except BaseException as e:  # noqa: BLE001 — fail the batch, keep serving
                for req in batch:
                    if not req.future.done():
                        req.future.set_error(e)
        # submissions that raced the close flag and slipped into the queue
        # after the final empty-check must not hang their callers
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.future.set_error(ServingClosed("runtime shut down"))
        self._drained.set()

    # -- shutdown ----------------------------------------------------------

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop admission; `drain=True` completes everything already
        admitted (in-flight batches included), `drain=False` fails the
        still-queued requests with ServingClosed."""
        self._closed = True
        if not drain:
            # the scheduler thread itself fails what is still queued (it
            # owns the carry slot; draining from this thread would race it)
            self._abort = True
        if not self._drained.wait(timeout):
            raise TimeoutError("serving batcher did not drain in time")
        self._thread.join(timeout)
