"""chaos — deterministic fault injection for the resilience test suite.

Recovery code that is never executed is recovery code that does not work:
the reference's driver-side retry (optim/DistriOptimizer.scala:855-935)
shipped for years with no test killing a training job.  This module makes
every failure mode a REPRODUCIBLE fixture:

  * `StepFaultInjector` — raises at exact (or seeded-pseudorandom) global
    step indices, exercising the optimizer's bounded retry+restore loop;
  * `CheckpointWriteFault` — fails the Nth checkpoint file write MID-FILE
    (half the payload on disk), exercising the atomic-commit protocol and
    the partial-dir GC on resume;
  * `SimulatedPreemption` — triggers a PreemptionGuard at a step index,
    exercising the final-sync-save + marker + clean-drain path without
    touching process signals.

Everything is seeded/step-indexed — no wall clock, no real randomness —
so a failing recovery path replays bit-for-bit under pytest.  Hooks attach
with `Optimizer.set_chaos(hook)`; compose several with `compose()`.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional, Sequence, Set

import numpy as np

from bigdl_tpu.resilience.preemption import PreemptionGuard


class ChaosStepFault(RuntimeError):
    """The injected step-function failure (stands in for a lost device,
    a poisoned batch, an XLA runtime error...)."""


class StepFaultInjector:
    """Raise `exc_type` immediately before the step whose global index
    (completed-step count, the optimizer's `neval`) is in `fail_steps`.

    `seed`/`horizon`/`n_faults` derive the fail set pseudorandomly but
    reproducibly.  `once=True` (default) fires each step index a single
    time across restarts — the injector outlives the retry loop, so the
    replayed step succeeds on the next attempt (a transient fault);
    `once=False` models a persistent fault that exhausts the retry budget.
    """

    def __init__(self, fail_steps: Sequence[int] = (), *,
                 seed: Optional[int] = None, horizon: Optional[int] = None,
                 n_faults: int = 1, once: bool = True,
                 exc_type: type = ChaosStepFault):
        steps = set(int(s) for s in fail_steps)
        if seed is not None:
            if not horizon:
                raise ValueError("seeded injection needs `horizon` (the "
                                 "step range to draw fail steps from)")
            rs = np.random.RandomState(seed)
            # steps 1..horizon-1: step 0 has no checkpoint to restore from
            draw = rs.choice(np.arange(1, horizon), size=min(n_faults, horizon - 1),
                             replace=False)
            steps |= {int(s) for s in draw}
        self.fail_steps: Set[int] = steps
        self.once = once
        self.exc_type = exc_type
        self.fired: list = []

    def on_step(self, step: int) -> None:
        if step in self.fail_steps and (not self.once
                                        or step not in self.fired):
            self.fired.append(step)
            raise self.exc_type(f"chaos: injected fault before step {step}")


class CheckpointWriteFault:
    """`fault=` hook for AsyncCheckpointer: fail the write of `fail_file`
    on the `fail_on_save`-th checkpoint attempt (1-based), mid-file."""

    def __init__(self, fail_on_save: int = 1, fail_file: str = "params.npz",
                 n_failures: int = 1):
        self.fail_on_save = int(fail_on_save)
        self.fail_file = fail_file
        self.n_failures = int(n_failures)
        self.saves_seen = 0
        self.fired = 0

    def __call__(self, relname: str) -> bool:
        if relname == self.fail_file:
            self.saves_seen += 1
            if self.saves_seen >= self.fail_on_save \
                    and self.fired < self.n_failures:
                self.fired += 1
                return True
        return False


class SimulatedPreemption:
    """Trigger `guard` right before step `at_step` — the deterministic
    stand-in for the SIGTERM a preemptible pool delivers."""

    def __init__(self, guard: PreemptionGuard, at_step: int,
                 reason: str = "chaos: simulated preemption"):
        self.guard = guard
        self.at_step = int(at_step)
        self.reason = reason
        self.fired = False

    def on_step(self, step: int) -> None:
        if not self.fired and step >= self.at_step:
            self.fired = True
            self.guard.trigger(self.reason)


def compose(*hooks) -> "_Composed":
    """One chaos hook fanning out to several injectors, in order."""
    return _Composed(hooks)


class _Composed:
    def __init__(self, hooks: Iterable):
        self.hooks = list(hooks)

    def on_step(self, step: int) -> None:
        for h in self.hooks:
            h.on_step(step)
