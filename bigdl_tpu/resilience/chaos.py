"""chaos — deterministic fault injection for the resilience test suite.

Recovery code that is never executed is recovery code that does not work:
the reference's driver-side retry (optim/DistriOptimizer.scala:855-935)
shipped for years with no test killing a training job.  This module makes
every failure mode a REPRODUCIBLE fixture:

  * `StepFaultInjector` — raises at exact (or seeded-pseudorandom) global
    step indices, exercising the optimizer's bounded retry+restore loop;
  * `CheckpointWriteFault` — fails the Nth checkpoint file write MID-FILE
    (half the payload on disk), exercising the atomic-commit protocol and
    the partial-dir GC on resume;
  * `SimulatedPreemption` — triggers a PreemptionGuard at a step index,
    exercising the final-sync-save + marker + clean-drain path without
    touching process signals;
  * `NaNInjector` — poisons the loss or gradients of exact (or seeded)
    step indices ON DEVICE (the optimizer folds its poison code into the
    jitted step), exercising the divergence watchdog's full policy ladder
    skip -> lr_backoff -> rollback -> abort;
  * `BitFlipCheckpointFault` — flips seeded byte(s) of a COMMITTED
    checkpoint shard after the atomic rename, exercising the CRC32C
    verify + restore fallback chain (bit-rot, not a torn write).

Everything is seeded/step-indexed — no wall clock, no real randomness —
so a failing recovery path replays bit-for-bit under pytest.  Hooks attach
with `Optimizer.set_chaos(hook)`; compose several with `compose()`.
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional, Sequence, Set

import numpy as np

from bigdl_tpu.resilience.preemption import PreemptionGuard


class ChaosStepFault(RuntimeError):
    """The injected step-function failure (stands in for a lost device,
    a poisoned batch, an XLA runtime error...)."""


class StepFaultInjector:
    """Raise `exc_type` immediately before the step whose global index
    (completed-step count, the optimizer's `neval`) is in `fail_steps`.

    `seed`/`horizon`/`n_faults` derive the fail set pseudorandomly but
    reproducibly.  `once=True` (default) fires each step index a single
    time across restarts — the injector outlives the retry loop, so the
    replayed step succeeds on the next attempt (a transient fault);
    `once=False` models a persistent fault that exhausts the retry budget.
    """

    def __init__(self, fail_steps: Sequence[int] = (), *,
                 seed: Optional[int] = None, horizon: Optional[int] = None,
                 n_faults: int = 1, once: bool = True,
                 exc_type: type = ChaosStepFault):
        steps = set(int(s) for s in fail_steps)
        if seed is not None:
            if not horizon:
                raise ValueError("seeded injection needs `horizon` (the "
                                 "step range to draw fail steps from)")
            rs = np.random.RandomState(seed)
            # steps 1..horizon-1: step 0 has no checkpoint to restore from
            draw = rs.choice(np.arange(1, horizon), size=min(n_faults, horizon - 1),
                             replace=False)
            steps |= {int(s) for s in draw}
        self.fail_steps: Set[int] = steps
        self.once = once
        self.exc_type = exc_type
        self.fired: list = []

    def on_step(self, step: int) -> None:
        if step in self.fail_steps and (not self.once
                                        or step not in self.fired):
            self.fired.append(step)
            raise self.exc_type(f"chaos: injected fault before step {step}")


class CheckpointWriteFault:
    """`fault=` hook for AsyncCheckpointer: fail the write of `fail_file`
    on the `fail_on_save`-th checkpoint attempt (1-based), mid-file.

    Layout-aware: under the chunked (v2) layout a tree is many chunk
    files, so a `fail_file` of `"<tree>.npz"` also matches that tree's
    chunk files (`<tree>/...npy`) and fires on the FIRST one of the
    armed save.  The chunked writer announces each save via `note_save()`
    (one save = many writes — counting per-file would inflate
    `saves_seen`); the monolithic writer never calls it and the original
    one-match-per-save counting applies."""

    def __init__(self, fail_on_save: int = 1, fail_file: str = "params.npz",
                 n_failures: int = 1):
        self.fail_on_save = int(fail_on_save)
        self.fail_file = fail_file
        self.n_failures = int(n_failures)
        self.saves_seen = 0
        self.fired = 0
        self._per_save = False

    def note_save(self) -> None:
        """Chunked-writer save announcement: counts the save attempt."""
        self._per_save = True
        self.saves_seen += 1

    def __call__(self, relname: str) -> bool:
        tree = self.fail_file[:-len(".npz")] \
            if self.fail_file.endswith(".npz") else None
        if relname != self.fail_file and not (
                tree and relname.startswith(tree + "/")):
            return False
        if not self._per_save:
            self.saves_seen += 1  # monolithic: one matching write per save
        if self.saves_seen >= self.fail_on_save \
                and self.fired < self.n_failures:
            self.fired += 1
            return True
        return False


class SimulatedPreemption:
    """Trigger `guard` right before step `at_step` — the deterministic
    stand-in for the SIGTERM a preemptible pool delivers."""

    def __init__(self, guard: PreemptionGuard, at_step: int,
                 reason: str = "chaos: simulated preemption"):
        self.guard = guard
        self.at_step = int(at_step)
        self.reason = reason
        self.fired = False

    def on_step(self, step: int) -> None:
        if not self.fired and step >= self.at_step:
            self.fired = True
            self.guard.trigger(self.reason)


POISON_NONE = 0
POISON_LOSS = 1
POISON_GRAD = 2


class NaNInjector:
    """Poison the numerics of the steps in `fail_steps` — the divergence
    watchdog's test fixture.

    Unlike the other injectors this one does not raise on the host: the
    optimizer queries `poison_code(step)` at dispatch and feeds the code
    to the jitted step as a device scalar, which adds NaN to the loss
    (`target="loss"`) or to every gradient leaf (`target="grad"`) ON
    DEVICE — so the watchdog's detection path (finite-check folded into
    the step, zero extra host syncs) is exercised end to end, not
    shortcut by a host-side exception.

    `persistent=True` (default) keeps poisoning a step every time it is
    replayed — the shape that escalates the ladder and, after a rollback,
    proves the marked-step skip; `persistent=False` poisons each index
    once (a transient cosmic-ray batch the skip rung absorbs)."""

    TARGETS = {"loss": POISON_LOSS, "grad": POISON_GRAD}

    def __init__(self, fail_steps: Sequence[int] = (), *,
                 seed: Optional[int] = None, horizon: Optional[int] = None,
                 n_faults: int = 1, target: str = "loss",
                 persistent: bool = True):
        if target not in self.TARGETS:
            raise ValueError(f"target must be one of {sorted(self.TARGETS)}, "
                             f"got {target!r}")
        steps = set(int(s) for s in fail_steps)
        if seed is not None:
            if not horizon:
                raise ValueError("seeded injection needs `horizon` (the "
                                 "step range to draw fail steps from)")
            rs = np.random.RandomState(seed)
            draw = rs.choice(np.arange(1, horizon),
                             size=min(n_faults, horizon - 1), replace=False)
            steps |= {int(s) for s in draw}
        self.fail_steps: Set[int] = steps
        self.target = target
        self.persistent = persistent
        self.fired: list = []

    def on_step(self, step: int) -> None:
        """No host-side fault — poisoning happens on device."""

    def poison_code(self, step: int) -> int:
        if step in self.fail_steps and (self.persistent
                                        or step not in self.fired):
            self.fired.append(step)
            return self.TARGETS[self.target]
        return POISON_NONE


class BitFlipCheckpointFault:
    """`post_commit=` hook for AsyncCheckpointer: xor seeded byte(s) of
    `file` inside the `fail_on_save`-th COMMITTED checkpoint dir — silent
    bit-rot the npz zip layer or the per-leaf CRC32C must catch on
    restore.  Local paths only (the test fixture's scope).

    Chunked (v2) layout: a `file` of `"<tree>.npz"` that does not exist
    as a literal file resolves to ONE chunk file of that tree — index
    `chunk` (default 0) into the sorted chunk list.  The corruption is a
    single flipped chunk; the per-chunk CRC must name exactly it and the
    restore fallback chain must walk back to the previous good save."""

    def __init__(self, fail_on_save: int = 1, file: str = "params.npz", *,
                 seed: int = 0, n_bytes: int = 1, n_failures: int = 1,
                 chunk: int = 0):
        self.fail_on_save = int(fail_on_save)
        self.file = file
        self.seed = int(seed)
        self.n_bytes = max(1, int(n_bytes))
        self.n_failures = int(n_failures)
        self.chunk = int(chunk)
        self.saves_seen = 0
        self.fired: list = []

    def _resolve(self, ckpt_dir: str):
        import os

        path = os.path.join(ckpt_dir, self.file)
        if os.path.isfile(path):
            return path
        tree = self.file[:-len(".npz")] \
            if self.file.endswith(".npz") else self.file
        tdir = os.path.join(ckpt_dir, tree)
        if os.path.isdir(tdir):
            chunks = sorted(f for f in os.listdir(tdir)
                            if f.endswith(".npy"))
            if chunks:
                return os.path.join(tdir, chunks[self.chunk % len(chunks)])
        return None

    def __call__(self, ckpt_dir: str) -> None:
        import os

        self.saves_seen += 1
        if self.saves_seen < self.fail_on_save \
                or len(self.fired) >= self.n_failures:
            return
        path = self._resolve(ckpt_dir)
        if path is None:
            return
        size = os.path.getsize(path)
        if size == 0:
            return
        rs = np.random.RandomState(self.seed + self.saves_seen)
        offsets = rs.randint(0, size, size=self.n_bytes)
        with open(path, "r+b") as fh:
            for off in offsets:
                fh.seek(int(off))
                b = fh.read(1)
                fh.seek(int(off))
                fh.write(bytes([b[0] ^ 0x80]))
        self.fired.append(ckpt_dir)


class ReplicaKillFault:
    """Fleet chaos hook: SIGKILL-analog drop of one serving replica
    mid-burst.

    Attach with `FleetRouter.set_chaos(fault)`: `on_dispatch(n, router)`
    fires after every dispatch decision, and on the `at_dispatch`-th one
    the fault calls `router.kill_replica(name)` — the replica's in-flight
    requests fail with `ReplicaDead`, requeue onto their tenant queues,
    and redispatch to survivors.  The invariant under test: zero
    ACCEPTED requests silently dropped (a loud deadline rejection is
    allowed; a hung future is not).

    Deterministic like every fixture here: dispatch-count indexed, no
    wall clock, `fired` records what was killed for assertions.
    `n_kills` > 1 re-arms every `at_dispatch` dispatches after the
    previous kill (a rolling failure, bounded so survivors remain).

    Generation fleets can aim the kill INSIDE a request instead of at
    the dispatch stream: `at_decode_step=n` (or `at_prefill_chunk=n`)
    kills after the bound engine's n-th decode step (prefill-chunk
    fold) — the mid-stream death the failover layer exists for.  Wire
    it with `bind_engine(engine, router, replica_name)`; the engine's
    step hook fires `on_engine_step` from the victim's own scheduler
    thread at a settle-safe boundary (`kill_replica` only marks DEAD
    and spawns a reaper, so killing from that thread cannot
    deadlock)."""

    def __init__(self, at_dispatch: Optional[int] = None, *,
                 name: Optional[str] = None, n_kills: int = 1,
                 at_decode_step: Optional[int] = None,
                 at_prefill_chunk: Optional[int] = None):
        if at_dispatch is None and at_decode_step is None \
                and at_prefill_chunk is None:
            at_dispatch = 1
        if at_dispatch is not None and at_dispatch < 1:
            raise ValueError(f"at_dispatch must be >= 1, got {at_dispatch}")
        if at_decode_step is not None and at_decode_step < 1:
            raise ValueError(
                f"at_decode_step must be >= 1, got {at_decode_step}")
        if at_prefill_chunk is not None and at_prefill_chunk < 1:
            raise ValueError(
                f"at_prefill_chunk must be >= 1, got {at_prefill_chunk}")
        self.at_dispatch = int(at_dispatch) if at_dispatch is not None \
            else None
        self.at_decode_step = int(at_decode_step) \
            if at_decode_step is not None else None
        self.at_prefill_chunk = int(at_prefill_chunk) \
            if at_prefill_chunk is not None else None
        self.name = name
        self.n_kills = int(n_kills)
        self.fired: list = []
        self._next_at = self.at_dispatch
        self._router = None

    def on_step(self, step: int) -> None:
        """No-op: this fault rides the fleet dispatch stream, not the
        trainer step stream (compose() compatibility)."""

    def on_dispatch(self, n_dispatched: int, router) -> None:
        if self.at_dispatch is None:
            return  # engine-step targeted: on_engine_step pulls the trigger
        if len(self.fired) >= self.n_kills or n_dispatched < self._next_at:
            return
        if router.n_replicas() <= 1:
            return  # never kill the last replica — that is an outage, not chaos
        killed = router.kill_replica(self.name)
        if killed is not None:
            self.fired.append((n_dispatched, killed))
            self._next_at = n_dispatched + self.at_dispatch

    def bind_engine(self, engine, router, replica_name: str) -> None:
        """Arm the engine-indexed triggers on one generation engine:
        kill `replica_name` off `router` after the engine's
        `at_decode_step`-th decode step or `at_prefill_chunk`-th chunk
        fold (whichever is configured; counts are cumulative per
        engine).  The victim must be the engine's OWN replica — the
        point is mid-stream death of in-flight work."""
        self._router = router
        if self.name is None:
            self.name = replica_name
        engine.set_step_hook(self.on_engine_step)

    def on_engine_step(self, kind: str, count: int) -> None:
        if len(self.fired) >= self.n_kills:
            return
        at = self.at_decode_step if kind == "decode" \
            else self.at_prefill_chunk
        if at is None or count < at:
            return
        router = self._router
        if router is None or router.n_replicas() <= 1:
            return
        killed = router.kill_replica(self.name)
        if killed is not None:
            self.fired.append((f"{kind}:{count}", killed))


def compose(*hooks) -> "_Composed":
    """One chaos hook fanning out to several injectors, in order."""
    return _Composed(hooks)


class _Composed:
    def __init__(self, hooks: Iterable):
        self.hooks = list(hooks)

    def on_step(self, step: int) -> None:
        for h in self.hooks:
            h.on_step(step)

    def on_dispatch(self, n_dispatched: int, router) -> None:
        for h in self.hooks:
            fn = getattr(h, "on_dispatch", None)
            if fn is not None:
                fn(n_dispatched, router)

    def on_engine_step(self, kind: str, count: int) -> None:
        for h in self.hooks:
            fn = getattr(h, "on_engine_step", None)
            if fn is not None:
                fn(kind, count)

    def poison_code(self, step: int) -> int:
        """Fan in: first non-zero poison wins (composing two NaNInjectors
        on the same step is a fixture bug, not a real scenario)."""
        for h in self.hooks:
            fn = getattr(h, "poison_code", None)
            if fn is not None:
                code = fn(step)
                if code:
                    return code
        return POISON_NONE
