"""bigdl_tpu.resilience — production fault tolerance for the trainer.

Reference: the BigDL production story is trigger-driven synchronous saves
(optim/AbstractOptimizer.scala:202-221) plus an unbounded driver-side
retry (optim/DistriOptimizer.scala:855-935).  On a preemptible TPU pool
that design loses work twice over: every save stalls the dispatch head
for the full host write, and an eviction between triggers replays
everything since the last one.

Three cooperating parts close the gap:

  * `AsyncCheckpointer` (async_ckpt.py): the step loop pays only an
    on-device snapshot; transfer + atomic commit (`tmp.<step>` -> fsync ->
    rename, meta.json last) run in a bounded writer thread, with
    `keep_last`/`keep_every` retention and stale-staging-dir GC.
  * `PreemptionGuard` (preemption.py): SIGTERM/SIGINT or a preempt-file
    poll become a cooperative flag; the trainer writes one final
    synchronous checkpoint at the exact current step, drains feed+writer,
    drops a `PREEMPTED.json` marker and raises `Preempted`.
  * `chaos` (chaos.py): deterministic, seeded fault injectors (step
    exceptions, mid-file checkpoint write failures, simulated preemption,
    device-side NaN poisoning, post-commit checkpoint bit flips) so every
    recovery path above — and the bigdl_tpu.health watchdog ladder — has
    a test that actually kills training.

The `Optimizer` consumes all three: `set_checkpoint(..., async_save=,
keep_last=, keep_every=)`, `set_preemption()`, `set_fault_tolerance(
max_restarts=, backoff_base_s=)` (bounded exponential-backoff restarts
replacing the one-shot retry), and `set_chaos(hook)`.
"""

from bigdl_tpu.resilience.async_ckpt import (
    AsyncCheckpointer,
    CheckpointWriteError,
    apply_retention,
    committed_steps,
    default_layout,
)
from bigdl_tpu.resilience.chaos import (
    BitFlipCheckpointFault,
    ChaosStepFault,
    CheckpointWriteFault,
    NaNInjector,
    ReplicaKillFault,
    SimulatedPreemption,
    StepFaultInjector,
    compose,
)
from bigdl_tpu.resilience.preemption import (
    Preempted,
    PreemptionGuard,
    clear_marker,
    read_marker,
    write_marker,
)

__all__ = [
    "AsyncCheckpointer",
    "BitFlipCheckpointFault",
    "ChaosStepFault",
    "NaNInjector",
    "CheckpointWriteError",
    "CheckpointWriteFault",
    "Preempted",
    "PreemptionGuard",
    "ReplicaKillFault",
    "SimulatedPreemption",
    "StepFaultInjector",
    "apply_retention",
    "clear_marker",
    "committed_steps",
    "compose",
    "default_layout",
    "read_marker",
    "write_marker",
]
