"""PreemptionGuard — turn SIGTERM/SIGINT (or a preempt file) into a clean,
resumable exit.

Preemptible TPU pools deliver a grace window between the eviction notice
(SIGTERM) and the kill; the reference framework has nothing in this
window — the Spark job dies and driver-side retry replays from the last
trigger-driven save, losing everything since.  The guard converts the
signal into a cooperative flag the step loop polls once per batch: the
trainer then writes ONE final synchronous checkpoint at the exact current
step, drains the DeviceFeed worker and async writer, drops a resumable
marker, and raises `Preempted` — the next run restores to the step the
signal arrived at, not the last periodic trigger.

For tests and external orchestrators there is a file-based channel:
touching the path in `BIGDL_TPU_PREEMPT_FILE` (polled at most every
`poll_interval_s`, so the per-step cost is a monotonic-clock read) is
equivalent to the signal.  `chaos.SimulatedPreemption` triggers the guard
at a deterministic step index with no process machinery at all.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
from typing import Dict, Optional, Sequence

from bigdl_tpu.utils.checkpoint import _exists, _join, _open

logger = logging.getLogger("bigdl_tpu.resilience")

MARKER_NAME = "PREEMPTED.json"


class Preempted(RuntimeError):
    """Training stopped cooperatively on a preemption notice.

    Deliberately NOT retried by the optimizer's restart loop — the host is
    going away; the point is the committed final checkpoint + marker."""

    def __init__(self, reason: str, step: Optional[int] = None,
                 checkpoint: Optional[str] = None):
        super().__init__(
            f"preempted ({reason}) at step {step}; "
            f"final checkpoint: {checkpoint or 'none'}")
        self.reason = reason
        self.step = step
        self.checkpoint = checkpoint


class PreemptionGuard:
    """Cooperative preemption flag fed by signals, a poll file, or tests.

    Parameters
    ----------
    signals : signal numbers to trap (default SIGTERM+SIGINT).  Handlers
        install only in the main thread (CPython restriction) — elsewhere
        the guard still works through the file/trigger channels.
    preempt_file : path whose existence requests preemption; defaults to
        `$BIGDL_TPU_PREEMPT_FILE`.
    poll_interval_s : minimum spacing between file-existence checks.
    """

    def __init__(self, signals: Optional[Sequence[int]] = None,
                 preempt_file: Optional[str] = None,
                 poll_interval_s: float = 0.2):
        self.signals = tuple(signals) if signals is not None \
            else (signal.SIGTERM, signal.SIGINT)
        self.preempt_file = preempt_file \
            or os.environ.get("BIGDL_TPU_PREEMPT_FILE")
        self.poll_interval_s = float(poll_interval_s)
        self._flag = threading.Event()
        self._reason: Optional[str] = None
        self._saved: Dict[int, object] = {}
        self._next_poll = 0.0

    # ------------------------------------------------------------------

    def install(self) -> "PreemptionGuard":
        if threading.current_thread() is not threading.main_thread():
            logger.warning("PreemptionGuard: not the main thread, signal "
                           "handlers not installed (file/trigger channels "
                           "still active)")
            return self
        for signum in self.signals:
            self._saved[signum] = signal.signal(signum, self._on_signal)
        return self

    def uninstall(self) -> None:
        for signum, old in self._saved.items():
            try:
                signal.signal(signum, old)
            except (ValueError, TypeError):  # pragma: no cover - teardown
                pass
        self._saved.clear()

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def _on_signal(self, signum, frame) -> None:
        # no exception from the handler: the loop exits at a batch
        # boundary where params/opt_state are consistent and saveable
        self.trigger(f"signal {signal.Signals(signum).name}")

    # ------------------------------------------------------------------

    def trigger(self, reason: str = "manual") -> None:
        """Request preemption (idempotent; first reason wins)."""
        if not self._flag.is_set():
            self._reason = reason
            self._flag.set()
            logger.warning("preemption requested: %s", reason)
            # SIGTERM postmortem: the grace window is the last chance to
            # capture what the run looked like when the eviction landed
            from bigdl_tpu import obs as _obs

            _obs.flight_notify("preemption", cause=reason)

    def requested(self) -> bool:
        """Polled once per batch by the trainer: flag check + rate-limited
        preempt-file poll."""
        if self._flag.is_set():
            return True
        if self.preempt_file:
            now = time.monotonic()
            if now >= self._next_poll:
                self._next_poll = now + self.poll_interval_s
                if os.path.exists(self.preempt_file):
                    self.trigger(f"preempt file {self.preempt_file}")
        return self._flag.is_set()

    @property
    def reason(self) -> str:
        return self._reason or "unknown"


# ----------------------------------------------------------------------
# resumable marker (written next to the checkpoints)
# ----------------------------------------------------------------------

def write_marker(ckpt_path: str, *, step: int, epoch: int,
                 checkpoint: Optional[str], reason: str,
                 health: Optional[Dict] = None) -> str:
    """Drop `PREEMPTED.json` under the checkpoint root: orchestrators (and
    humans) can tell an intentional preemption exit from a crash, and know
    exactly which checkpoint resumes it.  `health` carries the divergence
    watchdog's verdict at preemption time so an orchestrator can tell a
    clean eviction from one that interrupted an unhealthy run."""
    marker = _join(ckpt_path, MARKER_NAME)
    payload = {"step": int(step), "epoch": int(epoch),
               "checkpoint": checkpoint, "reason": reason,
               "resumable": checkpoint is not None}
    if health is not None:
        payload["health"] = health
    with _open(marker, "w") as fh:
        json.dump(payload, fh, indent=2)
    return marker


def read_marker(ckpt_path: str) -> Optional[Dict]:
    """The preemption marker's contents, or None."""
    marker = _join(ckpt_path, MARKER_NAME)
    if not _exists(marker):
        return None
    with _open(marker, "r") as fh:
        return json.load(fh)


def clear_marker(ckpt_path: str) -> None:
    """Remove the marker (called when a resumed run finishes cleanly)."""
    marker = _join(ckpt_path, MARKER_NAME)
    if "://" not in marker:
        if os.path.exists(marker):
            os.remove(marker)
    else:  # pragma: no cover - remote fs
        from bigdl_tpu.utils.checkpoint import _fs_for

        fs = _fs_for(marker)
        if fs.exists(marker):
            fs.rm(marker)
