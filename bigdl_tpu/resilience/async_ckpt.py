"""AsyncCheckpointer — checkpoint IO off the step loop, committed atomically.

Reference: optim/AbstractOptimizer.scala:202-221 saves synchronously inside
the iteration callback — the driver (and with it the dispatch head) stalls
for the full serialize+write on every trigger.  Here the step loop pays
only an on-device snapshot (a handful of async copy dispatches); the
device->host transfer and the file writes run in ONE bounded background
writer thread, overlapping in-flight device compute exactly like the
DeviceFeed overlaps H2D staging on the input side.

Commit protocol (local paths): every file lands in a `tmp.<step>` staging
dir, each file is fsync'd, `meta.json` is written LAST, then the staging
dir is atomically renamed to `ckpt_<step>` and the parent dir fsync'd.  A
crash at ANY point leaves either a committed checkpoint or a `tmp.*` /
meta-less dir that `latest_checkpoint(gc_partial=True)` reclaims on resume
— never a half-checkpoint that loads.  Remote (fsspec) paths have no
atomic rename, so they write in place with meta.json as the last-write
commit marker (the scheme `latest_checkpoint` already trusts).

Retention: `keep_last=N` keeps the N newest committed checkpoints;
`keep_every=K` additionally pins every step that is a multiple of K
(the "hourly keeper" policy).  GC also reclaims stale `tmp.*` staging
dirs that no in-flight job owns.

Failure policy: a failed write is logged, counted and remembered
(`last_error`), but does NOT kill training — losing one checkpoint is
recoverable, killing the run is not.  `wait()` drains the queue so
end-of-training and pre-restore paths observe every commit.
"""

from __future__ import annotations

import io
import json
import logging
import os
import queue
import re
import shutil
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import obs as _obs
from bigdl_tpu.health import integrity as _integrity
from bigdl_tpu.utils import ckpt_chunked as _ck
from bigdl_tpu.utils.checkpoint import (
    CHUNKED_SCHEMA_VERSION,
    SCHEMA_VERSION,
    _exists,
    _flatten,
    _is_remote,
    _isdir,
    _join,
    _listdir,
    _makedirs,
    _open,
    _rmtree,
)

logger = logging.getLogger("bigdl_tpu.resilience")

_STOP = object()
_LAYOUTS = ("chunked", "monolithic")


def default_layout() -> str:
    """Writer layout: `chunked` (v2 — per-shard chunk files + mesh
    manifest, elastic restore) unless `BIGDL_TPU_CKPT_LAYOUT=monolithic`
    pins the v1 single-.npz-per-tree format."""
    v = os.environ.get("BIGDL_TPU_CKPT_LAYOUT", "chunked").strip().lower()
    if v not in _LAYOUTS:
        raise ValueError(
            f"BIGDL_TPU_CKPT_LAYOUT must be one of {_LAYOUTS}, got {v!r}")
    return v


class CheckpointWriteError(RuntimeError):
    """A checkpoint file write failed (possibly mid-file)."""


class _Job(NamedTuple):
    step: int
    trees: Tuple[Any, Any, Any]  # device snapshots: params, model_state, opt_state
    driver_state: Dict[str, Any]


def _snapshot(tree: Any) -> Any:
    """On-device copy of every jax leaf — the only cost the step loop pays.

    The jitted step DONATES its buffers, so the writer cannot hold the live
    params: the copies are enqueued before the next step's dispatch and the
    in-order device executes them first, giving the writer a stable buffer
    to transfer at its leisure.  Host leaves are copied too (optimizer
    slots mutated in place must not race the writer)."""
    if tree is None:
        return None
    return jax.tree_util.tree_map(
        lambda l: jnp.copy(l) if isinstance(l, jax.Array)
        else (np.array(l) if isinstance(l, np.ndarray) else l), tree)


def committed_steps(path: str) -> List[int]:
    """Steps of committed checkpoints (dirs with a meta.json) under path."""
    if not _isdir(path):
        return []
    steps = []
    for name in _listdir(path):
        m = re.fullmatch(r"ckpt_(\d+)", name)
        if m and _exists(_join(path, name, "meta.json")):
            steps.append(int(m.group(1)))
    return sorted(steps)


def apply_retention(path: str, keep_last: Optional[int],
                    keep_every: Optional[int],
                    protect: Tuple[int, ...] = ()) -> List[str]:
    """Delete committed checkpoints outside the retention policy, and stale
    `tmp.*` staging dirs not owned by an in-flight (`protect`ed) job.
    Returns the removed paths.  keep_last=None keeps everything."""
    removed: List[str] = []
    if not _isdir(path):
        return removed
    steps = committed_steps(path)
    keep = set(steps if keep_last is None else steps[-max(0, keep_last):])
    if keep_every:
        keep |= {s for s in steps if s % keep_every == 0}
    keep |= set(protect)
    for s in steps:
        if s not in keep:
            d = _join(path, f"ckpt_{s}")
            _rmtree(d)
            removed.append(d)
    for name in _listdir(path):
        m = re.fullmatch(r"tmp\.(\d+)", name)
        if m and int(m.group(1)) not in protect:
            d = _join(path, name)
            _rmtree(d)
            removed.append(d)
    if removed:
        logger.info("checkpoint retention: removed %d dir(s): %s",
                    len(removed), [os.path.basename(r) for r in removed])
    return removed


class AsyncCheckpointer:
    """Bounded background checkpoint writer with atomic commit + retention.

    Parameters
    ----------
    path : checkpoint root (the trainer's `set_checkpoint` path)
    keep_last / keep_every : retention policy (see module docstring)
    queue_depth : max queued snapshots; a full queue backpressures
        `save_async` (bounding host memory at queue_depth+1 snapshots)
    fault : chaos hook `f(relname) -> bool`; True makes the write of that
        file fail mid-file (tests of the partial-checkpoint recovery path)
    post_commit : chaos hook `f(ckpt_dir)` invoked AFTER the atomic rename
        commits a checkpoint — the BitFlipCheckpointFault attachment point
        (bit-rot happens to committed files, not in-flight writes)
    layout : `"chunked"` (default, from `BIGDL_TPU_CKPT_LAYOUT`) writes
        the v2 sharded layout — one chunk file per distinct shard of each
        leaf, device->host transfer bounded by ONE chunk at a time, mesh
        descriptor + per-chunk CRC manifest in meta.json, restorable onto
        a different topology.  `"monolithic"` keeps the v1 per-tree .npz.
        `peak_host_bytes` records the last save's high-water host buffer
        (max chunk vs full gathered tree) for the bench to assert on.
    """

    def __init__(self, path: str, *, keep_last: Optional[int] = None,
                 keep_every: Optional[int] = None, queue_depth: int = 2,
                 fault: Optional[Callable[[str], bool]] = None,
                 post_commit: Optional[Callable[[str], None]] = None,
                 layout: Optional[str] = None,
                 name: str = "AsyncCkptWriter"):
        if queue_depth < 1:
            raise ValueError(f"queue_depth must be >= 1, got {queue_depth}")
        if layout is None:
            layout = default_layout()
        if layout not in _LAYOUTS:
            raise ValueError(
                f"layout must be one of {_LAYOUTS}, got {layout!r}")
        self.path = str(path)
        self.layout = layout
        self.peak_host_bytes = 0
        self.keep_last = keep_last
        self.keep_every = keep_every
        self._fault = fault
        self._post_commit = post_commit
        self._name = name
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._inflight: set = set()
        self.committed: List[int] = []
        self.failed: List[int] = []
        self.last_error: Optional[BaseException] = None
        self._closed = False

    # ------------------------------------------------------------------
    # producer side (the step loop)
    # ------------------------------------------------------------------

    def save_async(self, step: int, params: Any, model_state: Any = None,
                   opt_state: Any = None,
                   driver_state: Optional[Dict] = None) -> None:
        """Snapshot on device and enqueue; returns as soon as the copies
        are dispatched (the step loop's entire checkpoint cost)."""
        if self._closed:
            raise RuntimeError(f"{self._name} is closed")
        job = _Job(int(step),
                   (_snapshot(params), _snapshot(model_state),
                    _snapshot(opt_state)),
                   dict(driver_state or {}))
        with self._lock:
            self._inflight.add(job.step)
        self._ensure_thread()
        # bounded: backpressure instead of host-mem growth.  The timeout
        # loop keeps the wait interruptible — a writer that died outside
        # its try (interpreter teardown, untrappable kill) gets restarted
        # instead of leaving the step loop blocked on a full queue forever
        while True:
            try:
                self._q.put(job, timeout=0.1)
                return
            except queue.Full:
                self._ensure_thread()

    def save_sync(self, step: int, params: Any, model_state: Any = None,
                  opt_state: Any = None,
                  driver_state: Optional[Dict] = None) -> str:
        """Drain the queue, then write THIS checkpoint inline (the
        preemption path's final save, and the `async_save=False` mode).
        Raises CheckpointWriteError on failure — a sync save that is lost
        silently defeats its purpose."""
        self.wait()
        job = _Job(int(step),
                   (_snapshot(params), _snapshot(model_state),
                    _snapshot(opt_state)),
                   dict(driver_state or {}))
        with self._lock:
            self._inflight.add(job.step)
        try:
            d = self._write(job)
        except BaseException as e:
            with self._lock:
                self.failed.append(job.step)
                self.last_error = e
            _obs.registry().inc("ckpt/failed")
            raise CheckpointWriteError(
                f"sync checkpoint at step {job.step} failed") from e
        finally:
            with self._lock:
                self._inflight.discard(job.step)
        _obs.registry().inc("ckpt/committed")
        _obs.instant("ckpt.commit", cat="ckpt", step=job.step)
        with self._lock:
            self.committed.append(job.step)
            protect = tuple(self._inflight)
        apply_retention(self.path, self.keep_last, self.keep_every,
                        protect=protect)
        return d

    def wait(self, stall_check: Optional[Callable[[], None]] = None) -> None:
        """Barrier: every queued snapshot is committed (or failed+logged)
        when this returns.  End-of-training and every restore path call
        this so `latest_checkpoint` sees the full commit history.

        `stall_check` (the hang watchdog's `check`) is called each poll so
        a wedged writer raises `StalledStep` into the driver instead of
        blocking it forever."""
        self._drain(stall_check)

    def _drain(self, stall_check: Optional[Callable[[], None]] = None) -> None:
        """Bounded-step equivalent of `Queue.join()`: waits on the same
        all_tasks_done condition, but wakes every 100 ms to restart a
        writer that died outside its try block — a bare join() there
        deadlocks the driver with jobs stranded in the queue."""
        q = self._q
        with q.all_tasks_done:
            while q.unfinished_tasks:
                if stall_check is not None:
                    stall_check()
                if not self._closed and (self._thread is None
                                         or not self._thread.is_alive()):
                    self._ensure_thread()
                q.all_tasks_done.wait(timeout=0.1)
                if self._closed and (self._thread is None
                                     or not self._thread.is_alive()):
                    break  # closing and the writer is gone: nothing will drain

    def close(self) -> None:
        """Drain, stop and join the writer thread.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            while True:
                try:
                    self._q.put(_STOP, timeout=0.1)
                    break
                except queue.Full:
                    if not self._thread.is_alive():
                        break  # dead writer, full queue: nothing to stop
            self._drain()
            self._thread.join(timeout=30.0)
            if self._thread.is_alive():  # pragma: no cover - defensive
                raise RuntimeError(f"{self._name} did not stop")
            self._thread = None

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------
    # writer thread
    # ------------------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            # daemon: a crashed driver must not hang interpreter exit; the
            # conftest leak guard still flags one alive past a test
            self._thread = threading.Thread(target=self._run,
                                            name=self._name, daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while True:
            try:
                # bounded get: idle wake-ups are cheap and keep the worker
                # loop responsive to interpreter teardown (daemon threads
                # stuck in an unbounded get can't be reasoned about)
                job = self._q.get(timeout=0.5)
            except queue.Empty:
                continue
            if job is _STOP:
                self._q.task_done()
                return
            tr = _obs.tracer()
            try:
                if tr is not None:
                    with tr.span("ckpt.write", cat="ckpt", step=job.step):
                        d = self._write(job)
                    tr.instant("ckpt.commit", cat="ckpt", step=job.step)
                else:
                    d = self._write(job)
                with self._lock:
                    self.committed.append(job.step)
                    protect = tuple(self._inflight)
                _obs.registry().inc("ckpt/committed")
                logger.info("checkpoint step %d committed to %s",
                            job.step, d, extra={"step": job.step})
                apply_retention(self.path, self.keep_last, self.keep_every,
                                protect=protect)
            except BaseException as e:
                # a lost checkpoint is recoverable; a killed run is not —
                # the partial staging dir stays on disk (cleanup code after
                # an IO error is untrustworthy) and resume-time GC reclaims
                with self._lock:
                    self.failed.append(job.step)
                    self.last_error = e
                _obs.registry().inc("ckpt/failed")
                logger.exception("async checkpoint at step %d failed "
                                 "(training continues)", job.step)
            finally:
                with self._lock:
                    self._inflight.discard(job.step)
                self._q.task_done()

    # ------------------------------------------------------------------
    # atomic commit
    # ------------------------------------------------------------------

    def _write(self, job: _Job) -> str:
        if self.layout == "chunked":
            return self._write_chunked(job)
        flats = {}
        for name, tree in zip(("params", "model_state", "opt_state"),
                              job.trees):
            if tree is not None:
                flats[name + ".npz"] = _flatten(tree)  # device->host here
        self.peak_host_bytes = sum(a.nbytes for f in flats.values()
                                   for a in f.values())
        _obs.registry().set_gauge("ckpt/peak_host_bytes",
                                  float(self.peak_host_bytes))
        meta = {"schema_version": SCHEMA_VERSION, "step": job.step,
                "driver_state": job.driver_state,
                # per-leaf CRC32C computed HERE, in the writer thread —
                # restore verifies against these (health/integrity.py);
                # the step loop never pays for the checksum pass
                "integrity": {n: _integrity.tree_crcs(f)
                              for n, f in flats.items()}}
        final = _join(self.path, f"ckpt_{job.step}")
        if _is_remote(self.path):
            return self._write_remote(final, flats, meta)
        return self._write_local(final, flats, meta, job.step)

    def _write_chunked(self, job: _Job) -> str:
        """v2 save: same tmp -> fsync -> rename commit protocol, but the
        payload is one chunk file per distinct shard of each leaf and the
        device->host transfer happens inside `write_tree` one chunk at a
        time — the full gathered tree NEVER exists on host."""
        note = getattr(self._fault, "note_save", None)
        if note is not None:
            note()  # a chunked save is many file writes; count saves here
        remote = _is_remote(self.path)
        final = _join(self.path, f"ckpt_{job.step}")
        if remote:
            dest = final
            _makedirs(dest)
        else:
            dest = os.path.join(self.path, f"tmp.{job.step}")
            if os.path.isdir(dest):
                shutil.rmtree(dest)
            os.makedirs(dest)

        def emit(relname: str, payload) -> None:
            if remote:
                if self._fault is not None and self._fault(relname):
                    raise CheckpointWriteError(
                        f"chaos: fault writing {relname}")
                with _open(_join(dest, relname), "wb") as fh:
                    fh.write(payload)
            else:
                p = os.path.join(dest, relname)
                os.makedirs(os.path.dirname(p), exist_ok=True)
                self._write_file(p, payload, relname)

        peak = [0]
        manifest = {}
        for name, tree in zip(_ck.TREE_NAMES, job.trees):
            if tree is not None:
                manifest[name] = _ck.write_tree(
                    name, tree, emit,
                    note_host=lambda nb: peak.__setitem__(
                        0, max(peak[0], nb)))
        self.peak_host_bytes = peak[0]
        _obs.registry().set_gauge("ckpt/peak_host_bytes", float(peak[0]))
        meta = {"schema_version": CHUNKED_SCHEMA_VERSION, "step": job.step,
                "driver_state": job.driver_state,
                # the mesh the save ran under — restore onto a DIFFERENT
                # topology reads this to know the source layout
                "mesh": _ck.mesh_descriptor(job.trees),
                # per-leaf chunk grid + per-chunk CRC32C (writer thread;
                # the step loop never pays for the checksum pass)
                "manifest": manifest}
        payload = json.dumps(meta, indent=2).encode()
        if remote:
            # no atomic rename on object stores: meta.json is the
            # last-write commit marker, same as the v1 remote path
            with _open(_join(dest, "meta.json"), "wb") as fh:
                fh.write(payload)
        else:
            # meta.json LAST, then atomic rename + parent fsync
            self._write_file(os.path.join(dest, "meta.json"), payload,
                             "meta.json")
            if os.path.isdir(final):
                shutil.rmtree(final)  # re-save of the same step
            os.rename(dest, final)
            dfd = os.open(self.path, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        if self._post_commit is not None:
            self._post_commit(final)  # chaos: bit-rot a COMMITTED chunk
        return final

    def _write_local(self, final: str, flats: Dict[str, Dict],
                     meta: Dict, step: int) -> str:
        tmp = os.path.join(self.path, f"tmp.{step}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        for relname, flat in flats.items():
            buf = io.BytesIO()
            np.savez(buf, **flat)
            self._write_file(os.path.join(tmp, relname), buf.getbuffer(),
                             relname)
        # meta.json LAST: its presence is the per-dir commit marker
        self._write_file(os.path.join(tmp, "meta.json"),
                         json.dumps(meta, indent=2).encode(), "meta.json")
        if os.path.isdir(final):
            shutil.rmtree(final)  # re-save of the same step
        os.rename(tmp, final)
        # fsync the parent so the rename itself survives a power cut
        dfd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        if self._post_commit is not None:
            self._post_commit(final)  # chaos: bit-rot a COMMITTED shard
        return final

    def _write_remote(self, final: str, flats: Dict[str, Dict],
                      meta: Dict) -> str:
        _makedirs(final)
        for relname, flat in flats.items():
            if self._fault is not None and self._fault(relname):
                raise CheckpointWriteError(f"chaos: fault writing {relname}")
            buf = io.BytesIO()
            np.savez(buf, **flat)
            with _open(_join(final, relname), "wb") as fh:
                fh.write(buf.getbuffer())
        with _open(_join(final, "meta.json"), "w") as fh:
            json.dump(meta, fh, indent=2)
        if self._post_commit is not None:
            self._post_commit(final)
        return final

    def _write_file(self, path: str, payload, relname: str) -> None:
        """fsync'd local write; the chaos fault leaves the file truncated
        mid-payload (the crash-while-writing shape resume must survive)."""
        fail = self._fault is not None and self._fault(relname)
        with open(path, "wb") as fh:
            if fail:
                fh.write(payload[:max(1, len(payload) // 2)])
                fh.flush()
                raise CheckpointWriteError(
                    f"chaos: injected mid-file failure writing {relname}")
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
