"""Native (C++) runtime components, loaded via ctypes.

The reference's native layer (BigDL-core: MKL kernels, aligned memory,
Crc32c — survey §2.9) maps mostly onto XLA; what legitimately stays native
on TPU is the HOST side: checksummed record IO and a multi-threaded
prefetching loader that keeps the infeed queue full.  Sources live in
`src/`; the shared library is compiled with g++ on first import and cached
next to the sources (no pip/pybind dependency — plain `extern "C"` +
ctypes).

Public surface:
  crc32c(data) / crc32c_masked(data)
  TFRecord reader/writer handles (wrapped by bigdl_tpu.dataset.tfrecord)
  Prefetch loader handles (wrapped by bigdl_tpu.dataset.tfrecord)

`available()` reports whether the library compiled; pure-python fallbacks
in the wrappers keep every feature functional without it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src")
_LIB_PATH = os.path.join(_HERE, "_libbigdl_tpu_native.so")

_lock = threading.Lock()
_lib = None
_tried = False
_build_error: str | None = None


def _sources():
    return sorted(
        os.path.join(_SRC, f) for f in os.listdir(_SRC) if f.endswith(".cc"))


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(os.path.getmtime(s) > lib_mtime for s in _sources())


def _build() -> None:
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-pthread",
           "-o", _LIB_PATH] + _sources()
    subprocess.run(cmd, check=True, capture_output=True, text=True)


def _bind(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.bigdl_crc32c.restype = ctypes.c_uint32
    lib.bigdl_crc32c.argtypes = [u8p, ctypes.c_size_t]
    lib.bigdl_crc32c_extend.restype = ctypes.c_uint32
    lib.bigdl_crc32c_extend.argtypes = [ctypes.c_uint32, u8p, ctypes.c_size_t]
    lib.bigdl_crc32c_masked.restype = ctypes.c_uint32
    lib.bigdl_crc32c_masked.argtypes = [u8p, ctypes.c_size_t]

    lib.bigdl_tfrecord_reader_open.restype = ctypes.c_void_p
    lib.bigdl_tfrecord_reader_open.argtypes = [ctypes.c_char_p]
    lib.bigdl_tfrecord_reader_next.restype = ctypes.c_longlong
    lib.bigdl_tfrecord_reader_next.argtypes = [ctypes.c_void_p,
                                               ctypes.POINTER(u8p)]
    lib.bigdl_tfrecord_reader_close.argtypes = [ctypes.c_void_p]

    lib.bigdl_tfrecord_writer_open.restype = ctypes.c_void_p
    lib.bigdl_tfrecord_writer_open.argtypes = [ctypes.c_char_p]
    lib.bigdl_tfrecord_writer_write.restype = ctypes.c_int
    lib.bigdl_tfrecord_writer_write.argtypes = [ctypes.c_void_p, u8p,
                                                ctypes.c_uint64]
    lib.bigdl_tfrecord_writer_flush.restype = ctypes.c_int
    lib.bigdl_tfrecord_writer_flush.argtypes = [ctypes.c_void_p]
    lib.bigdl_tfrecord_writer_close.argtypes = [ctypes.c_void_p]

    lib.bigdl_prefetch_open.restype = ctypes.c_void_p
    lib.bigdl_prefetch_open.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                        ctypes.c_int, ctypes.c_int, ctypes.c_int]
    lib.bigdl_prefetch_next.restype = ctypes.c_longlong
    lib.bigdl_prefetch_next.argtypes = [ctypes.c_void_p, u8p, ctypes.c_size_t,
                                        ctypes.POINTER(ctypes.c_size_t)]
    lib.bigdl_prefetch_errors.restype = ctypes.c_longlong
    lib.bigdl_prefetch_errors.argtypes = [ctypes.c_void_p]
    lib.bigdl_prefetch_close.argtypes = [ctypes.c_void_p]


def get_lib() -> ctypes.CDLL | None:
    """Build (if needed) and load the native library; None if unavailable."""
    global _lib, _tried, _build_error
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if _needs_build():
                _build()
            lib = ctypes.CDLL(_LIB_PATH)
            _bind(lib)
            _lib = lib
        except (subprocess.CalledProcessError, OSError) as e:
            _build_error = getattr(e, "stderr", None) or str(e)
        return _lib


def available() -> bool:
    return get_lib() is not None


def build_error() -> str | None:
    get_lib()
    return _build_error


def _as_u8p(data: bytes):
    return ctypes.cast(ctypes.c_char_p(data), ctypes.POINTER(ctypes.c_uint8))


def crc32c(data: bytes) -> int:
    lib = get_lib()
    if lib is None:
        return _py_crc32c(data)
    return lib.bigdl_crc32c(_as_u8p(data), len(data))


def crc32c_masked(data: bytes) -> int:
    lib = get_lib()
    if lib is None:
        crc = _py_crc32c(data)
        return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF
    return lib.bigdl_crc32c_masked(_as_u8p(data), len(data))


# Pure-python CRC32C fallback (table-driven)
_PY_TABLE = None


def _py_crc32c(data: bytes) -> int:
    global _PY_TABLE
    if _PY_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ (poly if crc & 1 else 0)
            table.append(crc)
        _PY_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _PY_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF
