// Multi-threaded prefetching record loader.
//
// The reference overlaps input decode with compute via JVM thread pools
// (dataset/image/MTLabeledBGRImgToBatch.scala, utils/ThreadPool.scala); on
// TPU the same overlap must happen on the host so the infeed queue never
// starves the chip.  This loader owns N reader threads, each draining a
// shard-partitioned list of TFRecord files into one bounded ring buffer;
// the Python side pops records (GIL released while blocked).
//
// Concurrency: one mutex + two condvars (not_empty / not_full) around a
// fixed-capacity ring of heap-owned records.  Shutdown is cooperative via
// `stop` + broadcast.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* bigdl_tfrecord_reader_open(const char* path);
long long bigdl_tfrecord_reader_next(void* handle, const uint8_t** out);
void bigdl_tfrecord_reader_close(void* handle);
}

namespace {

struct Record {
  uint8_t* data;
  size_t len;
};

struct Loader {
  std::vector<std::string> files;
  size_t capacity;
  std::vector<std::thread> threads;

  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::vector<Record> ring;
  size_t head = 0, tail = 0, count = 0;
  std::atomic<bool> stop{false};
  std::atomic<int> active_readers{0};
  std::atomic<long long> errors{0};

  bool push(Record rec) {
    std::unique_lock<std::mutex> lk(mu);
    not_full.wait(lk, [&] { return count < capacity || stop.load(); });
    if (stop.load()) {
      free(rec.data);
      return false;
    }
    ring[tail] = rec;
    tail = (tail + 1) % capacity;
    ++count;
    not_empty.notify_one();
    return true;
  }

  void reader_main(size_t start_idx, size_t stride) {
    for (size_t i = start_idx; i < files.size() && !stop.load(); i += stride) {
      void* rd = bigdl_tfrecord_reader_open(files[i].c_str());
      if (!rd) {
        ++errors;
        continue;
      }
      const uint8_t* ptr = nullptr;
      long long len = -2;  // "clean EOF" if stop interrupts before first read
      while (!stop.load() && (len = bigdl_tfrecord_reader_next(rd, &ptr)) >= 0) {
        Record rec{static_cast<uint8_t*>(malloc(len ? len : 1)),
                   static_cast<size_t>(len)};
        if (len) memcpy(rec.data, ptr, len);
        if (!push(rec)) break;
      }
      if (len == -1) ++errors;
      bigdl_tfrecord_reader_close(rd);
    }
    if (--active_readers == 0) {
      std::lock_guard<std::mutex> lk(mu);
      not_empty.notify_all();  // wake consumers: stream is done
    }
  }
};

}  // namespace

extern "C" {

void* bigdl_prefetch_open(const char** paths, int n_paths, int n_threads,
                          int capacity) {
  Loader* L = new Loader;
  for (int i = 0; i < n_paths; ++i) L->files.emplace_back(paths[i]);
  L->capacity = capacity > 0 ? capacity : 64;
  L->ring.resize(L->capacity);
  if (n_threads <= 0) n_threads = 2;
  if (n_threads > n_paths && n_paths > 0) n_threads = n_paths;
  L->active_readers = n_threads;
  for (int t = 0; t < n_threads; ++t)
    L->threads.emplace_back(&Loader::reader_main, L, t, n_threads);
  return L;
}

// Pops one record. Returns length (>= 0; empty records are valid), -2 when
// the stream is exhausted, -1 if `buf_cap` is too small (record stays
// queued; call again bigger — required size is written to *needed).
long long bigdl_prefetch_next(void* handle, uint8_t* buf, size_t buf_cap,
                              size_t* needed) {
  Loader* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  L->not_empty.wait(lk, [&] {
    return L->count > 0 || L->active_readers.load() == 0 || L->stop.load();
  });
  if (L->count == 0) return -2;  // drained and all readers done
  Record& rec = L->ring[L->head];
  if (rec.len > buf_cap) {
    if (needed) *needed = rec.len;
    return -1;
  }
  if (rec.len) memcpy(buf, rec.data, rec.len);
  free(rec.data);
  long long len = static_cast<long long>(rec.len);
  L->head = (L->head + 1) % L->capacity;
  --L->count;
  L->not_full.notify_one();
  return len;
}

long long bigdl_prefetch_errors(void* handle) {
  return static_cast<Loader*>(handle)->errors.load();
}

void bigdl_prefetch_close(void* handle) {
  Loader* L = static_cast<Loader*>(handle);
  L->stop.store(true);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->not_full.notify_all();
    L->not_empty.notify_all();
  }
  for (auto& t : L->threads) t.join();
  while (L->count > 0) {
    free(L->ring[L->head].data);
    L->head = (L->head + 1) % L->capacity;
    --L->count;
  }
  delete L;
}

}  // extern "C"
