// CRC32C (Castagnoli) — the checksum TFRecord framing and TensorBoard event
// files require.  Reference keeps a Java port (src/main/java/netty/Crc32c.java)
// for the same purpose; this is the native equivalent feeding both the
// TFRecord reader/writer and the summary-event writer.
//
// Table-driven, 8 tables x 256 entries (slice-by-8): ~1 byte/cycle without
// SSE4.2 dependence, portable across the build images.

#include <cstdint>
#include <cstddef>

namespace {

uint32_t g_tables[8][256];

void init_tables() {
  const uint32_t poly = 0x82f63b78u;  // reflected CRC-32C polynomial
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int k = 0; k < 8; ++k)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    g_tables[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = g_tables[0][i];
    for (int t = 1; t < 8; ++t) {
      crc = g_tables[0][crc & 0xff] ^ (crc >> 8);
      g_tables[t][i] = crc;
    }
  }
}

// Run at .so load time (single-threaded), so concurrent prefetch reader
// threads never race a lazy init.
struct TableInit {
  TableInit() { init_tables(); }
} g_table_init;

}  // namespace

extern "C" {

uint32_t bigdl_crc32c_extend(uint32_t crc, const uint8_t* data, size_t n) {
  crc = ~crc;
  while (n >= 8) {
    crc ^= static_cast<uint32_t>(data[0]) | (static_cast<uint32_t>(data[1]) << 8) |
           (static_cast<uint32_t>(data[2]) << 16) | (static_cast<uint32_t>(data[3]) << 24);
    uint32_t hi = static_cast<uint32_t>(data[4]) | (static_cast<uint32_t>(data[5]) << 8) |
                  (static_cast<uint32_t>(data[6]) << 16) | (static_cast<uint32_t>(data[7]) << 24);
    crc = g_tables[7][crc & 0xff] ^ g_tables[6][(crc >> 8) & 0xff] ^
          g_tables[5][(crc >> 16) & 0xff] ^ g_tables[4][crc >> 24] ^
          g_tables[3][hi & 0xff] ^ g_tables[2][(hi >> 8) & 0xff] ^
          g_tables[1][(hi >> 16) & 0xff] ^ g_tables[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n--) crc = g_tables[0][(crc ^ *data++) & 0xff] ^ (crc >> 8);
  return ~crc;
}

uint32_t bigdl_crc32c(const uint8_t* data, size_t n) {
  return bigdl_crc32c_extend(0, data, n);
}

// TFRecord "masked" crc: rotate right 15 and add a constant.
uint32_t bigdl_crc32c_masked(const uint8_t* data, size_t n) {
  uint32_t crc = bigdl_crc32c_extend(0, data, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

}  // extern "C"
