// TFRecord framing: read/write records with masked-crc32c integrity.
//
// Frame layout (TFRecordWriter wire format):
//   uint64 length | uint32 masked_crc32(length) | bytes data |
//   uint32 masked_crc32(data)
//
// Reference counterpart: utils/tf/TFRecordIterator + TFRecordInputFormat
// (JVM) over netty/Crc32c.java.  Here the reader/writer are native so the
// host input pipeline never pays Python byte-twiddling costs.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {
uint32_t bigdl_crc32c_masked(const uint8_t* data, size_t n);
}

namespace {

struct Reader {
  FILE* f;
  uint8_t* buf;
  size_t cap;
};

struct Writer {
  FILE* f;
};

bool read_exact(FILE* f, void* dst, size_t n) {
  return fread(dst, 1, n, f) == n;
}

}  // namespace

extern "C" {

// ---------------- reader ----------------

void* bigdl_tfrecord_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Reader* r = new Reader{f, static_cast<uint8_t*>(malloc(1 << 16)), 1 << 16};
  return r;
}

// Returns record length (>= 0; empty records are valid), -2 on clean EOF,
// -1 on corruption/short read.  Data pointer (valid until next call) is
// written to *out.
long long bigdl_tfrecord_reader_next(void* handle, const uint8_t** out) {
  Reader* r = static_cast<Reader*>(handle);
  uint8_t header[12];
  size_t got = fread(header, 1, 12, r->f);
  if (got == 0) return -2;  // EOF
  if (got != 12) return -1;
  uint64_t len;
  memcpy(&len, header, 8);
  uint32_t len_crc;
  memcpy(&len_crc, header + 8, 4);
  if (bigdl_crc32c_masked(header, 8) != len_crc) return -1;
  // a crc-valid but absurd length (corruption or forgery — crc32c is not
  // cryptographic) must not overflow the doubling loop or exhaust memory
  const uint64_t kMaxRecord = 1ull << 36;  // 64 GiB
  if (len > kMaxRecord) return -1;
  if (len + 4 > r->cap) {
    size_t want = r->cap;
    while (want < len + 4) want <<= 1;
    uint8_t* grown = static_cast<uint8_t*>(realloc(r->buf, want));
    if (!grown) return -1;
    r->buf = grown;
    r->cap = want;
  }
  if (!read_exact(r->f, r->buf, len + 4)) return -1;
  uint32_t data_crc;
  memcpy(&data_crc, r->buf + len, 4);
  if (bigdl_crc32c_masked(r->buf, len) != data_crc) return -1;
  *out = r->buf;
  return static_cast<long long>(len);
}

void bigdl_tfrecord_reader_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (r) {
    fclose(r->f);
    free(r->buf);
    delete r;
  }
}

// ---------------- writer ----------------

void* bigdl_tfrecord_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  return new Writer{f};
}

int bigdl_tfrecord_writer_write(void* handle, const uint8_t* data, uint64_t len) {
  Writer* w = static_cast<Writer*>(handle);
  uint8_t header[12];
  memcpy(header, &len, 8);
  uint32_t len_crc = bigdl_crc32c_masked(header, 8);
  memcpy(header + 8, &len_crc, 4);
  uint32_t data_crc = bigdl_crc32c_masked(data, len);
  if (fwrite(header, 1, 12, w->f) != 12) return -1;
  if (fwrite(data, 1, len, w->f) != len) return -1;
  if (fwrite(&data_crc, 1, 4, w->f) != 4) return -1;
  return 0;
}

int bigdl_tfrecord_writer_flush(void* handle) {
  return fflush(static_cast<Writer*>(handle)->f);
}

void bigdl_tfrecord_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  if (w) {
    fclose(w->f);
    delete w;
  }
}

}  // extern "C"
