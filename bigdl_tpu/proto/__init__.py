"""Generated-protobuf home.  protoc emits absolute `import x_pb2` lines,
so importing this package puts the directory on sys.path once — every
consumer does `import bigdl_tpu.proto` then `import <schema>_pb2`."""

import os
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
if _DIR not in sys.path:
    sys.path.insert(0, _DIR)
