"""Checkpoint save/load.

Reference: optim/AbstractOptimizer.scala:202-221 (trigger-driven
`model.<iter>` + `optimMethod-<name>.<iter>` files in a timestamped subdir)
and utils/File.scala (local/HDFS/S3).  Resume restores mid-epoch because
counters live in optimizer state (optim/DistriOptimizer.scala:127-137).

Format: a directory per checkpoint containing a schema-versioned
`meta.json` plus the tree payloads in one of two layouts:

  * **v1 (monolithic)** — one `.npz` per pytree (params / model_state /
    opt_state), pytrees flattened to path-keyed arrays ("0/weight",
    "cell/w_ih"); stable across process restarts and inspectable with
    numpy — the same goals as the reference's protobuf ModuleSerializer
    (§2.6), without inventing a binary schema.
  * **v2 (chunked, the default writer layout)** — per-leaf chunk files
    whose boundaries come from the live `NamedSharding`, plus a mesh
    descriptor and per-chunk CRC manifest in meta.json, enabling elastic
    restore onto a different topology.  See `utils/ckpt_chunked.py`.

The reader here accepts BOTH (old monolithic checkpoints stay
restorable) and refuses a directory that mixes the two layouts.

Remote paths: any `scheme://...` path (gs://, s3://, hdfs://, memory://)
routes through fsspec — the analogue of utils/File.scala's hdfs:/s3a:
support.  Plain paths use the local filesystem directly.
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from bigdl_tpu.health import integrity as _integrity
from bigdl_tpu.health.integrity import CorruptCheckpointError

logger = logging.getLogger("bigdl_tpu.checkpoint")

SCHEMA_VERSION = 1           # monolithic per-tree .npz
CHUNKED_SCHEMA_VERSION = 2   # per-leaf sharded chunks + mesh descriptor
_SEP = "/"
_TREE_NAMES = ("params", "model_state", "opt_state")


def _is_remote(path: str) -> bool:
    return "://" in path


def _fs_for(path: str):
    import fsspec

    return fsspec.core.url_to_fs(path)[0]


def _open(path: str, mode: str):
    if _is_remote(path):
        import fsspec

        return fsspec.open(path, mode).open()
    return open(path, mode)


def _makedirs(path: str) -> None:
    if _is_remote(path):
        _fs_for(path).makedirs(path, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def _isdir(path: str) -> bool:
    if _is_remote(path):
        try:
            return _fs_for(path).isdir(path)
        except FileNotFoundError:
            return False
        # auth/network errors propagate: silently reporting "no checkpoint"
        # would restart training from scratch
    return os.path.isdir(path)


def _listdir(path: str):
    if _is_remote(path):
        return [e.rstrip("/").rsplit("/", 1)[-1]
                for e in _fs_for(path).ls(path, detail=False)]
    return os.listdir(path)


def _exists(path: str) -> bool:
    if _is_remote(path):
        return _fs_for(path).exists(path)
    return os.path.exists(path)


def _rmtree(path: str) -> None:
    if _is_remote(path):
        _fs_for(path).rm(path, recursive=True)
    else:
        shutil.rmtree(path, ignore_errors=True)


def _join(*parts: str) -> str:
    if _is_remote(parts[0]):
        return "/".join(p.strip("/") if i else p.rstrip("/")
                        for i, p in enumerate(parts))
    return os.path.join(*parts)


def _savez(path: str, flat) -> None:
    if _is_remote(path):
        import io

        buf = io.BytesIO()
        np.savez(buf, **flat)
        with _open(path, "wb") as fh:
            fh.write(buf.getbuffer())
    else:
        np.savez(path, **flat)


def _loadz(path: str):
    if _is_remote(path):
        import io

        with _open(path, "rb") as fh:
            return np.load(io.BytesIO(fh.read()))
    return np.load(path)


def agree_from_process_zero(value: int) -> int:
    """Make process 0's scalar decision global (collective; every process
    must call).  Used so checkpoint triggers that read locally-divergent
    state (min_loss/max_score) cannot deadlock the collective save."""
    if jax.process_count() <= 1:
        return value
    from jax.experimental import multihost_utils

    return int(multihost_utils.broadcast_one_to_all(
        np.asarray(value, np.int64)))


def _to_numpy(leaf) -> np.ndarray:
    """Host copy of a leaf.  Cross-process sharded arrays are gathered
    collectively (every process must reach this point) so each host holds
    the FULL array — the multi-host analogue of DistriOptimizer.getModel
    gathering shards back before checkpointing
    (optim/DistriOptimizer.scala:938)."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(leaf, tiled=True))
    return np.asarray(leaf)


def _flatten(tree: Any, materialize: bool = True) -> Dict[str, np.ndarray]:
    """materialize=False: participate in the collective gathers for
    cross-process shards (same traversal order) but skip the device->host
    copy of replicated leaves — non-writer processes need no host copy."""
    flat = {}
    paths = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in paths[0]:
        key = _SEP.join(_path_part(p) for p in path)
        addressable = not (isinstance(leaf, jax.Array)
                           and not leaf.is_fully_addressable)
        if not materialize and addressable:
            continue
        arr = _to_numpy(leaf)
        if materialize:
            flat[key if key else "_root"] = arr
    return flat


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _unflatten_into(template: Any, flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild arrays into the structure of `template`."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = _SEP.join(_path_part(p) for p in path) or "_root"
        if key not in flat:
            raise KeyError(f"checkpoint missing tensor '{key}'")
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint tensor '{key}' shape {arr.shape} != model {np.shape(leaf)}")
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_checkpoint(path: str, step: int, params: Any, model_state: Any = None,
                    opt_state: Any = None, driver_state: Optional[Dict] = None) -> str:
    """Write checkpoint dir `<path>/ckpt_<step>`; returns its path.

    Multi-process safe: EVERY process must call this (the flatten step runs
    collective gathers for cross-process shards), but only process 0
    touches the filesystem; a barrier at the end keeps fast processes from
    racing ahead and reading a half-written checkpoint on resume."""
    d = _join(path, f"ckpt_{step}")
    writer = jax.process_index() == 0
    flat_p = _flatten(params, materialize=writer)
    flat_ms = _flatten(model_state, materialize=writer) \
        if model_state is not None else None
    flat_os = _flatten(opt_state, materialize=writer) \
        if opt_state is not None else None
    if writer:
        _makedirs(d)
        named = {"params.npz": flat_p}
        if flat_ms is not None:
            named["model_state.npz"] = flat_ms
        if flat_os is not None:
            named["opt_state.npz"] = flat_os
        meta = {"schema_version": SCHEMA_VERSION, "step": int(step),
                "driver_state": driver_state or {},
                # per-leaf CRC32C, verified on restore (health/integrity.py)
                "integrity": {n: _integrity.tree_crcs(f)
                              for n, f in named.items()}}
        for n, f in named.items():
            _savez(_join(d, n), f)
        with _open(_join(d, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"ckpt_{step}")
    return d


def _refuse_mixed_layout(ckpt_dir: str, meta: Dict) -> None:
    """A checkpoint dir must be ONE layout.  meta.json is the commit
    marker, so a v1 meta sitting next to chunk dirs (or a v2 meta next to
    monolithic `.npz` files) means two saves interleaved into one dir or a
    botched migration — loading the half matching the meta would silently
    resurrect stale tensors from the other.  Refuse loudly; the
    `latest_checkpoint` fallback chain treats this like any other
    corruption and walks back to an intact candidate."""
    sv = meta.get("schema_version")
    has_npz = any(_exists(_join(ckpt_dir, t + ".npz")) for t in _TREE_NAMES)
    has_chunks = any(_isdir(_join(ckpt_dir, t)) for t in _TREE_NAMES)
    if sv == SCHEMA_VERSION and (has_chunks or meta.get("manifest")):
        raise CorruptCheckpointError(
            f"checkpoint {ckpt_dir} declares monolithic schema "
            f"v{SCHEMA_VERSION} but also contains chunked-layout data — "
            f"mixed-layout dirs are refused; keep each save in one layout")
    if sv == CHUNKED_SCHEMA_VERSION and has_npz:
        raise CorruptCheckpointError(
            f"checkpoint {ckpt_dir} declares chunked schema "
            f"v{CHUNKED_SCHEMA_VERSION} but also contains monolithic .npz "
            f"files — mixed-layout dirs are refused; keep each save in one "
            f"layout")


def load_checkpoint(ckpt_dir: str, params_template: Any,
                    model_state_template: Any = None,
                    opt_state_template: Any = None,
                    verify: Optional[bool] = None,
                    target_shardings: Optional[Dict[str, Dict]] = None
                    ) -> Tuple[Any, Any, Any, Dict]:
    """Returns (params, model_state, opt_state, driver_state).

    Multi-process: collective — EVERY process must call.  Only process 0
    reads the filesystem (the writer side mirrors this); the loaded values
    are broadcast to all processes, so hosts without a shared filesystem
    resume identically.

    Accepts both layouts: v1 monolithic `.npz` and v2 chunked (elastic
    reshard-on-load — a chunked checkpoint saved under mesh A restores
    onto the templates' CURRENT shardings, so N chips -> M just works).
    `target_shardings` optionally overrides placement per tree:
    `{"params": {leaf_key: Sharding}}` wins over the template leaf's own
    sharding (single-process only — the chunked writer's domain).

    `verify` gates CRC32C checks — per-leaf against meta.json's
    `integrity` block (v1) or per-chunk against the manifest (v2); None
    defers to `BIGDL_TPU_CKPT_VERIFY`, default ON.  A mismatch — or any
    unreadable file — raises CorruptCheckpointError; checkpoints from
    before the integrity schema load unverified."""
    verify = _integrity.verify_enabled(verify)
    reader = jax.process_count() <= 1 or jax.process_index() == 0
    meta = {"schema_version": SCHEMA_VERSION, "driver_state": {}}
    chunked = 0
    if reader:
        with _open(_join(ckpt_dir, "meta.json"), "r") as f:
            meta = json.load(f)
        if meta.get("schema_version") not in (SCHEMA_VERSION,
                                              CHUNKED_SCHEMA_VERSION):
            raise ValueError(
                f"unsupported checkpoint schema {meta.get('schema_version')}")
        _refuse_mixed_layout(ckpt_dir, meta)
        chunked = int(meta.get("schema_version") == CHUNKED_SCHEMA_VERSION)
    chunked = agree_from_process_zero(chunked)
    expected_crcs = meta.get("integrity") if verify else None
    manifest = meta.get("manifest") or {}

    # File presence is decided by the reader and agreed collectively, so
    # every process takes the same branch (loads+broadcast vs None).
    present = [0, 0, 0]
    if reader:
        if chunked:
            # key presence, not truthiness: an empty tree (e.g. a
            # stateless model's `{}` model_state) is saved as an empty
            # entry list and must round-trip as `{}`, not None
            present = [int(manifest.get(t) is not None) for t in _TREE_NAMES]
        else:
            present = [int(_exists(_join(ckpt_dir, t + ".npz")))
                       for t in _TREE_NAMES]
    present = [agree_from_process_zero(v) for v in present]

    def load_npz(name, template, is_present):
        if template is None:
            return None
        if not is_present:
            # A supplied template with no file is a missing/partial
            # checkpoint: zeros here would silently corrupt state like BN
            # running_var, so params are an error and aux trees load as
            # None (caller re-inits them).
            if name == "params.npz":
                raise FileNotFoundError(
                    f"checkpoint {ckpt_dir} has no {name}")
            return None
        p = _join(ckpt_dir, name)
        if reader:
            # npz is a zip: a flipped bit usually surfaces as a BadZipFile
            # or zlib error from np.load rather than wrong bytes, so ANY
            # read failure under verification is an integrity failure —
            # the fallback chain must treat both identically
            try:
                with _loadz(p) as z:
                    flat = dict(z)
            except CorruptCheckpointError:
                raise
            except Exception as e:
                if expected_crcs is not None:
                    raise CorruptCheckpointError(
                        f"checkpoint file {p} unreadable: {e}") from e
                raise
            if expected_crcs is not None and name in expected_crcs:
                _integrity.verify_flat(flat, expected_crcs[name], p)
            return _unflatten_into(template, flat)
        # non-reader: zeros placeholder in template structure, overwritten
        # by the broadcast below
        return jax.tree_util.tree_map(
            lambda l: np.zeros(np.shape(l), np.asarray(l).dtype), template)

    def load_chunked(tree_name, template, is_present):
        if template is None:
            return None
        if not is_present:
            if tree_name == "params":
                raise FileNotFoundError(
                    f"checkpoint {ckpt_dir} has no {tree_name} chunks")
            return None
        if reader:
            from bigdl_tpu.utils import ckpt_chunked as _ck

            # multi-process: assemble on host here, the broadcast tail
            # below ships it; single-process: reshard straight onto the
            # template's (current mesh's) shardings
            return _ck.load_tree(
                ckpt_dir, manifest[tree_name], template, verify,
                to_device=jax.process_count() <= 1,
                target_shardings=(target_shardings or {}).get(tree_name))
        return jax.tree_util.tree_map(
            lambda l: np.zeros(np.shape(l), np.asarray(l).dtype), template)

    if chunked:
        params = load_chunked("params", params_template, present[0])
        model_state = load_chunked("model_state", model_state_template,
                                   present[1])
        opt_state = load_chunked("opt_state", opt_state_template, present[2])
    else:
        params = load_npz("params.npz", params_template, present[0])
        model_state = load_npz("model_state.npz", model_state_template,
                               present[1])
        opt_state = load_npz("opt_state.npz", opt_state_template, present[2])
    if reader and verify and (chunked or expected_crcs is not None):
        _integrity.count("verified")
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        trees = [t for t in (params, model_state, opt_state) if t is not None]
        if trees:
            synced = multihost_utils.broadcast_one_to_all(trees)
            it = iter(synced)
            params = next(it) if params is not None else None
            model_state = next(it) if model_state is not None else None
            opt_state = next(it) if opt_state is not None else None
        # driver_state json: broadcast the byte length first, then a buffer
        # sized to it — no fixed-size truncation
        raw = json.dumps(meta.get("driver_state", {})).encode()
        nbytes = agree_from_process_zero(len(raw))
        buf = np.zeros(nbytes, np.uint8)
        if reader and nbytes:
            buf[:] = np.frombuffer(raw, np.uint8)
        if nbytes:
            buf = np.asarray(multihost_utils.broadcast_one_to_all(buf))
        text = buf.tobytes().decode()
        meta["driver_state"] = json.loads(text) if text else {}
    return params, model_state, opt_state, meta.get("driver_state", {})


def load_params(ckpt_dir: str, params_template: Any,
                model_state_template: Any = None) -> Tuple[Any, Any]:
    """Serving-side load: (params, model_state) only — no optimizer slots,
    no driver state.  Used by the model registry's checkpoint hot-swap
    (`bigdl_tpu/serving/registry.py`): the template comes from the version
    currently serving, so a shape-drifted checkpoint fails HERE (with the
    offending tensor named) instead of inside a request's forward."""
    params, model_state, _, _ = load_checkpoint(
        ckpt_dir, params_template, model_state_template)
    return params, model_state


def verify_checkpoint(ckpt_dir: str) -> Dict:
    """Full integrity pass over one committed checkpoint dir: every
    payload is read back and its CRC32C compared — per-leaf against the
    `integrity` block (v1 monolithic) or per-chunk against the manifest
    (v2 chunked, which also checks each leaf's grid covers its shape).
    Returns the parsed meta on success; raises CorruptCheckpointError on
    any mismatch, unreadable file, or mixed-layout dir.  A pre-integrity
    checkpoint (no block) passes vacuously — old runs stay restorable.

    Local-only (no collective): callers are process 0's restore/registry
    paths, which already own the filesystem decision."""
    try:
        with _open(_join(ckpt_dir, "meta.json"), "r") as f:
            meta = json.load(f)
    except Exception as e:
        raise CorruptCheckpointError(
            f"checkpoint {ckpt_dir} meta.json unreadable: {e}") from e
    _refuse_mixed_layout(ckpt_dir, meta)
    if meta.get("schema_version") == CHUNKED_SCHEMA_VERSION:
        from bigdl_tpu.utils import ckpt_chunked as _ck

        _ck.verify_manifest(ckpt_dir, meta.get("manifest"))
        return meta
    for name, expected in (meta.get("integrity") or {}).items():
        p = _join(ckpt_dir, name)
        try:
            with _loadz(p) as z:
                flat = dict(z)
        except Exception as e:
            raise CorruptCheckpointError(
                f"checkpoint file {p} unreadable: {e}") from e
        _integrity.verify_flat(flat, expected, p)
    return meta


def checkpoint_health(ckpt_dir: str) -> Dict:
    """The watchdog verdict stamped into a checkpoint's driver_state
    (`{"verdict": "healthy"|"diverged", "bad_steps": [...]}`).  Missing
    stamp (pre-health checkpoints, or watchdog off) reads as healthy."""
    try:
        with _open(_join(ckpt_dir, "meta.json"), "r") as f:
            meta = json.load(f)
    except Exception as e:
        raise CorruptCheckpointError(
            f"checkpoint {ckpt_dir} meta.json unreadable: {e}") from e
    return (meta.get("driver_state") or {}).get("health") \
        or {"verdict": "healthy", "bad_steps": []}


def gc_partial_checkpoints(path: str) -> List[str]:
    """Reclaim interrupted checkpoint debris under `path`: `ckpt_<N>` dirs
    missing their meta.json commit marker (a save killed mid-write) and
    `tmp.<N>` staging dirs the async writer never got to rename.  Applies
    to both layouts — a chunked dir with committed chunk files but no
    meta.json is exactly as dead as a lone `.npz` and is reclaimed whole,
    never half-loaded.  Returns the removed paths.

    Call this only on RESUME paths (no writer can be mid-save then) — a
    live writer's staging dir looks exactly like debris."""
    removed: List[str] = []
    if not _isdir(path):
        return removed
    for name in _listdir(path):
        m = re.fullmatch(r"ckpt_(\d+)", name)
        partial = (m is not None
                   and not _exists(_join(path, name, "meta.json"))) \
            or re.fullmatch(r"tmp\.(\d+)", name) is not None
        full = _join(path, name)
        if partial and _isdir(full):
            _rmtree(full)
            removed.append(full)
    if removed:
        logger.warning(
            "garbage-collected %d interrupted partial checkpoint dir(s) "
            "under %s: %s — resuming from the newest COMMITTED checkpoint",
            len(removed), path, sorted(os.path.basename(r) for r in removed))
    return removed


def latest_checkpoint(path: str, gc_partial: bool = False, *,
                      verify: Optional[bool] = None,
                      require_healthy: bool = False) -> Optional[str]:
    """Newest COMMITTED ckpt dir under `path`, agreed across processes
    (collective when multi-process): only process 0's filesystem answer
    counts — checkpoints are written by process 0, so on hosts without a
    shared filesystem the others see nothing yet must resume the SAME step.

    `gc_partial=True` (resume paths only) deletes interrupted partial
    checkpoint dirs with a warning instead of silently skipping them.

    Fallback chain: with `verify=True` (or None + `BIGDL_TPU_CKPT_VERIFY`
    on, when either gate is requested) candidates are walked NEWEST FIRST
    and any that fails its CRC32C pass is skipped with a warning + counter
    instead of crashing the restore; `require_healthy=True` additionally
    skips checkpoints whose meta carries a diverged watchdog verdict (the
    rollback path — "last good" means last stamped healthy).  Plain calls
    (both gates off) keep the original single-stat fast path."""
    check_crc = verify is True or (
        require_healthy and _integrity.verify_enabled(verify))
    best_step = -1
    if jax.process_count() <= 1 or jax.process_index() == 0:
        if gc_partial:
            gc_partial_checkpoints(path)
        steps: List[int] = []
        if _isdir(path):
            for name in _listdir(path):
                m = re.fullmatch(r"ckpt_(\d+)", name)
                # meta.json is written LAST: a dir without it is an
                # interrupted save and must not block resume from the
                # previous intact checkpoint
                if m and _exists(_join(path, name, "meta.json")):
                    steps.append(int(m.group(1)))
        if not (check_crc or require_healthy):
            best_step = max(steps, default=-1)
        else:
            for s in sorted(steps, reverse=True):
                d = _join(path, f"ckpt_{s}")
                try:
                    if require_healthy:
                        h = checkpoint_health(d)
                        if h.get("verdict") == "diverged":
                            _integrity.count("unhealthy_skipped")
                            logger.warning(
                                "restore fallback: skipping %s — stamped "
                                "diverged (bad steps %s)", d,
                                h.get("bad_steps"))
                            continue
                    if check_crc:
                        verify_checkpoint(d)
                except CorruptCheckpointError as e:
                    _integrity.count("corrupt_skipped")
                    logger.warning(
                        "restore fallback: skipping corrupt checkpoint "
                        "%s: %s", d, e)
                    continue
                best_step = s
                break
    best_step = agree_from_process_zero(best_step)
    if best_step < 0:
        return None
    return _join(path, f"ckpt_{best_step}")
