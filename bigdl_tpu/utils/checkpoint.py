"""Checkpoint save/load.

Reference: optim/AbstractOptimizer.scala:202-221 (trigger-driven
`model.<iter>` + `optimMethod-<name>.<iter>` files in a timestamped subdir)
and utils/File.scala (local/HDFS/S3).  Resume restores mid-epoch because
counters live in optimizer state (optim/DistriOptimizer.scala:127-137).

Format: a directory per checkpoint containing a schema-versioned
`meta.json` plus one `.npz` per pytree (params / model_state / opt_state).
Pytrees are flattened to path-keyed arrays ("0/weight", "cell/w_ih"), so
the format is stable across process restarts and inspectable with numpy —
the same goals as the reference's protobuf ModuleSerializer (§2.6), without
inventing a binary schema.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

SCHEMA_VERSION = 1
_SEP = "/"


def _flatten(tree: Any) -> Dict[str, np.ndarray]:
    flat = {}
    paths = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in paths[0]:
        key = _SEP.join(_path_part(p) for p in path)
        flat[key if key else "_root"] = np.asarray(leaf)
    return flat


def _path_part(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    return str(p)


def _unflatten_into(template: Any, flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild arrays into the structure of `template`."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths:
        key = _SEP.join(_path_part(p) for p in path) or "_root"
        if key not in flat:
            raise KeyError(f"checkpoint missing tensor '{key}'")
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint tensor '{key}' shape {arr.shape} != model {np.shape(leaf)}")
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_checkpoint(path: str, step: int, params: Any, model_state: Any = None,
                    opt_state: Any = None, driver_state: Optional[Dict] = None) -> str:
    """Write checkpoint dir `<path>/ckpt_<step>`; returns its path."""
    d = os.path.join(path, f"ckpt_{step}")
    os.makedirs(d, exist_ok=True)
    meta = {"schema_version": SCHEMA_VERSION, "step": int(step),
            "driver_state": driver_state or {}}
    np.savez(os.path.join(d, "params.npz"), **_flatten(params))
    if model_state is not None:
        np.savez(os.path.join(d, "model_state.npz"), **_flatten(model_state))
    if opt_state is not None:
        np.savez(os.path.join(d, "opt_state.npz"), **_flatten(opt_state))
    with open(os.path.join(d, "meta.json"), "w") as f:
        json.dump(meta, f, indent=2)
    return d


def load_checkpoint(ckpt_dir: str, params_template: Any,
                    model_state_template: Any = None,
                    opt_state_template: Any = None) -> Tuple[Any, Any, Any, Dict]:
    """Returns (params, model_state, opt_state, driver_state)."""
    with open(os.path.join(ckpt_dir, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("schema_version") != SCHEMA_VERSION:
        raise ValueError(f"unsupported checkpoint schema {meta.get('schema_version')}")

    def load_npz(name, template):
        p = os.path.join(ckpt_dir, name)
        if template is None or not os.path.exists(p):
            return None
        with np.load(p) as z:
            return _unflatten_into(template, dict(z))

    params = load_npz("params.npz", params_template)
    model_state = load_npz("model_state.npz", model_state_template)
    opt_state = load_npz("opt_state.npz", opt_state_template)
    return params, model_state, opt_state, meta.get("driver_state", {})


def latest_checkpoint(path: str) -> Optional[str]:
    if not os.path.isdir(path):
        return None
    best, best_step = None, -1
    for name in os.listdir(path):
        m = re.fullmatch(r"ckpt_(\d+)", name)
        if m and int(m.group(1)) > best_step:
            best, best_step = os.path.join(path, name), int(m.group(1))
    return best
